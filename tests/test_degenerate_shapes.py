"""Degenerate-shape robustness: zero-length sequences in a batch, B=1,
T=1, minimal beams — the edges real data pipelines produce (last ragged
batch, empty documents) and real frameworks break on.  Everything must
stay finite and exception-free (ref: the reference's empty-sequence
handling in SequenceToBatch and Argument::checkSubset)."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer


@pytest.fixture(scope="module")
def mixed_model():
    def conf():
        from paddle_tpu.dsl import (
            AdamOptimizer, ParamAttr, SoftmaxActivation, classification_cost,
            concat_layer, data_layer, embedding_layer, fc_layer, last_seq,
            layer_norm_layer, multi_head_attention_layer, pooling_layer,
            settings, simple_gru,
        )
        from paddle_tpu.dsl.poolings import AvgPooling
        settings(batch_size=4, learning_rate=1e-3,
                 learning_method=AdamOptimizer())
        w = data_layer(name="w", size=16)
        emb = embedding_layer(input=w, size=8,
                              param_attr=ParamAttr(initial_std=0.1))
        g = simple_gru(input=emb, size=8)
        att = multi_head_attention_layer(layer_norm_layer(input=emb),
                                         size=8, num_heads=2, causal=True)
        feats = concat_layer(input=[
            pooling_layer(input=g, pooling_type=AvgPooling()),
            last_seq(input=att)])
        out = fc_layer(input=feats, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))

    return Trainer(parse_config_callable(conf), seed=0)


@pytest.mark.parametrize("name,B,T,lens", [
    ("zero_len_row", 4, 5, [5, 0, 3, 1]),
    ("all_zero_len", 4, 5, [0, 0, 0, 0]),
    ("B1_T1", 1, 1, [1]),
    ("T1_with_zero", 4, 1, [1, 1, 0, 1]),
])
def test_train_survives(mixed_model, name, B, T, lens):
    rng = np.random.default_rng(0)
    b = {"w": Argument(ids=rng.integers(0, 16, (B, T)).astype(np.int32),
                       lengths=np.asarray(lens, np.int32)),
         "y": Argument(ids=rng.integers(0, 3, B).astype(np.int32))}
    loss = float(mixed_model.train_one_batch(b))
    assert np.isfinite(loss), (name, loss)
    # the loss of a poisoned batch can still be finite — the NaNs surface
    # in the UPDATED params; check them per case so a failure is
    # attributed to the right shape
    for k, v in mixed_model.params.items():
        assert np.isfinite(np.asarray(v)).all(), (name, k)


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.config.parser import parse_config
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=32,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=0)


@pytest.mark.parametrize("B,P,lens,max_new", [
    (1, 1, [1], 1),           # singleton everything
    (3, 4, [1, 4, 2], 5),     # ragged prompts incl. length 1
    (2, 3, [3, 2], 0),        # nothing to generate
])
def test_decode_cache_parity_on_edges(lm, B, P, lens, max_new):
    from paddle_tpu.graph.lm_decode import lm_generate
    prompt = np.ones((B, P), np.int32)
    lens = np.asarray(lens, np.int32)
    t1, l1 = lm_generate(lm.executor, lm.params, prompt,
                         prompt_lengths=lens, max_new=max_new,
                         use_cache=True)
    t2, l2 = lm_generate(lm.executor, lm.params, prompt,
                         prompt_lengths=lens, max_new=max_new)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_beam_minimal(lm):
    from paddle_tpu.graph.lm_decode import lm_beam_generate
    toks, lens, scores = lm_beam_generate(
        lm.executor, lm.params, np.ones((1, 1), np.int32), max_new=1,
        beam_size=1)
    assert np.asarray(toks).shape == (1, 1, 2)
    assert np.isfinite(np.asarray(scores)).all()


def test_nested_ops_with_empty_subsequences():
    """Numpy oracle over the VALID region — finiteness alone can't catch
    a pool that reads padding or picks the wrong token."""
    import jax.numpy as jnp

    from paddle_tpu.ops import sequence as seqops
    xn = np.random.default_rng(0).normal(size=(2, 3, 4, 5)).astype(np.float32)
    x = jnp.asarray(xn)
    lens = jnp.asarray([0, 2], jnp.int32)          # row 0: NO sub-seqs
    subs = jnp.asarray([[0, 0, 0], [0, 3, 0]], jnp.int32)  # empty first sub
    # row 1's only valid tokens: sub 1, t in [0, 3)
    valid1 = xn[1, 1, :3]
    np.testing.assert_allclose(
        np.asarray(seqops.nested_pool_max(x, lens, subs))[1],
        valid1.max(0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(seqops.nested_pool_last(x, lens, subs))[1],
        valid1[-1], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(seqops.nested_pool_first(x, lens, subs))[1],
        valid1[0], rtol=1e-6)
    for fn in (seqops.nested_pool_max, seqops.nested_pool_last,
               seqops.nested_pool_first):
        assert np.isfinite(np.asarray(fn(x, lens, subs))).all(), fn.__name__
    v = np.asarray(seqops.nested_pool_max_per_sub(x, lens, subs))
    assert np.isfinite(v).all()
    assert float(np.abs(v[0]).max()) == 0.0        # fully-invalid row -> 0
    np.testing.assert_allclose(v[1, 1], valid1.max(0), rtol=1e-6)
    assert float(np.abs(v[1, 0]).max()) == 0.0     # empty sub -> 0
