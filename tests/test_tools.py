"""Tools suite tests: merge-model round trip, dot diagram, cost parsing,
image augmentation, torch weight import (torch CPU is available in-image)."""

import os
import subprocess
import sys

import numpy as np

from paddle_tpu.config.parser import parse_config_callable


def _config():
    from paddle_tpu import dsl

    def conf():
        dsl.settings(batch_size=8, learning_rate=0.1)
        x = dsl.data_layer(name="x", size=6)
        h = dsl.fc_layer(input=x, size=5, act=dsl.TanhActivation(), name="hidden")
        out = dsl.fc_layer(input=h, size=3, act=dsl.SoftmaxActivation(), name="out")
        dsl.classification_cost(input=out, label=dsl.data_layer(name="y", size=3))
    return parse_config_callable(conf)


def test_hlo_gather_detector_anchors_to_shapes():
    """ADVICE r5 regression for tools/hlo_sparse_check.py:113: the table
    all-gather verdict must anchor to parsed operand/result shapes and
    the gathered dimension — a row count appearing elsewhere in the line
    (replica_groups, channel ids, a feature-dim activation gather) must
    not trip the exit-2 verdict; real table materializations (direct or
    grouped [rows/n, n, D] form) must."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.hlo_sparse_check import gather_spans_table

    tables = [((3952, 64), 0), ((6040, 64), 0), ((512, 256), 0)]
    # feature-dim activation gather whose WIDTH equals a table row count
    act = ("%ag = f32[64,256]{0,1} all-gather(f32[64,32]{0,1} %c), "
           "channel_id=6, replica_groups=[1,8]<=[8], dimensions={1}")
    assert not gather_spans_table(act, [((256, 256), 0)] + tables)
    # row count only inside replica_groups / channel id
    noise = ("%ag2 = f32[64,10]{1,0} all-gather(f32[8,10]{1,0} %x), "
             "channel_id=3952, replica_groups=[1,3952]<=[3952], "
             "dimensions={0}")
    assert not gather_spans_table(noise, tables)
    # coincidentally table-shaped result gathered along the UNSHARDED dim
    other_dim = ("%ag3 = f32[512,256]{1,0} all-gather(f32[512,32]{1,0} %x), "
                 "replica_groups=[1,8]<=[8], dimensions={1}")
    assert not gather_spans_table(other_dim, tables)
    # genuine: the table reassembled directly...
    direct = ("%ag4 = f32[3952,64]{1,0} all-gather(f32[494,64]{1,0} %s), "
              "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}")
    assert gather_spans_table(direct, tables)
    # ...or in GSPMD's grouped [rows/n, n, D] lowering (bitcast follows)
    grouped = ("%ag5 = f32[64,8,256]{1,0,2} all-gather(f32[64,1,256]"
               "{1,0,2} %p), channel_id=9, replica_groups=[1,8]<=[8], "
               "dimensions={1}")
    assert gather_spans_table(grouped, tables)


def test_hlo_shard_check_decode_has_no_pool_allgather():
    """tools/hlo_shard_check.py on the real engine over a 2-shard host
    mesh: the tensor-parallel decode, mixed, spec-verify AND multi-step
    scan programs must contain zero all-gathers of the KV pools or
    attention projections, and exactly the per-layer post-attention
    all-reduce — for the scan that count covers ONE body (lax.scan
    lowers to a while loop; the body appears once in the HLO), the
    acceptance evidence for the sharded-decode HBM/FLOPs split
    (docs/serving.md)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    from tools.hlo_shard_check import run_check

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs >= 2 devices (conftest provides 8 host devices)")
    out = run_check(model=2, save="")
    assert out["ok"], out["verdict"]
    for step in ("decode", "mixed", "spec", "scan"):
        rec = out["steps"][step]
        assert rec["table_all_gathers"] == [], (step, rec)
        assert rec["n_all_gathers"] == 0, \
            (step, "unexpected all-gather — sharded decode must keep ALL "
                   "activations head-local until the out-projection reduce")
        assert rec["n_all_reduces"] == rec["expected_all_reduces"], rec


def test_check_metrics_names_lint(tmp_path):
    """ISSUE 5 tier-1 lint: obs.metrics.CATALOG and docs/observability.md
    must agree both ways — plus the drift detectors actually detect."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.check_metrics_names import SECTION, check, doc_metric_names, main

    assert main() == 0, "CATALOG vs docs/observability.md drifted"

    # drift detection: a doc with one bogus row and none of the real names
    fake = tmp_path / "observability.md"
    fake.write_text(f"# x\n\n{SECTION}\n\n| Metric | Kind |\n|---|---|\n"
                    f"| `made_up_metric` | gauge |\n")
    undocumented, stale = check(str(fake))
    assert stale == {"made_up_metric"}
    assert "serving_queue_depth" in undocumented

    # a doc without the anchor section is a loud error, not a silent pass
    nosec = tmp_path / "empty.md"
    nosec.write_text("# nothing here\n")
    import pytest

    with pytest.raises(ValueError, match="Metric reference"):
        doc_metric_names(str(nosec))


def test_check_metrics_names_catches_dead_catalog_rows(tmp_path):
    """ISSUE 6: the third lint direction — every CATALOG name must be
    referenced somewhere under paddle_tpu/ OUTSIDE the CATALOG block
    itself, so a dead row (declared, documented, never emitted) cannot
    linger.  The current tree is clean; a planted bogus name is caught;
    the CATALOG assignment cannot vouch for itself."""
    from tools.check_metrics_names import _source_without_catalog, \
        unreferenced_names

    assert unreferenced_names() == set(), \
        "dead CATALOG rows (or the reference scan broke)"
    assert unreferenced_names({"totally_made_up_metric"}) == \
        {"totally_made_up_metric"}
    # a real name referenced ONLY by its own catalog row reads as dead:
    # the blanked source must not contain the rows the full source has
    metrics_py = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "paddle_tpu", "obs", "metrics.py")
    blanked = _source_without_catalog(metrics_py)
    with open(metrics_py) as f:
        full = f.read()
    assert "jit_compiles_total" in full
    assert "jit_compiles_total" not in blanked
    assert "CATALOG" in blanked                # only the assignment went


def test_check_metrics_names_event_table_lint(tmp_path):
    """ISSUE 13 satellite: the FOURTH lint direction — every flight-event
    kind emitted under paddle_tpu/ has a row in the doc's flight-event
    table and vice versa, with non-literal kinds themselves flagged (a
    computed kind could ship undocumented)."""
    from tools.check_metrics_names import (EVENT_SECTION, check_events,
                                           doc_event_kinds,
                                           emitted_event_kinds)

    # the current tree is clean in both directions
    undoc, stale, problems = check_events()
    assert undoc == set() and stale == set() and problems == []
    kinds, _ = emitted_event_kinds()
    assert {"queued", "route", "retry", "shed", "pump_death",
            "fleet_unhealthy", "replica_drain"} <= kinds

    # drift detection: a doc with one bogus row and none of the real ones
    fake = tmp_path / "observability.md"
    fake.write_text(f"# x\n\n{EVENT_SECTION}\n\n| Kind | Meaning |\n"
                    f"|---|---|\n| `made_up_event` | ? |\n")
    undoc, stale, _ = check_events(str(fake))
    assert stale == {"made_up_event"}
    assert "queued" in undoc

    # a computed kind is a lint error, not a silent gap
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "mod.py").write_text(
        'flight.record("documented_kind", a=1)\n'
        'self.flight.record("undocumented_kind")\n'
        'flight.record("prefix_" + op)\n'
        'other.record("not_a_flight_event")\n')
    fake.write_text(f"# x\n\n{EVENT_SECTION}\n\n| Kind | Meaning |\n"
                    f"|---|---|\n| `documented_kind` | ok |\n")
    undoc, stale, problems = check_events(str(fake), str(root))
    assert undoc == {"undocumented_kind"}
    assert stale == set()
    assert len(problems) == 1 and "not a string literal" in problems[0]

    # a doc without the anchor section is a loud error
    nosec = tmp_path / "empty.md"
    nosec.write_text("# nothing\n")
    import pytest

    with pytest.raises(ValueError, match="Flight event reference"):
        doc_event_kinds(str(nosec))


def test_trace_dump_merge_stitches_processes_with_offsets(tmp_path,
                                                         capsys):
    """ISSUE 13: --merge stitches span FILES (meta identity line + clock
    offset applied) into one Chrome trace with a process group per file,
    and load_spans still reads a meta-bearing file transparently."""
    import json as _json

    from tools.trace_dump import load_spans, load_trace_file, main

    router = tmp_path / "router.jsonl"
    with open(router, "w") as f:
        f.write(_json.dumps({"meta": {"process": {
            "role": "router", "pid": 1, "addr": "h:1"},
            "offset_s": 0.0}}) + "\n")
        f.write(_json.dumps({"seq": 0, "name": "ingress",
                             "track": "req:t", "ts": 50.0, "dur": 2.0,
                             "attrs": {"trace_id": "aa"}}) + "\n")
    replica = tmp_path / "replica.jsonl"
    with open(replica, "w") as f:
        f.write(_json.dumps({"meta": {"process": {
            "role": "replica", "pid": 2, "addr": "h:2"},
            "offset_s": 45.0}}) + "\n")           # epoch 45s behind
        f.write(_json.dumps({"seq": 0, "name": "decode",
                             "track": "req:t", "ts": 5.5, "dur": 1.0,
                             "attrs": {"trace_id": "aa"}}) + "\n")

    # meta line is transparent to the single-file loaders
    assert [s["name"] for s in load_spans(str(router))] == ["ingress"]
    meta, spans = load_trace_file(str(replica))
    assert meta["process"]["role"] == "replica" and len(spans) == 1

    out = tmp_path / "fleet.json"
    assert main([str(router), str(replica), "--merge",
                 "-o", str(out)]) == 0
    assert "2 processes" in capsys.readouterr().out
    merged = _json.loads(out.read_text())
    evs = merged["traceEvents"]
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert len(procs) == 2
    ing = next(e for e in evs if e["name"] == "ingress")
    dec = next(e for e in evs if e["name"] == "decode")
    assert ing["pid"] != dec["pid"]
    # offset applied then globally rebased: decode starts 0.5s into
    # the ingress span (50.5 vs 50.0 in the aligned timebase)
    assert ing["ts"] == 0.0
    assert dec["ts"] == 0.5e6
    assert dec["args"]["trace_id"] == ing["args"]["trace_id"]

    # several files WITHOUT --merge is an explicit error, not a guess
    assert main([str(router), str(replica)]) == 2
    # single-file path unchanged (no --merge needed)
    assert main([str(router), "-o", str(tmp_path / "one.json")]) == 0


def test_trace_dump_summary_lanes_and_compile_breakdown(tmp_path, capsys):
    """ISSUE 6: --summary must make a recompile storm visible from the
    trace file alone — per-lane counts plus a compile-lane table with
    signatures × compile-time and STORMS markers."""
    import json

    from tools.trace_dump import compile_breakdown, load_spans, main

    spans = [
        {"seq": 0, "name": "queued", "track": "req:a", "ts": 0.0,
         "dur": 0.1},
        {"seq": 1, "name": "decode", "track": "req:a", "ts": 0.1,
         "dur": 0.4},
        {"seq": 2, "name": "queued", "track": "req:b", "ts": 0.0,
         "dur": 0.2},
        {"seq": 3, "name": "decode_step", "track": "engine", "ts": 0.1,
         "dur": 0.2},
        {"seq": 4, "name": "serving.prefill", "track": "compile",
         "ts": 0.0, "dur": 0.8, "attrs": {"sig": "int32[1,8]"}},
        {"seq": 5, "name": "serving.prefill", "track": "compile",
         "ts": 1.0, "dur": 0.6, "attrs": {"sig": "int32[1,16]"}},
        {"seq": 6, "name": "recompile_storm", "track": "compile",
         "ts": 1.5, "instant": True,
         "attrs": {"site": "serving.prefill", "signatures": 6}},
    ]
    src = tmp_path / "spans.jsonl"
    src.write_text("".join(json.dumps(s) + "\n" for s in spans))

    assert main([str(src), "--summary"]) == 0
    out = capsys.readouterr().out
    # per-lane counts: request lanes collapse to one req:* row
    assert "req:*" in out and "compile" in out and "engine" in out
    assert "7 spans on 3 lanes" in out
    # the compile breakdown: 2 compiles, 2 sigs, 1400ms, storm marker
    assert "compile lane (2 compiles):" in out
    lines = out.splitlines()
    start = next(i for i, l in enumerate(lines)
                 if l.startswith("compile lane"))
    line = next(l for l in lines[start:]
                if l.strip().startswith("serving.prefill"))
    assert "2" in line and "1400.00" in line and "STORMS=1" in line

    # a trace with no compile lane gets no breakdown (older traces)
    assert compile_breakdown(load_spans(str(src))[:4]) == ""
    plain = tmp_path / "plain.jsonl"
    plain.write_text("".join(json.dumps(s) + "\n" for s in spans[:4]))
    assert main([str(plain), "--summary"]) == 0
    assert "compile lane" not in capsys.readouterr().out


def test_merge_model_roundtrip(tmp_path):
    import jax

    from paddle_tpu.graph.builder import GraphExecutor
    from paddle_tpu.tools.merge_model import load_bundle, merge_model
    from paddle_tpu.trainer import checkpoint as ckpt

    cfg = _config()
    ex = GraphExecutor(cfg.model_config)
    params = {k: np.asarray(v) for k, v in
              ex.init_params(jax.random.PRNGKey(0)).items()}
    d = ckpt.save_checkpoint(str(tmp_path / "ck"), 0, params,
                             config_json=cfg.to_json())
    bundle = str(tmp_path / "model.bundle")
    merge_model(d, bundle)
    cfg2, params2 = load_bundle(bundle)
    assert cfg2.model_config.layer("hidden").size == 5
    assert set(params2) == set(params)
    for k in params:
        np.testing.assert_array_equal(params2[k], params[k])


def test_model_diagram():
    from paddle_tpu.tools.make_model_diagram import model_to_dot

    cfg = _config()
    dot = model_to_dot(cfg.model_config)
    assert dot.startswith("digraph")
    assert '"hidden"' in dot and '"out"' in dot
    assert '"hidden" -> "out"' in dot


def test_plotcurve_parsing():
    from paddle_tpu.tools.plotcurve import ascii_plot, parse_costs

    lines = [
        "I 0701 paddle_tpu.trainer] pass 0 batch 10: cost 1.5 err 0.4",
        "noise line",
        "I 0701 paddle_tpu.trainer] pass 0 batch 20: cost 0.75 err 0.2",
    ]
    ys = parse_costs(lines)
    assert ys == [1.5, 0.75]
    art = ascii_plot(ys)
    assert "final 0.7500" in art


def test_image_augmentation():
    from paddle_tpu.tools import image_util as iu

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
    chw = iu.to_chw(img)
    assert chw.shape == (3, 32, 32)
    c = iu.center_crop(chw, 28)
    assert c.shape == (3, 28, 28)
    np.testing.assert_array_equal(c, chw[:, 2:30, 2:30])
    r = iu.random_crop(chw, 28, rng)
    assert r.shape == (3, 28, 28)
    f = iu.horizontal_flip(c)
    np.testing.assert_array_equal(f[:, :, 0], c[:, :, -1])
    a = iu.augment(chw, 28, rng, train=True, mean=127.5, scale=1 / 127.5)
    assert a.shape == (3, 28, 28) and a.dtype == np.float32
    assert np.abs(a).max() <= 1.0


def test_torch2paddle_convert():
    import torch

    from paddle_tpu.tools.torch2paddle import convert_state_dict

    cfg = _config()
    # torch Linear mirror of the model: 6->5->3 with biases
    net = torch.nn.Sequential(
        torch.nn.Linear(6, 5), torch.nn.Tanh(),
        torch.nn.Linear(5, 3))
    params = convert_state_dict(net.state_dict(), cfg.model_config)
    # every model parameter matched, linear weights transposed
    w_hidden = [v for k, v in params.items() if v.shape == (6, 5)]
    assert w_hidden, {k: v.shape for k, v in params.items()}
    np.testing.assert_allclose(
        w_hidden[0], net[0].weight.detach().numpy().T, rtol=1e-6)


def test_dump_config_cli(tmp_path):
    conf_file = tmp_path / "conf.py"
    conf_file.write_text(
        "from paddle_tpu.dsl import *\n"
        "settings(batch_size=4, learning_rate=0.1)\n"
        "x = data_layer(name='x', size=4)\n"
        "out = fc_layer(input=x, size=2, act=SoftmaxActivation(), name='out')\n"
        "classification_cost(input=out, label=data_layer(name='y', size=2))\n")
    env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.dump_config", str(conf_file)],
        capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    assert '"out"' in r.stdout


def test_bundle_into_gradient_machine(tmp_path):
    import jax

    from paddle_tpu import api
    from paddle_tpu.tools.merge_model import merge_model
    from paddle_tpu.trainer import checkpoint as ckpt

    cfg = _config()
    m = api.GradientMachine.createFromConfigProto(cfg.model_config, seed=5)
    d = ckpt.save_checkpoint(str(tmp_path / "ck"), 0,
                             {k: np.asarray(v) for k, v in m.params.items()},
                             config_json=cfg.to_json())
    bundle = str(tmp_path / "model.bundle")
    merge_model(d, bundle)
    m2 = api.GradientMachine.createFromFile(bundle)
    for k in m.params:
        np.testing.assert_array_equal(np.asarray(m.params[k]),
                                      np.asarray(m2.params[k]))
    # deployable: forward works
    batch = {"x": __import__("paddle_tpu.parameter.argument",
                             fromlist=["Argument"]).Argument(
        value=np.zeros((2, 6), np.float32))}
    outs = m2.forwardTest(batch)
    assert "out" in outs


def test_embedding_zoo_roundtrip(tmp_path):
    """extract/to_text/from_text (ref: demo/model_zoo/embedding/
    extract_para.py, paraconvert.py)."""
    import numpy as np

    from paddle_tpu.tools import embedding_zoo as ez

    rng = np.random.default_rng(0)
    pre = rng.normal(size=(6, 4)).astype(np.float32)
    pre_words = ["<unk>", "the", "cat", "sat", "mat", "dog"]
    usr_words = ["cat", "unicorn", "dog"]

    out = ez.extract_rows(pre, pre_words, usr_words)
    np.testing.assert_array_equal(out[0], pre[2])     # cat
    np.testing.assert_array_equal(out[1], pre[0])     # OOV -> <unk> row
    np.testing.assert_array_equal(out[2], pre[5])     # dog

    # without an <unk> row, OOV falls back to the mean vector
    out2 = ez.extract_rows(pre[1:], pre_words[1:], ["unicorn"])
    np.testing.assert_allclose(out2[0], pre[1:].mean(0), rtol=1e-6)

    txt = tmp_path / "emb.txt"
    ez.to_text(out, usr_words, str(txt))
    back, words = ez.from_text(str(txt))
    assert words == usr_words
    np.testing.assert_allclose(back, out, rtol=1e-5, atol=1e-6)

    # CLI end to end
    pre_npy = tmp_path / "pre.npy"
    np.save(pre_npy, pre)
    (tmp_path / "pre.dict").write_text("\n".join(pre_words) + "\n")
    (tmp_path / "usr.dict").write_text("\n".join(usr_words) + "\n")
    usr_npy = tmp_path / "usr.npy"
    ez.main(["extract", "--pre_model", str(pre_npy),
             "--pre_dict", str(tmp_path / "pre.dict"),
             "--usr_model", str(usr_npy),
             "--usr_dict", str(tmp_path / "usr.dict")])
    np.testing.assert_array_equal(np.load(usr_npy), out)


def test_cli_multiplexer_dispatch(tmp_path, capsys):
    """`python -m paddle_tpu <cmd>` dispatches like the reference's `paddle`
    shell wrapper (ref: paddle/scripts/submit_local.sh.in:109-134)."""
    import paddle_tpu.__main__ as cli

    assert cli.main(["--help"]) == 0
    assert "train" in capsys.readouterr().out
    assert cli.main(["version"]) == 0
    assert "paddle_tpu" in capsys.readouterr().out
    assert cli.main(["no_such_cmd"]) == 2

    # a real dispatch: dump_config through the multiplexer
    cfg = tmp_path / "c.py"
    cfg.write_text(
        "from paddle_tpu.dsl import *\n"
        "settings(batch_size=4, learning_rate=0.1)\n"
        "x = data_layer(name='x', size=4)\n"
        "o = fc_layer(input=x, size=2, act=SoftmaxActivation())\n"
        "classification_cost(input=o, label=data_layer(name='y', size=2))\n")
    assert cli.main(["dump_config", str(cfg)]) == 0
    out = capsys.readouterr().out
    import json
    assert json.loads(out)["model_config"]["layers"]
