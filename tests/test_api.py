"""Programmatic API tests (mirror of the reference's swig API tests —
ref: paddle/api/test/{testMatrix,testVector,testArguments,
testGradientMachine,testTrain,testTrainer}.py)."""

import numpy as np

from paddle_tpu import api
from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.data.provider import dense_vector, integer_value


def _config():
    from paddle_tpu import dsl

    def conf():
        dsl.settings(batch_size=16, learning_rate=0.3,
                     learning_method=dsl.MomentumOptimizer(momentum=0.9))
        x = dsl.data_layer(name="x", size=8)
        h = dsl.fc_layer(input=x, size=16, act=dsl.TanhActivation())
        out = dsl.fc_layer(input=h, size=2, act=dsl.SoftmaxActivation())
        dsl.classification_cost(input=out, label=dsl.data_layer(name="y", size=2))
    return parse_config_callable(conf)


def _batches(n, bs=16, seed=0):
    rng = np.random.default_rng(seed)
    conv = api.DataProviderConverter(
        [dense_vector(8), integer_value(2)], names=["x", "y"])
    out = []
    for _ in range(n):
        xs = rng.standard_normal((bs, 8)).astype(np.float32)
        ys = (xs.sum(1) > 0).astype(np.int32)
        out.append(conv(list(zip(xs, ys))))
    return out


def test_matrix_vector_roundtrip():
    m = api.Matrix.createDense([1, 2, 3, 4, 5, 6], 2, 3)
    assert m.getHeight() == 2 and m.getWidth() == 3
    assert m.get(1, 2) == 6.0
    m.set(0, 0, 9.0)
    np.testing.assert_allclose(m.copyToNumpyMat()[0, 0], 9.0)

    v = api.Vector.create([1.5, 2.5])
    assert v.getSize() == 2
    iv = api.IVector.create([3, 4, 5])
    assert iv.copyToNumpyArray().tolist() == [3, 4, 5]


def test_arguments_slots():
    args = api.Arguments.createArguments(2)
    assert args.getSlotNum() == 2
    args.setSlotValue(0, api.Matrix.createDense([0.0] * 8, 2, 4))
    args.setSlotIds(1, api.IVector.create([1, 0]))
    assert args.getSlotValue(0).getWidth() == 4
    assert args.getSlotIds(1).getSize() == 2


def test_gradient_machine_forward_backward():
    cfg = _config()
    m = api.GradientMachine.createFromConfigProto(cfg.model_config)
    params = m.getParameters()
    assert params and all(isinstance(p, api.Parameter) for p in params)
    # parameter get/set round-trip
    p0 = params[0]
    val = p0.getValue()
    p0.setValue(np.zeros_like(val))
    assert np.all(params[0].getValue() == 0)
    p0.setValue(val)

    batch = _batches(1)[0]
    outs = m.forwardTest(batch)
    out_name = [n for n in outs if n.startswith("__fc_layer_1")]
    assert out_name, list(outs)

    loss, grads = m.forwardBackward(batch)
    assert np.isfinite(loss)
    assert set(grads) == set(m.params)


def test_manual_training_loop_converges():
    """The testTrain.py pattern: GradientMachine + ParameterOptimizer."""
    cfg = _config()
    m = api.GradientMachine.createFromConfigProto(cfg.model_config)
    opt = api.ParameterOptimizer.create(cfg.opt_config, cfg.model_config)
    opt.init(m.params)
    batches = _batches(20)
    costs = []
    opt.startPass()
    for b in batches:
        loss, grads = m.forwardBackward(b)
        m.params = opt.update(m.params, grads, batch_size=16)
        costs.append(loss)
    opt.finishPass()
    assert costs[-1] < costs[0] * 0.8, (costs[0], costs[-1])


def test_api_trainer_loop():
    """The api_train.py pattern: api.Trainer driving passes."""
    cfg = _config()
    m = api.GradientMachine.createFromConfigProto(cfg.model_config)
    tr = api.Trainer.create(cfg, m)
    batches = _batches(10)
    tr.startTrain()
    pass_costs = []
    for _ in range(3):
        tr.startTrainPass()
        for b in batches:
            tr.trainOneDataBatch(16, b)
        tr.finishTrainPass()
        pass_costs.append(tr.getPassCost())
    tr.startTestPeriod()
    for b in _batches(3, seed=9):
        tr.testOneDataBatch(16, b)
    test_cost = tr.finishTestPeriod()
    tr.finishTrain()
    assert pass_costs[-1] < pass_costs[0]
    assert np.isfinite(test_cost)
    # machine received the trained params back
    assert m.params is tr._t.params


def test_machine_save_load(tmp_path):
    cfg = _config()
    m = api.GradientMachine.createFromConfigProto(cfg.model_config, seed=3)
    m.saveParameters(str(tmp_path))
    m2 = api.GradientMachine.createFromConfigProto(cfg.model_config, seed=9)
    import os
    sub = [os.path.join(str(tmp_path), d) for d in os.listdir(str(tmp_path))]
    m2.loadParameters(sub[0])
    for name in m.params:
        np.testing.assert_array_equal(np.asarray(m.params[name]),
                                      np.asarray(m2.params[name]))
