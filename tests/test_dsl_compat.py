"""Reference DSL-surface compat: every name trainer_config_helpers
exports must exist here AND the composites must build/train (ref:
python/paddle/trainer_config_helpers/*.py __all__ lists)."""

import re

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer


def test_every_reference_export_exists():
    ref_names = set()
    for f in ["layers", "networks", "optimizers", "activations", "poolings",
              "evaluators", "attrs", "data_sources", "default_decorators"]:
        try:
            src = open("/root/reference/python/paddle/"
                       f"trainer_config_helpers/{f}.py").read()
        except OSError:
            pytest.skip("reference tree unavailable")
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
        if m:
            ref_names |= set(re.findall(r"[\"']([^\"']+)[\"']", m.group(1)))
    import paddle_tpu.dsl as dsl
    missing = sorted(n for n in ref_names if not hasattr(dsl, n))
    assert not missing, f"missing DSL exports: {missing}"


def test_recurrent_units_and_gru_composites_train():
    """lstmemory_unit / gru_unit inside user recurrent_groups, plus
    bidirectional_gru over simple_gru2 — build, train, loss drops."""
    V, T, B = 24, 6, 8

    def conf():
        from paddle_tpu.dsl import (
            AdamOptimizer, LinearActivation, ParamAttr, SoftmaxActivation,
            classification_cost, concat_layer, data_layer, embedding_layer,
            fc_layer, gru_unit, last_seq, lstmemory_unit, bidirectional_gru,
            recurrent_group, settings,
        )
        settings(batch_size=B, learning_rate=3e-3,
                 learning_method=AdamOptimizer())
        w = data_layer(name="word", size=V)
        emb = embedding_layer(input=w, size=12,
                              param_attr=ParamAttr(initial_std=0.1))

        # the reference contract: inputs arrive PRE-PROJECTED (4*size for
        # the lstm unit, 3*size for the gru unit)
        def lstm_step(ipt):
            proj = fc_layer(input=ipt, size=32, act=LinearActivation(),
                            name="u_lstm_in")
            return lstmemory_unit(input=proj, name="u_lstm")

        def gru_step(ipt):
            return gru_unit(input=fc_layer(input=ipt, size=24,
                                           name="u_gru_in"), name="u_gru")

        ls = recurrent_group(step=lstm_step, input=emb, name="rg_lstm")
        gs = recurrent_group(step=gru_step, input=emb, name="rg_gru")
        bg = bidirectional_gru(input=emb, size=8, return_seq=False)
        feats = concat_layer(input=[last_seq(input=ls), last_seq(input=gs),
                                    bg])
        out = fc_layer(input=feats, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    rng = np.random.default_rng(3)
    batches = [{
        "word": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                         lengths=np.full((B,), T, np.int32)),
        "y": Argument(ids=rng.integers(0, 4, B).astype(np.int32)),
    } for _ in range(10)]
    tr = Trainer(parse_config_callable(conf), seed=0)
    first = float(np.mean([tr.train_one_batch(b) for b in batches]))
    last = first
    for _ in range(4):
        last = float(np.mean([tr.train_one_batch(b) for b in batches]))
    assert last < first, (first, last)


def test_img_conv_bn_pool_and_misc_layers_train():
    """img_conv_bn_pool composite + out_prod/sum_to_one_norm layers +
    evaluator_base + Cudnn pooling aliases, end to end."""
    H = 8

    def conf():
        from paddle_tpu.dsl import (
            CudnnAvgPooling, CudnnMaxPooling, MomentumOptimizer,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, evaluator_base, fc_layer, img_conv_bn_pool,
            out_prod_layer, settings, sum_to_one_norm_layer,
        )
        from paddle_tpu.dsl import AvgPooling, MaxPooling
        assert CudnnMaxPooling is MaxPooling
        assert CudnnAvgPooling is AvgPooling
        settings(batch_size=8, learning_rate=0.02,
                 learning_method=MomentumOptimizer(momentum=0.9))
        img = data_layer(name="img", size=3 * H * H, height=H, width=H)
        conv = img_conv_bn_pool(input=img, filter_size=3, num_filters=4,
                                pool_size=2, num_channel=3,
                                act=TanhActivation(), conv_padding=1,
                                pool_stride=2)
        a = fc_layer(input=conv, size=5, act=TanhActivation())
        b = fc_layer(input=conv, size=3, act=TanhActivation())
        op = out_prod_layer(input1=a, input2=b)
        norm = sum_to_one_norm_layer(
            input=fc_layer(input=conv, size=6, act=SoftmaxActivation()))
        out = fc_layer(input=[op, norm], size=4, act=SoftmaxActivation())
        label = data_layer(name="y", size=4)
        classification_cost(input=out, label=label)
        evaluator_base(input=out, type="classification_error", label=label)

    rng = np.random.default_rng(4)
    batches = [{
        "img": Argument(value=rng.normal(size=(8, 3 * H * H))
                        .astype(np.float32)),
        "y": Argument(ids=rng.integers(0, 4, 8).astype(np.int32)),
    } for _ in range(6)]
    tr = Trainer(parse_config_callable(conf), seed=0)
    first = float(np.mean([tr.train_one_batch(b) for b in batches]))
    last = first
    for _ in range(4):
        last = float(np.mean([tr.train_one_batch(b) for b in batches]))
    assert last < first, (first, last)


def test_wrap_default_decorators():
    """The wrap_* decorator surface user configs extend the DSL with."""
    from paddle_tpu.dsl import (
        TanhActivation, wrap_act_default, wrap_bias_attr_default,
        wrap_name_default, wrap_param_attr_default,
    )
    from paddle_tpu.dsl.base import config_context

    with config_context():
        @wrap_name_default("myhelper")
        @wrap_act_default()
        @wrap_param_attr_default()
        @wrap_bias_attr_default()
        def helper(name=None, act=None, param_attr=None, bias_attr=None):
            return name, act, param_attr, bias_attr

        n1, a, p, b = helper()
        n2, _, _, _ = helper()
        assert n1 != n2 and "myhelper" in n1
        assert isinstance(a, TanhActivation)
        assert p is not None and b is not None
        # explicit values pass through untouched
        n3, a3, _, b3 = helper(name="x", act="ACT", bias_attr=False)
        assert (n3, a3, b3) == ("x", "ACT", False)


def test_agg_level_nested_pooling():
    """AggregateLevel semantics on a nested input: EACH_SEQUENCE pools per
    sub-sequence (a sequence out), EACH_TIMESTEP pools the whole outer
    sequence flat (one vector) — numpy oracle both ways."""
    import jax.numpy as jnp

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import AggregateLevel
    from paddle_tpu.graph.builder import GraphExecutor

    def conf(level):
        def f():
            from paddle_tpu.dsl import (
                MomentumOptimizer, SumPooling, data_layer, pooling_layer,
                settings,
            )
            settings(batch_size=2, learning_rate=0.1,
                     learning_method=MomentumOptimizer())
            x = data_layer(name="x", size=3)
            pooling_layer(input=x, pooling_type=SumPooling(),
                          agg_level=level, name="pooled")
        return f

    rng = np.random.default_rng(5)
    B, S, T, D = 2, 2, 4, 3               # nested layout: [B, S, T, D]
    val = rng.normal(size=(B, S, T, D)).astype(np.float32)
    lengths = np.asarray([2, 1], np.int32)            # sub-seqs per row
    sub_lengths = np.asarray([[3, 2], [4, 0]], np.int32)
    feed = {"x": Argument(value=jnp.asarray(val),
                          lengths=jnp.asarray(lengths),
                          sub_lengths=jnp.asarray(sub_lengths))}

    def run(level):
        cfg = parse_config_callable(conf(level))
        ex = GraphExecutor(cfg.model_config)
        params = ex.init_params(0)
        outputs, _, _ = ex.forward(params, feed)
        return outputs["pooled"]

    seq = run(AggregateLevel.EACH_SEQUENCE)     # per-sub sums: [B, S, D]
    flat = run(AggregateLevel.EACH_TIMESTEP)    # all-token sums: [B, D]
    v = np.asarray(seq.value, np.float32)
    assert v.shape == (B, S, D)
    np.testing.assert_allclose(v[0, 0], val[0, 0, :3].sum(0), rtol=1e-5)
    np.testing.assert_allclose(v[0, 1], val[0, 1, :2].sum(0), rtol=1e-5)
    np.testing.assert_allclose(v[1, 0], val[1, 0, :4].sum(0), rtol=1e-5)
    np.testing.assert_allclose(v[1, 1], 0.0, atol=1e-7)   # invalid sub
    f = np.asarray(flat.value, np.float32)
    assert f.shape == (B, D)
    np.testing.assert_allclose(
        f[0], val[0, 0, :3].sum(0) + val[0, 1, :2].sum(0), rtol=1e-5)
    np.testing.assert_allclose(f[1], val[1, 0, :4].sum(0), rtol=1e-5)
