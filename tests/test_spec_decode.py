"""Speculative decoding with exact verification (serving/engine.py
`_run_spec_step` / `_spec_impl`, serving/drafter.py, sampler.py
`pick_next_chain`, paged_kv.py `uncommit_tail`).

The contract is absolute: speculation may change how many compiled steps
it takes to emit the tokens, NEVER the tokens — spec-on output is
bit-identical to spec-off (and therefore to the cold
`lm_generate(use_cache=True)` oracle) across every sampling knob, GQA,
prefix-cache hits + COW, chunked prefill coexistence, preempt/replay,
and tensor parallelism, while the compiled set stays bounded (the one
decode signature + ONE verify signature per (budget, spec_k); the mixed
signature never compiles while speculation is on).  Rejections must also
leave the allocator EXACTLY as a sequential engine would — the
uncommit_tail rollback accounting is checked with the kv.check oracle
under a drafter built to be always wrong."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import NgramDrafter, Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer


@pytest.fixture(scope="module")
def tr():
    # layers=1 keeps compiles cheap on the tier-1 CPU budget; the
    # multi-layer + GQA spec paths get their own configs below
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=23,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _assert_exact(tr, reqs, results):
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), results[r.req_id],
            err_msg=f"request {r.req_id!r} diverged from the cold "
                    f"lm_generate oracle under speculation")


def _rep_prompt(rng, vocab, n, motif=4):
    """Locally-repetitive prompt (tiled motif) so the n-gram drafter has
    something to find — the workload speculation targets."""
    m = rng.integers(2, vocab, motif).astype(np.int32)
    return np.tile(m, -(-n // motif))[:n]


def _assert_sigs(eng):
    """The tentpole's signature discipline under speculation: the one
    decode signature, ONE verify signature, and the mixed step never
    compiled (the verify step subsumes it while spec is on)."""
    assert eng._decode_step._cache_size() <= 1
    assert eng._spec_step._cache_size() == 1
    assert eng._mixed_step._cache_size() == 0, \
        "the mixed step compiled while speculation was on — the verify " \
        "signature should be carrying the chunk rows"


# ---------------------------------------------------------------------------
# the bit-exact oracle across sampling knobs / GQA / TP
# ---------------------------------------------------------------------------

def test_spec_on_equals_spec_off_across_sampling_knobs(tr):
    """All four sampling modes (greedy / top-k / nucleus / full), mixed
    repetitive prompt lengths: the speculative engine's tokens are
    bit-identical to the sequential engine's AND to the lm_generate
    oracle, with at least one draft genuinely accepted (the accept path
    ran, not just the reject path) and the signature set pinned."""
    rng = np.random.default_rng(0)
    knobs = [dict(), dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9), dict(temperature=1.1)]

    def reqs():
        return [Request(f"r{i}", _rep_prompt(rng2, 23, 11 + 2 * i),
                        max_new=8, rng=jax.random.PRNGKey(40 + i), **kw)
                for i, (rng2, kw) in enumerate(
                    (np.random.default_rng(100 + j), k)
                    for j, k in enumerate(knobs))]

    kw = dict(num_slots=2, page_size=4, max_context=32)
    base = ServingEngine(tr.executor, tr.params, **kw).run(reqs())
    eng = ServingEngine(tr.executor, tr.params, spec_k=3, **kw)
    spec = eng.run(reqs())
    assert set(base) == set(spec)
    for k in base:
        np.testing.assert_array_equal(base[k], spec[k], err_msg=str(k))
    _assert_exact(tr, reqs(), spec)
    assert eng.n_spec_drafted > 0 and eng.n_spec_accepted > 0, \
        "the workload never exercised the accept path"
    assert eng.n_spec_accepted <= eng.n_spec_drafted
    _assert_sigs(eng)
    eng.kv.check_reclaimed()


def test_spec_gqa_grouped_heads_stay_exact():
    """Grouped-query attention under speculation: the verify step's
    ragged multi-row dispatch with h_kv < heads reproduces the
    sequential tokens exactly."""
    cfg = parse_config(
        "demo/model_zoo/transformer_lm.py",
        "vocab=97,dim=32,layers=2,heads=4,batch_size=4,kv_heads=2")
    tr2 = Trainer(cfg, seed=5)
    rng = np.random.default_rng(2)
    prompts = [_rep_prompt(rng, 97, n, motif=5) for n in (7, 12, 9)]
    kw = dict(num_slots=2, page_size=8, max_context=64)
    reqs = lambda: [Request(i, p.copy(), max_new=6)
                    for i, p in enumerate(prompts)]
    base = ServingEngine(tr2.executor, tr2.params, **kw).run(reqs())
    eng = ServingEngine(tr2.executor, tr2.params, spec_k=3, **kw)
    spec = eng.run(reqs())
    for k in base:
        np.testing.assert_array_equal(base[k], spec[k], err_msg=str(k))
    assert eng.n_spec_drafted > 0


def test_spec_tp_model2_host_mesh_stays_exact():
    """Speculation composes with tensor parallelism: a model=2 host-mesh
    engine with spec on is token-for-token the single-device spec-off
    engine (the verify step runs through the same sharded ragged core
    and the sharded MLP/vocab projections)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest provides 8)")
    from paddle_tpu.parallel.mesh import model_mesh

    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    tr2 = Trainer(cfg, seed=3)
    rng = np.random.default_rng(4)
    prompts = [_rep_prompt(rng, 61, n) for n in (8, 13, 6)]
    knobs = [dict(), dict(temperature=0.8, top_k=5), dict(temperature=1.1)]
    reqs = lambda: [Request(i, p.copy(), max_new=6,
                            rng=jax.random.PRNGKey(70 + i), **kw)
                    for i, (p, kw) in enumerate(zip(prompts, knobs))]
    kw = dict(num_slots=2, page_size=8, max_context=64)
    tr2.executor.mesh = None
    base = ServingEngine(tr2.executor, tr2.params, **kw).run(reqs())
    tr2.executor.mesh = None
    eng = ServingEngine(tr2.executor, tr2.params, spec_k=3,
                        mesh=model_mesh(2), **kw)
    spec = eng.run(reqs())
    for k in base:
        np.testing.assert_array_equal(
            base[k], spec[k],
            err_msg=f"request {k!r} diverged between single-device "
                    f"sequential and model=2 speculative decode")
    assert eng.tp == 2 and eng.n_spec_drafted > 0
    _assert_sigs(eng)
    tr2.executor.mesh = None


# ---------------------------------------------------------------------------
# the distributional claim: fixed-key acceptance IS lm_generate's law
# ---------------------------------------------------------------------------

def test_rejection_sampled_acceptance_matches_lm_generate_law(tr):
    """The rejection-sampling equivalence at fixed keys: across many rng
    keys, full-distribution sampling through the speculative engine
    emits EXACTLY what lm_generate samples with the same key schedule —
    i.e. acceptance never warps the sampling law, it only decides how
    many tokens a step emits.  (With deterministic per-slot keys the
    classic accept-with-p(target)/p(draft) test degenerates to this
    stronger per-key exactness — the distribution matches because every
    single stream matches.)"""
    rng = np.random.default_rng(6)
    prompt = _rep_prompt(rng, 23, 10)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=3)
    accepted_any = 0
    for seed in range(10):
        # odd keys sample the FULL distribution (the law at maximum
        # entropy — acceptance is rare there and that is fine); even
        # keys sample peaked (temperature 0.05 — the untrained model's
        # logits are nearly flat, so only a very low temperature makes
        # the drafted continuation likely and genuinely runs the
        # sampled-acceptance path)
        temp = 1.0 if seed % 2 else 0.05
        r = Request(f"k{seed}", prompt.copy(), max_new=7,
                    temperature=temp, rng=jax.random.PRNGKey(seed))
        a0 = eng.n_spec_accepted
        got = eng.run([r])[r.req_id]
        accepted_any += eng.n_spec_accepted - a0
        np.testing.assert_array_equal(
            _oracle(tr, r), got,
            err_msg=f"key {seed} (temp {temp}): speculative sampling "
                    f"diverged from lm_generate's sampling law")
    assert accepted_any > 0, \
        "no key ever accepted a draft — the law test never exercised " \
        "the acceptance path"


# ---------------------------------------------------------------------------
# composition: prefix cache, chunked prefill, preempt/replay
# ---------------------------------------------------------------------------

def test_spec_with_prefix_hits_and_cow_stays_exact(tr):
    """Prefix-cache hits + mid-page COW divergence under speculation:
    followers map the donor's pages, diverge inside the boundary page,
    and speculate over their own committed tokens — all bit-exact, with
    the donor page surviving for an exact repeat."""
    rng = np.random.default_rng(7)
    base_p = _rep_prompt(rng, 23, 13)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=3)
    a = Request("a", base_p.copy(), max_new=6)
    results = eng.run([a])
    b = Request("b", np.concatenate(
        [base_p[:11], (base_p[11:13] + 1) % 23 + 2]).astype(np.int32),
        max_new=6)
    results.update(eng.run([b]))
    assert eng.n_prefix_hits >= 1 and eng.kv.n_cow >= 1
    again = Request("again", base_p.copy(), max_new=6)
    results.update(eng.run([again]))
    _assert_exact(tr, [a, b, again], results)
    assert eng.n_spec_drafted > 0
    eng.kv.check_reclaimed()


def test_spec_chains_coexist_with_prefill_chunks_under_budget(tr):
    """Mode-aware packing: a long prompt commits in chunk rows on the
    SAME verify dispatches that carry another slot's draft chains — the
    decoder keeps advancing (no stall), the budget histogram never
    exceeds max_step_tokens, and both requests stay exact."""
    rng = np.random.default_rng(8)
    short = Request("short", _rep_prompt(rng, 23, 4), max_new=12)
    long_ = Request("long", _rep_prompt(rng, 23, 25), max_new=4)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefill_chunk=4,
                        max_step_tokens=8, spec_k=2)
    eng.add_request(short)
    eng.step()                        # short: final chunk + token 0
    eng.step()                        # short decoding (specs when drafts)
    eng.add_request(long_)
    overlapped = 0
    while any(sl is not None and sl.req is long_ and sl.gen == 0
              for sl in eng.slots) or long_ in eng.queue:
        chunks0, chains0 = eng.n_prefill_chunks, eng.n_spec_chains
        before = eng.tokens_generated
        eng.step()
        if eng.n_prefill_chunks > chunks0 and eng.n_spec_chains > chains0:
            overlapped += 1
        assert eng.tokens_generated > before, \
            "a chunk-carrying step advanced no decode token"
    assert overlapped > 0, \
        "no step carried chunk rows and a spec chain together"
    results = dict(eng.results)       # short may have finished already
    results.update(eng.run())
    _assert_exact(tr, [short, long_], results)
    # the hard budget bound holds for verify steps too
    h = eng.step_tokens_hist
    counts, _total, n = h._vals[()]
    over = counts[-1] - counts[h.buckets.index(8.0)]
    assert n == eng.n_decode_steps and over == 0, \
        "a verify step scheduled more rows than max_step_tokens"
    _assert_sigs(eng)


def test_spec_preempt_replay_with_drafts_in_flight_stays_exact(tr):
    """Preempt/replay under an overcommitted pool with speculation on:
    victims roll back (their chain tails uncommitted), replay through
    verify steps, and every request still bit-matches the sequential
    engine AND the oracle; the allocator balances to zero refs."""
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    tr2 = Trainer(cfg, seed=7)
    rng = np.random.default_rng(9)
    prompts = [_rep_prompt(rng, 11, n, motif=3) for n in (6, 4, 5, 3, 6)]
    reqs = lambda: [Request(i, p.copy(), max_new=8)
                    for i, p in enumerate(prompts)]
    kw = dict(num_slots=2, page_size=4, max_context=16, num_pages=6)
    base_eng = ServingEngine(tr2.executor, tr2.params, **kw)
    base = base_eng.run(reqs())
    assert base_eng.n_preemptions > 0, "pool was never overcommitted"
    eng = ServingEngine(tr2.executor, tr2.params, spec_k=3, **kw)
    spec = eng.run(reqs())
    assert eng.n_preemptions > 0 and eng.n_spec_drafted > 0
    for k in base:
        np.testing.assert_array_equal(base[k], spec[k], err_msg=str(k))
    assert (eng.kv._ref == 0).all()
    eng.kv.check()


# ---------------------------------------------------------------------------
# rollback accounting + the drafter interface
# ---------------------------------------------------------------------------

class _WrongDrafter:
    """Pluggable-drafter interface exercised adversarially: proposes
    tokens chosen to NEVER match what greedy sampling emits (the oracle
    tokens shifted by one in vocab), forcing full rejection of every
    chain — the maximal-rollback path."""

    def __init__(self, tr, vocab, k_always):
        self.tr, self.vocab, self.k = tr, vocab, k_always

    def propose(self, ctx, k):
        return np.full(min(k, self.k), -1 % self.vocab, np.int32)


def test_forced_full_rejection_rolls_back_pages_exactly(tr):
    """A drafter that is ALWAYS wrong: every chain rejects completely,
    every step pays the maximal uncommit_tail rollback — and the engine
    still emits the exact oracle tokens one per step (a chain with zero
    accepts degenerates to sequential decode), with the allocator
    invariants (kv.check) holding mid-flight and the pool fully
    reclaimed at the end."""
    rng = np.random.default_rng(10)

    class Wrong:
        def propose(self, ctx, k):
            # token 0 is never generated (prompts/vocab draw from 2..),
            # and greedy argmax over a softmax head never emits it for
            # this seed — verified by the exactness assert below
            return np.zeros(k, np.int32)

    reqs = [Request(i, _rep_prompt(rng, 23, 6 + i), max_new=6)
            for i in range(3)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=3, drafter=Wrong())
    for r in reqs:
        eng.add_request(r)
    rolled = 0
    while eng.step():
        # mid-flight allocator oracle after every step: a bad rollback
        # (leaked tail page, freed shared page) trips here, not at the
        # end-of-workload accounting
        eng.kv.check()
    results = {k: eng.results.pop(k) for k in list(eng.results)}
    assert eng.n_spec_drafted > 0 and eng.n_spec_accepted == 0
    _assert_exact(tr, reqs, results)
    eng.kv.check_reclaimed()


def test_oracle_drafter_multiplies_steps_down(tr):
    """The throughput claim at its ceiling: a drafter that knows the
    continuation (replays a recorded greedy run) gets accept rate 1.0
    and emits max_new tokens in ~max_new/(k+1) verify steps — the
    dispatch-rate multiplication the tentpole exists for."""
    rng = np.random.default_rng(11)
    prompt = _rep_prompt(rng, 23, 9)
    probe = Request("probe", prompt.copy(), max_new=12)
    full = _oracle(tr, probe)

    class Replay:
        def propose(self, ctx, k):
            n = ctx.size
            if n < full.size and np.array_equal(full[:n], ctx):
                return full[n:n + k].astype(np.int32)
            return np.zeros(0, np.int32)

    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=4, drafter=Replay())
    got = eng.run([Request("o", prompt.copy(), max_new=12)])["o"]
    np.testing.assert_array_equal(full, got)
    assert eng.spec_accept_rate == 1.0
    # 12 tokens: token 0 at prefill + 11 decode tokens in chains of up
    # to 5 — at most ceil(11/5)+1 = 4 steps vs 12 sequentially
    assert eng.n_decode_steps <= 5, \
        f"{eng.n_decode_steps} steps for 12 tokens at accept rate 1.0"
    # counters reconcile exactly: chain tokens = accepted + chains
    assert eng.n_spec_tokens == eng.n_spec_accepted + eng.n_spec_chains


def test_ngram_drafter_proposes_recent_continuations():
    """The default prompt-lookup drafter: longest trailing n-gram wins,
    the MOST RECENT occurrence is used, proposals never exceed k, and
    degenerate contexts propose nothing."""
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    ctx = np.asarray([5, 6, 7, 9, 5, 6, 7, 8, 5, 6, 7], np.int32)
    # trailing 3-gram [5,6,7] last occurred at index 4 -> continues [8, 5]
    np.testing.assert_array_equal(d.propose(ctx, 2), [8, 5])
    # k caps the proposal
    np.testing.assert_array_equal(d.propose(ctx, 1), [8])
    # no repeat anywhere: nothing proposed
    assert d.propose(np.asarray([1, 2, 3, 4], np.int32), 3).size == 0
    # sub-2-token context: nothing proposed
    assert d.propose(np.asarray([3], np.int32), 3).size == 0
    # min_ngram respected: unigram fallback finds the last occurrence
    ctx2 = np.asarray([4, 9, 4, 2, 4], np.int32)
    np.testing.assert_array_equal(
        NgramDrafter(max_ngram=3, min_ngram=1).propose(ctx2, 1), [2])


def test_set_speculation_validates_and_toggles(tr):
    """set_speculation is the idle A/B knob: negative k rejects, the
    toggle is idle-only, and flipping spec on/off round-trips to
    identical tokens (the A/B bench's precondition)."""
    rng = np.random.default_rng(12)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32)
    with pytest.raises(ValueError, match="spec_k"):
        eng.set_speculation(-1)
    prompt = _rep_prompt(rng, 23, 10)
    off = eng.run([Request("r", prompt.copy(), max_new=6)])["r"]
    eng.set_speculation(3)
    on = eng.run([Request("r", prompt.copy(), max_new=6)])["r"]
    eng.set_speculation(0)
    off2 = eng.run([Request("r", prompt.copy(), max_new=6)])["r"]
    np.testing.assert_array_equal(off, on)
    np.testing.assert_array_equal(off, off2)
    assert eng.spec_k == 0


def test_draft_growth_never_evicts_cached_prefix_pages(tr):
    """try_grow(evict=False) — the draft-tail growth mode — takes FREE
    pages only: when the free list cannot cover the chain, the grow
    fails (the chain verifies fewer drafts) instead of invoking the
    prefix index's LRU eviction.  Optimistic pages a rejection returns
    the same step must never cost a committed cached prefix its
    retention."""
    rng = np.random.default_rng(13)
    # pool of 9 real pages, ps=4: request a commits 3 pages and donates
    # 2 whole ones to the prefix index at retire
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, num_pages=10)
    eng.run([Request("a", _rep_prompt(rng, 23, 11), max_new=2)])
    kv = eng.kv
    cached0 = kv.cached_page_count
    assert cached0 > 0, "retire donated nothing to the prefix index"
    # occupy the whole free list on slot 0
    assert kv.try_grow(0, len(kv._free) * 4)
    assert kv.free_page_count == 0
    # draft-mode growth on slot 1 must FAIL dry, not evict the cache
    assert not kv.try_grow(1, 8, evict=False)
    assert kv.cached_page_count == cached0, \
        "evict=False growth reclaimed cached prefix pages"
    # the default admission-mode growth MAY evict (the existing policy)
    assert kv.try_grow(1, 4)
    assert kv.cached_page_count < cached0
    kv.release(0)
    kv.release(1)
    kv.check()


def test_uncommit_tail_releases_only_private_tail_pages(tr):
    """paged_kv.uncommit_tail unit contract: trailing pages above the
    committed token count return to the free list, pages the committed
    span still needs stay, and the allocator oracle holds."""
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefix_cache=False)
    kv = eng.kv
    assert kv.try_grow(0, 14)               # 4 pages for 14 tokens
    assert int(kv._n_pages[0]) == 4
    freed = kv.uncommit_tail(0, 6)          # keep 2 pages
    assert freed == 2 and int(kv._n_pages[0]) == 2
    kv.check()
    assert kv.uncommit_tail(0, 6) == 0      # idempotent at the boundary
    kv.release(0)
    kv.check_reclaimed()


# ---------------------------------------------------------------------------
# adaptive speculation (PR 18): model drafter, dynamic k, the clamp contract
# ---------------------------------------------------------------------------

from paddle_tpu.obs.compile_watch import get_compile_watch
from paddle_tpu.serving.drafter import ModelDrafter, clamp_proposal


def test_clamp_proposal_contract():
    """The drafter-side clamp unit: at most k tokens, truncated just
    AFTER the first eos (a drafted eos may retire the slot; tokens past
    it could never be banked), eos_id=-1 disables the eos cut, and
    degenerate inputs stay empty."""
    d = np.asarray([4, 5, 6, 7, 8], np.int32)
    np.testing.assert_array_equal(clamp_proposal(d, 3), [4, 5, 6])
    # eos mid-proposal: keep the eos, drop everything after
    np.testing.assert_array_equal(clamp_proposal(d, 5, eos_id=6), [4, 5, 6])
    # eos beyond the k cut: the k clamp applies first
    np.testing.assert_array_equal(clamp_proposal(d, 2, eos_id=6), [4, 5])
    # no eos sentinel: untouched besides the k cap
    np.testing.assert_array_equal(clamp_proposal(d, 9, eos_id=-1), d)
    assert clamp_proposal(d, 0).size == 0
    assert clamp_proposal(np.zeros(0, np.int32), 4, eos_id=2).size == 0


def test_ngram_drafter_never_proposes_past_eos():
    """The eos clamp reaches the default drafter: a looked-up
    continuation containing eos truncates just after it — the bug class
    the engine's tripwire exists for (proposals past eos / past k used
    to be silently truncated, skewing accept-rate stats)."""
    d = NgramDrafter(max_ngram=2, min_ngram=1)
    # trailing [5, 6] last occurred early; its continuation is [9, 3, 8]
    ctx = np.asarray([5, 6, 9, 3, 8, 2, 5, 6], np.int32)
    np.testing.assert_array_equal(d.propose(ctx, 3), [9, 3, 8])
    # same lookup with eos=3: the proposal cuts just AFTER the eos
    np.testing.assert_array_equal(d.propose(ctx, 3, eos_id=3), [9, 3])
    # eos as the first continuation token: a one-token proposal
    np.testing.assert_array_equal(d.propose(ctx, 3, eos_id=9), [9])


def test_engine_asserts_on_drafter_clamp_violation(tr):
    """A drafter that violates the clamp contract (returns more than k
    tokens) trips the engine's assert instead of being silently
    truncated — a drafter bug must fail loudly, not masquerade as a low
    accept rate."""
    class Overlong:
        def propose(self, ctx, k):
            return np.zeros(k + 2, np.int32)

    rng = np.random.default_rng(14)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=2, drafter=Overlong())
    with pytest.raises(AssertionError, match="clamp contract"):
        eng.run([Request("r", _rep_prompt(rng, 23, 8), max_new=6)])


def test_model_drafter_self_spec_exact_and_one_signature(tr):
    """Self-speculation end to end: ModelDrafter.from_target drafting
    for ALL slots in one batched dispatch, with dynamic k and
    decode_mode=auto on — tokens bit-identical to the spec-off engine
    and the lm_generate oracle across all four sampling modes, the
    accept path genuinely exercised (greedy self-drafts agree with the
    greedy target), and EXACTLY ONE serving.draft_step signature for
    the whole workload (dynamic k rides as data)."""
    rng = np.random.default_rng(0)
    knobs = [dict(), dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9), dict(temperature=1.1)]

    def reqs():
        return [Request(f"m{i}", _rep_prompt(np.random.default_rng(200 + i),
                                             23, 9 + 2 * i),
                        max_new=8, rng=jax.random.PRNGKey(60 + i), **kw)
                for i, kw in enumerate(knobs)]

    kw = dict(num_slots=2, page_size=4, max_context=32)
    base = ServingEngine(tr.executor, tr.params, **kw).run(reqs())
    cw = get_compile_watch()
    sigs0 = cw.signature_count("serving.draft_step")
    verify0 = cw.signature_count("serving.spec_step")
    eng = ServingEngine(
        tr.executor, tr.params, spec_k=3, spec_dynamic=True,
        drafter=ModelDrafter.from_target(tr.executor, tr.params, window=16),
        **kw)
    spec = eng.run(reqs())
    assert set(base) == set(spec)
    for k in base:
        np.testing.assert_array_equal(base[k], spec[k], err_msg=str(k))
    _assert_exact(tr, reqs(), spec)
    assert eng.drafter_kind == "model"
    assert eng.n_draft_steps > 0 and eng.n_spec_accepted > 0, \
        "self-speculation never accepted a draft — greedy agreement " \
        "with the target should be near-certain"
    assert cw.signature_count("serving.draft_step") == sigs0 + 1, \
        "the batched draft dispatch must be ONE signature per (S, k)"
    assert cw.signature_count("serving.spec_step") - verify0 <= 1, \
        "dynamic k minted extra verify signatures — variable k must " \
        "ride as data"
    _assert_sigs(eng)
    eng.kv.check_reclaimed()


def test_model_drafter_law_across_ten_keys(tr):
    """The distributional-law matrix with the MODEL drafter: across 10
    rng keys (full-distribution and peaked alternating), the adaptive
    engine (model drafts + dynamic k) emits EXACTLY what lm_generate
    samples with the same key schedule — adaptivity never warps the
    sampling law."""
    rng = np.random.default_rng(15)
    prompt = _rep_prompt(rng, 23, 10)
    eng = ServingEngine(
        tr.executor, tr.params, num_slots=2, page_size=4, max_context=32,
        spec_k=3, spec_dynamic=True,
        drafter=ModelDrafter.from_target(tr.executor, tr.params, window=16))
    accepted_any = 0
    for seed in range(10):
        temp = 1.0 if seed % 2 else 0.05
        r = Request(f"k{seed}", prompt.copy(), max_new=7,
                    temperature=temp, rng=jax.random.PRNGKey(seed))
        a0 = eng.n_spec_accepted
        got = eng.run([r])[r.req_id]
        accepted_any += eng.n_spec_accepted - a0
        np.testing.assert_array_equal(
            _oracle(tr, r), got,
            err_msg=f"key {seed} (temp {temp}): adaptive speculation "
                    f"diverged from lm_generate's sampling law")
    assert accepted_any > 0


def test_dynamic_k_rises_to_full_depth_under_oracle_drafter(tr):
    """Dynamic-k convergence, favorable direction: an oracle drafter
    (accept rate 1.0) starts at the cold one-row probe and the EWMA
    drives k_s to the full spec_k — and the tokens stay exact."""
    rng = np.random.default_rng(16)
    prompt = _rep_prompt(rng, 23, 6)
    probe = Request("probe", prompt.copy(), max_new=16)
    full = _oracle(tr, probe)

    class Replay:
        def propose(self, ctx, k):
            n = ctx.size
            if n < full.size and np.array_equal(full[:n], ctx):
                return full[n:n + k].astype(np.int32)
            return np.zeros(0, np.int32)

    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=4, spec_dynamic=True,
                        drafter=Replay())
    eng.add_request(Request("o", prompt.copy(), max_new=16))
    ks = []
    while eng.step():
        for sl in eng.slots:
            if sl is not None and sl.accept_ewma is not None:
                ks.append(eng._dyn_k(sl))
    got = eng.results["o"]
    np.testing.assert_array_equal(full, got)
    assert eng.spec_accept_rate == 1.0
    assert ks and max(ks) == eng.spec_k, \
        f"EWMA never drove k to full depth (saw {sorted(set(ks))})"
    assert ks[-1] == eng.spec_k, "k did not STAY at full depth"


def test_dynamic_k_decays_to_plain_decode_under_adversarial_drafter(tr):
    """Dynamic-k convergence, hostile direction: an always-wrong drafter
    decays the slot to k=0 (plain decode — zero wasted verify rows)
    after the cold probe rejects, leaving only the paced re-probe; the
    engine must spend almost nothing on drafts while staying exact."""
    rng = np.random.default_rng(17)

    class Wrong:
        def propose(self, ctx, k):
            return np.zeros(k, np.int32)     # token 0 is never emitted

    r = Request("w", _rep_prompt(rng, 23, 5), max_new=20)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=3, spec_dynamic=True,
                        drafter=Wrong())
    eng.add_request(r)
    saw_zero = False
    while eng.step():
        for sl in eng.slots:
            if sl is not None and sl.accept_ewma is not None:
                saw_zero |= (int(round(sl.accept_ewma * eng.spec_k)) == 0)
    _assert_exact(tr, [r], dict(eng.results))
    assert saw_zero, "the EWMA never decayed the slot to k=0"
    assert eng.n_spec_accepted == 0
    # cold probe (1 token) + at most one paced re-probe over 19 windows
    # (_PROBE_EVERY = 16) + slack: nowhere near 19 * k = 57 static waste
    assert eng.n_spec_drafted <= 4, \
        f"dynamic k kept drafting against a 0.0 accept rate " \
        f"({eng.n_spec_drafted} drafted)"
    assert eng.n_spec_steps <= 4, "most windows should be PLAIN decode"


def test_model_drafter_tp_model2_stays_exact():
    """Adaptive speculation composes with tensor parallelism: a model=2
    engine with the batched model drafter + dynamic k is
    token-for-token the single-device spec-off engine.  The drafter's
    replication contract holds regardless of construction order (it
    snapshots a mesh-free executor), and the draft program stays ONE
    signature."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest provides 8)")
    from paddle_tpu.parallel.mesh import model_mesh

    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    tr2 = Trainer(cfg, seed=3)
    rng = np.random.default_rng(18)
    prompts = [_rep_prompt(rng, 61, n) for n in (8, 13, 6)]
    knobs = [dict(), dict(temperature=0.8, top_k=5), dict(temperature=1.1)]
    reqs = lambda: [Request(i, p.copy(), max_new=6,
                            rng=jax.random.PRNGKey(80 + i), **kw)
                    for i, (p, kw) in enumerate(zip(prompts, knobs))]
    kw = dict(num_slots=2, page_size=8, max_context=64)
    tr2.executor.mesh = None
    base = ServingEngine(tr2.executor, tr2.params, **kw).run(reqs())
    tr2.executor.mesh = None
    # drafter built BEFORE the engine stamps the mesh — the ordering
    # serve.py uses; the mesh-free snapshot must hold anyway
    drafter = ModelDrafter.from_target(tr2.executor, tr2.params, window=16)
    cw = get_compile_watch()
    sigs0 = cw.signature_count("serving.draft_step")
    eng = ServingEngine(tr2.executor, tr2.params, spec_k=3,
                        spec_dynamic=True, drafter=drafter,
                        mesh=model_mesh(2), **kw)
    spec = eng.run(reqs())
    for k in base:
        np.testing.assert_array_equal(
            base[k], spec[k],
            err_msg=f"request {k!r} diverged between single-device "
                    f"sequential and model=2 adaptive speculation")
    assert eng.tp == 2 and eng.n_draft_steps > 0
    assert cw.signature_count("serving.draft_step") == sigs0 + 1
    _assert_sigs(eng)
    tr2.executor.mesh = None


def test_set_speculation_dynamic_toggle_and_state_roundtrip(tr):
    """set_speculation(k, drafter, dynamic) is the idle A/B surface for
    the whole adaptive matrix, and the per-slot EWMA state rides
    checkpoint/restore (a restored engine resumes the learned depths
    instead of re-probing cold)."""
    rng = np.random.default_rng(19)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32)
    assert not eng.spec_dynamic
    eng.set_speculation(3, dynamic=True)
    assert eng.spec_k == 3 and eng.spec_dynamic
    eng.set_speculation(3, dynamic=False)
    assert not eng.spec_dynamic
    eng.set_speculation(
        2, drafter=ModelDrafter.from_target(tr.executor, tr.params,
                                            window=16), dynamic=True)
    assert eng.drafter_kind == "model" and eng.spec_dynamic
    # roundtrip: a mid-flight snapshot carries accept_ewma/probe_tick
    eng.add_request(Request("r", _rep_prompt(rng, 23, 8), max_new=8))
    for _ in range(3):
        eng.step()
    sl = next(s for s in eng.slots if s is not None)
    sl.probe_tick = 5                      # make the value distinctive
    snap = eng.checkpoint_state()
    eng2 = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, spec_k=2, spec_dynamic=True,
                        drafter=ModelDrafter.from_target(
                            tr.executor, tr.params, window=16))
    eng2.restore_state(snap)
    sl2 = next(s for s in eng2.slots if s is not None)
    assert sl2.accept_ewma == sl.accept_ewma
    assert sl2.probe_tick == 5
    assert eng2.n_draft_steps == eng.n_draft_steps
    got = eng2.run()["r"]
    _assert_exact(tr, [Request("r", _rep_prompt(
        np.random.default_rng(19), 23, 8), max_new=8)], {"r": got})
