"""Pooling fast-path oracle: the reshape-based tiled and global pooling
paths must match the generic `reduce_window` implementation exactly, in
forward AND gradient (the fast paths exist because reduce_window's
max-pool backward lowers to TPU's slow select-and-scatter; ref:
paddle/cuda/src/hl_cuda_cnn.cu hl_maxpool_forward/backward semantics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config.schema import PoolConfig
from paddle_tpu.graph.layers_conv import (
    pool2d_forward_nhwc, pool2d_reduce_window)


def _pool_cfg(ptype, size, stride, img, pad=0):
    return PoolConfig(pool_type=ptype, channels=3, size_x=size, stride=stride,
                      padding=pad, img_size=img, img_size_y=img)


@pytest.mark.parametrize("ptype", ["max-projection", "avg-projection"])
@pytest.mark.parametrize("size,stride,img", [
    (2, 2, 8),      # tiled 2x2/s2 (the VGG case)
    (4, 4, 8),      # tiled 4x4/s4
    (8, 8, 4),      # window > image: global pooling
])
def test_fastpath_matches_reduce_window(ptype, size, stride, img):
    p = _pool_cfg(ptype, size, stride, img)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, img, img, 3)),
                    jnp.float32)
    ref_fn = lambda a: pool2d_reduce_window(a, p)

    got = pool2d_forward_nhwc(x, p)
    want = ref_fn(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)

    # gradients: for avg they must match exactly; for max they may differ
    # only at tied window maxima (measure-zero for continuous data) — this
    # random input has no ties, so exact agreement is required there too
    g_got = jax.grad(lambda a: jnp.sum(jnp.square(pool2d_forward_nhwc(a, p))))(x)
    g_want = jax.grad(lambda a: jnp.sum(jnp.square(ref_fn(a))))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-6)


def test_overlapping_window_still_generic():
    """3x3/s2 (overlapping) must keep the exact reduce_window semantics."""
    p = _pool_cfg("max-projection", 3, 2, 8)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8, 3)),
                    jnp.float32)
    got = pool2d_forward_nhwc(x, p)
    assert got.shape == (2, 4, 4, 3)
    # fast paths would produce a different shape/semantics; the generic
    # path's output equals a hand-rolled window max
    man = np.full((2, 4, 4, 3), -np.inf, np.float32)
    xn = np.asarray(x)
    for oy in range(4):
        for ox in range(4):
            ys, xs = oy * 2, ox * 2
            man[:, oy, ox] = xn[:, ys:min(ys + 3, 8), xs:min(xs + 3, 8)].max((1, 2))
    np.testing.assert_allclose(np.asarray(got), man, rtol=1e-6)
