"""Attention ops + context parallelism tests.

Oracle strategy follows the reference's CPU-vs-GPU comparison tests
(SURVEY.md §4: test_matrixCompare) — dense attention is the oracle, the
blockwise and ring (context-parallel, 8-virtual-device mesh) paths must
match it in both forward values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import (

    blockwise_attention,
    dot_product_attention,
    multi_head_attention,
)

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


def _rand_qkv(rng, B=2, T=16, H=2, D=4, Tk=None):
    Tk = Tk or T
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, Tk, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, Tk, H, D)), jnp.float32)
    return q, k, v


def _valid(lengths, T):
    return jnp.arange(T)[None, :] < jnp.asarray(lengths)[:, None]


class TestDense:
    def test_softmax_rows_sum_to_one(self):
        rng = np.random.default_rng(0)
        q, k, v = _rand_qkv(rng)
        ones = jnp.ones_like(v)
        out = dot_product_attention(q, k, ones)
        np.testing.assert_allclose(out, np.ones(out.shape), rtol=1e-5)

    def test_causal_first_token_attends_self_only(self):
        rng = np.random.default_rng(1)
        q, k, v = _rand_qkv(rng)
        out = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out[:, 0], v[:, 0], rtol=1e-5)

    def test_masked_rows_are_zero(self):
        rng = np.random.default_rng(2)
        B, T = 2, 8
        q, k, v = _rand_qkv(rng, B=B, T=T)
        valid = _valid([5, 8], T)
        out = dot_product_attention(q, k, v, q_valid=valid, k_valid=valid)
        np.testing.assert_allclose(out[0, 5:], np.zeros_like(out[0, 5:]))

    def test_masked_keys_do_not_contribute(self):
        rng = np.random.default_rng(3)
        B, T = 2, 8
        q, k, v = _rand_qkv(rng, B=B, T=T)
        valid = _valid([6, 6], T)
        out1 = dot_product_attention(q, k, v, k_valid=valid)
        # poison the masked keys/values; result must not change
        k2 = k.at[:, 6:].set(100.0)
        v2 = v.at[:, 6:].set(-50.0)
        out2 = dot_product_attention(q, k2, v2, k_valid=valid)
        np.testing.assert_allclose(out1, out2, rtol=1e-5)


class TestBlockwise:
    @pytest.mark.parametrize("block_k", [4, 5, 16, 64])
    def test_matches_dense(self, block_k):
        rng = np.random.default_rng(4)
        q, k, v = _rand_qkv(rng, T=16, Tk=20)
        ref = dot_product_attention(q, k, v)
        out = blockwise_attention(q, k, v, block_k=block_k)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_matches_dense_causal_and_lengths(self):
        rng = np.random.default_rng(5)
        B, T = 3, 12
        q, k, v = _rand_qkv(rng, B=B, T=T)
        valid = _valid([12, 7, 3], T)
        ref = dot_product_attention(q, k, v, q_valid=valid, k_valid=valid,
                                    causal=True)
        out = blockwise_attention(q, k, v, q_valid=valid, k_valid=valid,
                                  causal=True, block_k=5)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_grads_match_dense(self):
        rng = np.random.default_rng(6)
        q, k, v = _rand_qkv(rng, T=8)

        def loss_dense(q, k, v):
            return jnp.sum(jnp.square(dot_product_attention(q, k, v, causal=True)))

        def loss_block(q, k, v):
            return jnp.sum(jnp.square(
                blockwise_attention(q, k, v, causal=True, block_k=4)))

        g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        g_out = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_out):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_backward_memory_stays_blockwise(self):
        """The scan body is rematerialized: backward must NOT save every
        block's score tile (n_blocks x [B,H,Tq,block_k] residuals measured
        32 GB at T=16384 on v5e, MEASURE/attn_bench round 4 — it OOM'd the
        chip).  Without remat, temp memory is quadratic in T (n_blocks
        tiles, each itself linear in T): doubling T must NOT ~4x the
        compiled backward's temp bytes.  Measured with remat: 106.9 ->
        246.6 MB (2.3x); without: would be >= 4.3x."""
        def temp_bytes(T, block=512):
            q = jnp.zeros((1, T, 2, 64), jnp.bfloat16)

            def loss(q, k, v):
                return blockwise_attention(
                    q, k, v, causal=True,
                    block_k=block).astype(jnp.float32).sum()

            c = jax.jit(jax.grad(loss, argnums=(0, 1, 2))
                        ).lower(q, q, q).compile()
            return c.memory_analysis().temp_size_in_bytes

        t1, t2 = temp_bytes(2048), temp_bytes(4096)
        assert t2 < 3.0 * t1, (t1, t2)


class TestRing:
    """Context parallelism on the 8-virtual-device CPU mesh (conftest)."""

    def _mesh(self, data=2, seq=4):
        from paddle_tpu.parallel.mesh import make_mesh
        return make_mesh(data=data, seq=seq)

    @pytest.mark.parametrize("data,seq", [(1, 8), (2, 4)])
    def test_matches_dense(self, data, seq):
        from paddle_tpu.parallel.context import ring_attention_sharded
        rng = np.random.default_rng(7)
        q, k, v = _rand_qkv(rng, B=4, T=16)
        mesh = self._mesh(data, seq)
        ref = dot_product_attention(q, k, v)
        out = ring_attention_sharded(mesh, q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_matches_dense_causal_varlen(self):
        from paddle_tpu.parallel.context import ring_attention_sharded
        rng = np.random.default_rng(8)
        B, T = 4, 16
        q, k, v = _rand_qkv(rng, B=B, T=T)
        valid = _valid([16, 9, 3, 13], T)
        mesh = self._mesh(2, 4)
        ref = dot_product_attention(q, k, v, q_valid=valid, k_valid=valid,
                                    causal=True)
        out = ring_attention_sharded(mesh, q, k, v, q_valid=valid,
                                     k_valid=valid, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_cross_attention_unequal_lengths_causal(self):
        """Tq != Tk: key-block global positions must use the KEY shard length."""
        from paddle_tpu.parallel.context import ring_attention_sharded
        rng = np.random.default_rng(19)
        q, k, v = _rand_qkv(rng, B=2, T=8, Tk=16)
        mesh = self._mesh(2, 4)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_seq_only_mesh_keeps_data_axis(self):
        """make_mesh always emits a data axis so shard_batch specs resolve."""
        from paddle_tpu.parallel.mesh import make_mesh
        from paddle_tpu.parallel.dp import shard_batch
        from paddle_tpu.parameter.argument import Argument
        mesh = make_mesh(data=1, seq=8)
        assert "data" in mesh.axis_names
        batch = {"x": Argument(value=jnp.zeros((4, 8)))}
        shard_batch(mesh, batch)  # must not raise

    def test_grads_match_dense(self):
        from paddle_tpu.parallel.context import ring_attention_sharded
        rng = np.random.default_rng(9)
        q, k, v = _rand_qkv(rng, B=2, T=8)
        mesh = self._mesh(1, 8)

        def loss_ref(q, k, v):
            return jnp.sum(jnp.square(dot_product_attention(q, k, v)))

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring_attention_sharded(mesh, q, k, v)))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_out = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g_ref, g_out):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestMHALayer:
    def _build(self, mesh=None, causal=False, T=12):
        from paddle_tpu.config.parser import parse_config_callable
        from paddle_tpu.dsl import (
            MomentumOptimizer, data_layer, fc_layer, multi_head_attention_layer,
            classification_cost, pooling_layer, settings, SoftmaxActivation,
        )
        from paddle_tpu.dsl.poolings import AvgPooling

        def conf():
            settings(batch_size=4, learning_rate=0.01,
                     learning_method=MomentumOptimizer(momentum=0.9))
            x = data_layer(name="x", size=16)
            h = multi_head_attention_layer(x, size=16, num_heads=4,
                                           causal=causal)
            pooled = pooling_layer(input=h, pooling_type=AvgPooling())
            out = fc_layer(input=pooled, size=4, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=4))

        from paddle_tpu.trainer.trainer import Trainer
        return Trainer(parse_config_callable(conf), seed=0, mesh=mesh)

    def _batch(self, B=4, T=12, D=16):
        from paddle_tpu.parameter.argument import Argument
        rng = np.random.default_rng(10)
        x = rng.normal(size=(B, T, D)).astype(np.float32)
        lens = np.array([T, T - 3, 5, T], np.int32)
        y = rng.integers(0, 4, B).astype(np.int32)
        return {"x": Argument(value=jnp.asarray(x), lengths=jnp.asarray(lens)),
                "y": Argument(ids=jnp.asarray(y))}

    def test_train_step_single_device(self):
        tr = self._build()
        loss = tr.train_one_batch(self._batch())
        assert np.isfinite(loss)

    def test_layer_flash_block_sizes_attrs_beat_env(self, monkeypatch):
        """The flash branch forwards block_q/block_k to the kernel in BOTH
        the training path and the cached-decode prefill: per-layer attrs
        win over the PADDLE_TPU_FLASH_BLOCK_Q/K env defaults (written from
        tools/tune_flash.py's on-device sweep), which beat the 128x128
        kernel default."""
        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.graph.lm_decode import lm_generate
        from paddle_tpu.ops import pallas_attention
        from paddle_tpu.trainer.trainer import Trainer

        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCK_Q", "256")
        monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCK_K", "512")
        seen = {}
        real = pallas_attention.flash_attention

        def spy(*a, **kw):
            seen.update({k: kw.get(k) for k in ("block_q", "block_k")})
            return real(*a, **kw)

        monkeypatch.setattr(pallas_attention, "flash_attention", spy)
        cfg = parse_config("demo/model_zoo/transformer_lm.py",
                           "dim=32,layers=1,heads=2,vocab=64,batch_size=2,"
                           "attn_impl=flash")
        tr = Trainer(cfg, seed=0)
        tr.train_one_batch(next(tr.train_batches()))
        assert seen == {"block_q": 256, "block_k": 512}   # env defaults

        # cached-decode prefill takes the same tuned sizes (it is the
        # long-context case tuning targets)
        seen.clear()
        toks, _ = lm_generate(tr.executor, tr.params,
                              np.ones((1, 4), np.int32), max_new=2,
                              use_cache=True)
        assert seen == {"block_q": 256, "block_k": 512}

        # per-layer attrs beat the env defaults
        seen.clear()
        for layer in cfg.model_config.layers:
            if layer.type == "multi_head_attention":
                layer.attrs["block_q"] = 128
                layer.attrs["block_k"] = 128
        tr2 = Trainer(cfg, seed=0)
        tr2.train_one_batch(next(tr2.train_batches()))
        assert seen == {"block_q": 128, "block_k": 128}

    def test_ring_path_matches_single_device(self):
        """Same params, same batch: seq-parallel mesh loss == local loss."""
        from paddle_tpu.parallel.mesh import make_mesh
        tr_local = self._build(causal=True)
        mesh = make_mesh(data=2, seq=4)
        tr_mesh = self._build(mesh=mesh, causal=True)
        # deep-copy: train_step donates its params buffer
        tr_mesh.params = {k: jnp.array(np.asarray(v))
                          for k, v in tr_local.params.items()}
        batch = self._batch()
        l_local = tr_local.train_one_batch(batch)
        l_mesh = tr_mesh.train_one_batch(batch)
        assert abs(l_local - l_mesh) < 1e-4, (l_local, l_mesh)


class TestUlysses:
    """All-to-all (Ulysses) context parallelism on the 8-device CPU mesh:
    tokens->heads resharding, local full-sequence attention, reshard back
    — must match dense exactly (same math, different layout)."""

    def _mesh(self, data=2, seq=4):
        from paddle_tpu.parallel.mesh import make_mesh
        return make_mesh(data=data, seq=seq)

    @pytest.mark.parametrize("data,seq,H", [(1, 8, 8), (2, 4, 4)])
    def test_matches_dense(self, data, seq, H):
        from paddle_tpu.parallel.context import ulysses_attention_sharded
        rng = np.random.default_rng(31)
        q, k, v = _rand_qkv(rng, B=4, T=16, H=H)
        mesh = self._mesh(data, seq)
        ref = dot_product_attention(q, k, v)
        out = ulysses_attention_sharded(mesh, q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_matches_dense_causal_varlen(self):
        from paddle_tpu.parallel.context import ulysses_attention_sharded
        rng = np.random.default_rng(32)
        B, T = 4, 16
        q, k, v = _rand_qkv(rng, B=B, T=T, H=4)
        valid = _valid([16, 9, 3, 13], T)
        mesh = self._mesh(2, 4)
        ref = dot_product_attention(q, k, v, q_valid=valid, k_valid=valid,
                                    causal=True)
        out = ulysses_attention_sharded(mesh, q, k, v, q_valid=valid,
                                        k_valid=valid, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_head_divisibility_enforced(self):
        from paddle_tpu.parallel.context import ulysses_attention_sharded
        rng = np.random.default_rng(33)
        q, k, v = _rand_qkv(rng, B=2, T=8, H=2)    # 2 heads, seq axis 4
        with pytest.raises(AssertionError, match="divisible"):
            ulysses_attention_sharded(self._mesh(2, 4), q, k, v)

    def test_layer_attn_impl_ulysses_trains(self):
        """attn_impl='ulysses' through the config layer on a seq mesh:
        losses track the single-device dense run."""
        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.trainer.trainer import Trainer

        args = ("dim=32,layers=1,heads=4,vocab=64,batch_size=8,"
                "attn_impl={}")
        steps = 4

        def run(impl, mesh):
            cfg = parse_config("demo/model_zoo/transformer_lm.py",
                               args.format(impl))
            tr = Trainer(cfg, seed=0, mesh=mesh)
            it = tr.train_batches()
            return [float(tr.train_one_batch(next(it)))
                    for _ in range(steps)]

        l_dense = run("dense", None)
        l_uly = run("ulysses", self._mesh(2, 4))
        np.testing.assert_allclose(l_uly, l_dense, rtol=5e-3, atol=5e-3)

        # a ulysses-trained config must DECODE too: the cached prefill
        # accepts the impl and falls through to local selection
        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.graph.lm_decode import lm_generate
        from paddle_tpu.trainer.trainer import Trainer
        cfg = parse_config("demo/model_zoo/transformer_lm.py",
                           args.format("ulysses"))
        tr = Trainer(cfg, seed=0)          # decode runs un-meshed
        toks, _ = lm_generate(tr.executor, tr.params,
                              np.ones((2, 4), np.int32), max_new=3,
                              use_cache=True)
        assert np.asarray(toks).shape == (2, 7)
