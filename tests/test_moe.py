"""Mixture-of-experts tests: routing invariants, single-expert oracle,
mesh-sharded equivalence (expert parallelism), DSL layer training."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel.moe import moe_ffn, moe_routing


class TestRouting:
    def test_dispatch_capacity_respected(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
        dispatch, combine, aux = moe_routing(logits, top_k=2, capacity=3)
        # each expert's buffer slot holds at most one token
        per_slot = jnp.sum(dispatch, axis=0)          # [E, C]
        assert float(per_slot.max()) <= 1.0 + 1e-6
        # each token occupies at most top_k slots
        per_tok = jnp.sum(dispatch, axis=(1, 2))
        assert float(per_tok.max()) <= 2.0 + 1e-6

    def test_combine_weights_normalized(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        # big capacity: nothing dropped -> combine sums to 1 per token
        _, combine, _ = moe_routing(logits, top_k=2, capacity=16)
        sums = jnp.sum(combine, axis=(1, 2))
        np.testing.assert_allclose(sums, np.ones(8), rtol=1e-5)

    def test_aux_loss_uniform_is_one(self):
        # uniform routing -> aux loss == 1 (its minimum for balanced load)
        logits = jnp.zeros((16, 4), jnp.float32)
        _, _, aux = moe_routing(logits, top_k=1, capacity=16)
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


class TestMoeFfn:
    def _params(self, rng, E, D, H, Dout):
        return dict(
            w_router=jnp.asarray(rng.normal(size=(D, E)) * 0.1, jnp.float32),
            w1=jnp.asarray(rng.normal(size=(E, D, H)) * 0.3, jnp.float32),
            b1=jnp.zeros((E, H), jnp.float32),
            w2=jnp.asarray(rng.normal(size=(E, H, Dout)) * 0.3, jnp.float32),
            b2=jnp.zeros((E, Dout), jnp.float32),
        )

    def test_single_expert_equals_plain_ffn(self):
        rng = np.random.default_rng(2)
        p = self._params(rng, E=1, D=8, H=16, Dout=8)
        x = jnp.asarray(rng.normal(size=(6, 8)), jnp.float32)
        y, aux = moe_ffn(x, **p, top_k=1, capacity_factor=8.0)
        ref = jax.nn.relu(x @ p["w1"][0] + p["b1"][0]) @ p["w2"][0] + p["b2"][0]
        np.testing.assert_allclose(y, ref, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)

    def test_sharded_matches_single_device(self):
        """Expert params sharded over `model` + tokens over `data` must give
        the same result as unsharded execution."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel.mesh import make_mesh
        rng = np.random.default_rng(3)
        E, D, H = 4, 8, 16
        p = self._params(rng, E=E, D=D, H=H, Dout=D)
        x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
        ref, _ = moe_ffn(x, **p, top_k=2, capacity_factor=2.0)

        mesh = make_mesh(data=2, model=4)
        px = jax.device_put(x, NamedSharding(mesh, P("data")))
        pp = dict(p)
        for k in ("w1", "b1", "w2", "b2"):
            spec = P("model", *([None] * (p[k].ndim - 1)))
            pp[k] = jax.device_put(p[k], NamedSharding(mesh, spec))

        @jax.jit
        def run(x, pp):
            return moe_ffn(x, **pp, top_k=2, capacity_factor=2.0)[0]

        out = run(px, pp)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=1e-6)

    def test_grads_flow_to_all_params(self):
        rng = np.random.default_rng(4)
        p = self._params(rng, E=4, D=8, H=16, Dout=8)
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)

        def loss(p):
            y, aux = moe_ffn(x, **p, top_k=2, capacity_factor=2.0)
            return jnp.sum(jnp.square(y)) + 0.01 * aux

        g = jax.grad(loss)(p)
        for k, v in g.items():
            assert float(jnp.abs(v).max()) > 0.0, f"zero grad for {k}"


class TestMoeLayer:
    def test_dsl_layer_trains(self):
        from paddle_tpu.config.parser import parse_config_callable
        from paddle_tpu.dsl import (
            MomentumOptimizer, SoftmaxActivation, classification_cost,
            data_layer, fc_layer, moe_layer, settings,
        )
        from paddle_tpu.parameter.argument import Argument
        from paddle_tpu.trainer.trainer import Trainer

        def conf():
            settings(batch_size=16, learning_rate=0.05,
                     learning_method=MomentumOptimizer(momentum=0.9))
            x = data_layer(name="x", size=12)
            h = moe_layer(x, num_experts=4, expert_hidden=32)
            out = fc_layer(input=h, size=4, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=4))

        tr = Trainer(parse_config_callable(conf), seed=0)
        rng = np.random.default_rng(0)

        def batch():
            x = rng.normal(size=(16, 12)).astype(np.float32)
            y = (x.sum(-1) > 0).astype(np.int32) * 3
            return {"x": Argument(value=jnp.asarray(x)),
                    "y": Argument(ids=jnp.asarray(y))}

        losses = [tr.train_one_batch(batch()) for _ in range(15)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_dsl_layer_on_mesh(self):
        """Same config trains on a (data, model) mesh with expert params
        sharded by their partition specs."""
        from paddle_tpu.config.parser import parse_config_callable
        from paddle_tpu.dsl import (
            MomentumOptimizer, SoftmaxActivation, classification_cost,
            data_layer, fc_layer, moe_layer, settings,
        )
        from paddle_tpu.parallel.mesh import make_mesh
        from paddle_tpu.parameter.argument import Argument
        from paddle_tpu.trainer.trainer import Trainer

        def conf():
            settings(batch_size=16, learning_rate=0.05,
                     learning_method=MomentumOptimizer(momentum=0.9))
            x = data_layer(name="x", size=12)
            h = moe_layer(x, num_experts=4, expert_hidden=32)
            out = fc_layer(input=h, size=4, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=4))

        mesh = make_mesh(data=2, model=4)
        tr = Trainer(parse_config_callable(conf), seed=0, mesh=mesh)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 12)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        loss = tr.train_one_batch({"x": Argument(value=jnp.asarray(x)),
                                   "y": Argument(ids=jnp.asarray(y))})
        assert np.isfinite(loss)
