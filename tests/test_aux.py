"""Aux-subsystem tests: checkgrad, param stats, NaN localisation, cluster
launcher command construction, trainer CLI jobs (mirrors ref: the trainer's
checkgrad job Trainer.cpp:303+, showParameterStats TrainerInternal.cpp:187,
CustomStackTrace-on-crash, scripts/cluster_train/paddle.py)."""

import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer


def _small_config(bad_log: bool = False):
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, TanhActivation,
        classification_cost, data_layer, fc_layer, settings,
    )
    from paddle_tpu.dsl.activations import LogActivation

    def conf():
        settings(batch_size=8, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=12)
        h = fc_layer(input=x, size=16,
                     act=LogActivation() if bad_log else TanhActivation())
        out = fc_layer(input=h, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    return parse_config_callable(conf)


def _batch(seed=0, B=8, scale=1.0):
    rng = np.random.default_rng(seed)
    return {
        "x": Argument(value=(rng.normal(size=(B, 12)) * scale).astype(np.float32)),
        "y": Argument(ids=rng.integers(0, 4, B).astype(np.int32)),
    }


class TestCheckGrad:
    def test_analytic_matches_numeric(self):
        tr = Trainer(_small_config(), seed=0)
        errors = tr.check_gradient(_batch(), epsilon=1e-3, max_entries=3)
        assert errors, "no parameters checked"
        worst = max(errors.values())
        # fp32 central differences: ~1e-2 noise floor (the CLI job uses 2e-2)
        assert worst < 2e-2, f"gradient check failed: {errors}"

    def test_wrong_gradient_is_flagged(self):
        """The noise-aware denominator must not make the check vacuous: a
        corrupted analytic gradient of visible magnitude still flags."""
        import jax.numpy as jnp
        tr = Trainer(_small_config(), seed=0)
        params = {"w": jnp.asarray([0.5, -0.3], jnp.float32)}

        def loss_fn(p):
            return jnp.sum(p["w"] ** 2)
        good = {"w": jnp.asarray([1.0, -0.6], jnp.float32)}   # d(w^2) = 2w
        bad = {"w": jnp.asarray([2.0, -0.6], jnp.float32)}    # w0 doubled
        e_good = tr._check_gradient_inner(loss_fn, good, 1e-3, 2, params)
        e_bad = tr._check_gradient_inner(loss_fn, bad, 1e-3, 2, params)
        assert e_good["w"] < 2e-2, e_good
        assert e_bad["w"] > 0.3, e_bad

    def test_kink_entries_are_skipped_in_refine(self):
        """FD across a ReLU-style |x| kink measures the subgradient
        average, not the one-sided analytic derivative — the f64 refine
        pass detects the fwd/bwd one-sided mismatch (after an epsilon-
        shrink retry) and skips the entry instead of reporting a spurious
        failure (the VGG configs' fc-bias entries hit exactly this)."""
        import jax  # noqa: F401
        import jax.numpy as jnp

        from paddle_tpu.utils import jax_compat
        tr = Trainer(_small_config(), seed=0)
        with jax_compat.enable_x64():
            params = {"w": jnp.asarray([0.0, 0.5], jnp.float64)}

            def loss_fn(p):
                return jnp.abs(p["w"][0]) + p["w"][1] ** 2
            # the kink sits EXACTLY at w0=0, so even the shrunk epsilon
            # straddles it; analytic reports the one-sided 1.0 (or 0 —
            # either way FD measures ~0 and would flag spuriously); w1's
            # gradient is exact
            grads = {"w": jnp.asarray([1.0, 1.0], jnp.float64)}
            errs = tr._check_gradient_inner(loss_fn, grads, 1e-3, 2, params,
                                            None, detect_kinks=True)
            assert errs["w"] < 2e-2, errs
            # without kink detection the same entry reports a large error
            errs_raw = tr._check_gradient_inner(loss_fn, grads, 1e-3, 2,
                                                params)
            assert errs_raw["w"] > 0.3, errs_raw

    def test_all_kink_parameter_keeps_fp32_flag(self):
        """ADVICE r5 regression: when EVERY sampled entry of a flagged
        parameter straddles a kink (a zero-init bias feeding ReLU), the
        f64 refine adjudicates nothing — it must OMIT the key (so
        check_gradient keeps the fp32 screen's flagged error and
        --job=checkgrad still exits 1), not record 0.0 and mask the
        flag."""
        import jax.numpy as jnp

        from paddle_tpu.utils import jax_compat
        tr = Trainer(_small_config(), seed=0)
        with jax_compat.enable_x64():
            # both entries sit EXACTLY on |x| kinks: nothing can validate
            params = {"w": jnp.asarray([0.0, 0.0], jnp.float64)}

            def loss_fn(p):
                return jnp.sum(jnp.abs(p["w"]))

            grads = {"w": jnp.asarray([1.0, 1.0], jnp.float64)}
            errs = tr._check_gradient_inner(loss_fn, grads, 1e-3, 2, params,
                                            None, detect_kinks=True)
        assert "w" not in errs, (
            f"unadjudicated parameter must not report a (clean-looking) "
            f"error: {errs}")

        # merge level: the fp32 screen's flagged value survives the
        # inconclusive refine, so the exit-code contract still fails
        tr2 = Trainer(_small_config(), seed=0)
        passes = []

        def fake_pass(batch, epsilon, max_entries, x64, names=None,
                      detect_kinks=False):
            passes.append(x64)
            return {} if x64 else {"w": 0.5}

        tr2._checkgrad_pass = fake_pass
        import jax
        if jax.default_backend() == "cpu":
            errors = tr2.check_gradient(_batch(), refine_threshold=0.02)
            assert passes == [False, True]
            assert errors["w"] == 0.5, (
                f"flagged-but-unadjudicated error was overwritten: {errors}")

    def test_two_stage_refine_end_to_end(self):
        """check_gradient's fp32-screen -> f64-refine flow: forcing every
        parameter through the refine (threshold -1) exercises enable_x64,
        the dtype round-trip, and the subset stream alignment — refined
        errors must stay under the CLI bar and cover every parameter."""
        tr = Trainer(_small_config(), seed=0)
        errors = tr.check_gradient(_batch(), epsilon=1e-3, max_entries=2,
                                   refine_threshold=-1.0)
        assert errors and max(errors.values()) < 2e-2, errors
        # subset alignment: refining exactly one parameter probes the same
        # entries the full pass samples, so its error stays consistent
        one = sorted(errors)[0]
        sub = tr._checkgrad_pass(_batch(), 1e-3, 2, x64=True, names=[one],
                                 detect_kinks=True)
        assert set(sub) == {one}
        assert abs(sub[one] - errors[one]) < 2e-2, (sub, errors[one])


class TestParamStats:
    def test_stats_shape(self):
        tr = Trainer(_small_config(), seed=0)
        stats = tr.param_stats()
        assert set(stats) == set(tr.params)
        for s in stats.values():
            assert s["max_abs"] >= s["mean_abs"] >= 0.0


class TestNanDiagnosis:
    def test_nonfinite_loss_names_layer(self):
        """Under --detect_nan (the reference's opt-in feenableexcept analog),
        log(negative) in layer 1 -> the error must name that layer."""
        from paddle_tpu.utils.flags import FLAGS
        tr = Trainer(_small_config(bad_log=True), seed=0)
        old = FLAGS.detect_nan
        FLAGS.detect_nan = True
        try:
            with pytest.raises(FloatingPointError, match="fc_layer"):
                # large negative inputs make log() produce NaN
                tr.train_one_batch(_batch(scale=100.0))
        finally:
            FLAGS.detect_nan = old

    def test_nonfinite_caught_by_periodic_bulk_check(self):
        """Without --detect_nan, losses buffer on device (no per-batch host
        sync) and the bulk check still raises within
        nonfinite_check_period batches."""
        from paddle_tpu.utils.flags import FLAGS
        tr = Trainer(_small_config(bad_log=True), seed=0)
        old = FLAGS.nonfinite_check_period
        FLAGS.nonfinite_check_period = 4
        try:
            with pytest.raises(FloatingPointError, match="non-finite loss"):
                for _ in range(4):
                    tr.train_one_batch(_batch(scale=100.0))
        finally:
            FLAGS.nonfinite_check_period = old


class TestFlagParsing:
    def test_bare_bool_flag_does_not_eat_next_flag(self):
        from paddle_tpu.utils.flags import FLAGS
        old_nan, old_passes = FLAGS.detect_nan, FLAGS.num_passes
        try:
            rest = FLAGS.parse(["--detect_nan", "--num_passes=5"])
            assert rest == []
            assert FLAGS.detect_nan is True
            assert FLAGS.num_passes == 5
        finally:
            FLAGS.detect_nan, FLAGS.num_passes = old_nan, old_passes


class TestClusterLaunch:
    def test_build_commands(self):
        from paddle_tpu.tools.cluster_launch import build_commands
        cmds = build_commands(["h0", "h1", "h2"], 8476, "/ws",
                              ["--config=c.py", "--num_passes=2"])
        assert len(cmds) == 3
        assert all(c[0] == "ssh" for c in cmds)
        assert "--coordinator_address=h0:8476" in cmds[0][-1]
        assert "--process_id=2" in cmds[2][-1]
        assert "--num_processes=3" in cmds[1][-1]
        assert "--config=c.py" in cmds[0][-1]

    def test_dry_run_cli(self):
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.cluster_launch",
             "--hosts", "a,b", "--dry_run", "--", "--config=x.py"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        lines = out.stdout.strip().splitlines()
        assert len(lines) == 2 and "ssh" in lines[0]


class TestTrainerMainJobs:
    def _run(self, *extra):
        import os
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.trainer_main",
             "--config=demo/introduction/trainer_config.py", *extra],
            capture_output=True, text=True, timeout=300, cwd=repo, env=env)

    def test_exit_code_contract(self):
        """CLI exit codes: 0 = job ran and passed, 1 = job ran and failed,
        2 = usage/config error — wrapper scripts rely on the distinction
        (the reference's paddle_trainer behaved the same way)."""
        ok = self._run("--job=train", "--num_passes=1", "--save_dir=")
        assert ok.returncode == 0, ok.stderr[-500:]
        usage = self._run("--job=no_such_job")
        assert usage.returncode == 2, (usage.returncode, usage.stderr[-300:])
        # --job=test on a config with no test source is a CONFIG error (2),
        # not a test failure (1)
        no_src = self._run("--job=test")
        assert no_src.returncode == 2, (no_src.returncode,
                                        no_src.stderr[-300:])
        assert "test data source" in no_src.stderr
        # an unparseable config is also a usage error, not a crash (rc 1
        # via traceback was the old behavior); FLAGS.parse is
        # last-occurrence-wins, so _run's extra --config overrides the
        # helper's default
        bad_cfg = self._run("--config=definitely/not/there.py")
        assert bad_cfg.returncode == 2, (bad_cfg.returncode,
                                         bad_cfg.stderr[-300:])
        assert "failed to parse config" in bad_cfg.stderr
        # exit 1 = the job RAN and failed: an impossibly strict checkgrad
        # bar fails on fp32 rounding alone
        strict = self._run("--job=checkgrad", "--checkgrad_bar=1e-14")
        assert strict.returncode == 1, (strict.returncode,
                                        strict.stderr[-300:])
        assert "FAILED" in strict.stderr

    def test_checkgrad_job(self):
        out = self._run("--job=checkgrad")
        assert out.returncode == 0, out.stderr[-2000:]
        assert "checkgrad" in out.stderr or "checkgrad" in out.stdout

    def test_param_stats_period(self):
        out = self._run("--job=train", "--num_passes=1", "--save_dir=",
                        "--show_parameter_stats_period=50")
        assert out.returncode == 0, out.stderr[-2000:]
        blob = out.stdout + out.stderr
        assert "mean_abs" in blob


def test_batch_validation_errors():
    """Common feed mistakes fail fast with specific messages, instead of
    'model has no cost layers' (missing key silently skipping layers) or
    NaN training (out-of-range ids gathering garbage)."""
    import numpy as np
    import pytest

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, classification_cost,
        data_layer, embedding_layer, fc_layer, pooling_layer, settings,
    )
    from paddle_tpu.dsl.poolings import MaxPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        settings(batch_size=4, learning_rate=0.1,
                 learning_method=MomentumOptimizer())
        w = data_layer(name="word", size=50)
        emb = embedding_layer(input=w, size=8)
        p = pooling_layer(input=emb, pooling_type=MaxPooling())
        out = fc_layer(input=p, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="label", size=3))

    tr = Trainer(parse_config_callable(conf), seed=0)
    ids = np.zeros((4, 6), np.int32)
    lens = np.full((4,), 6, np.int32)
    good = {"word": Argument(ids=ids, lengths=lens),
            "label": Argument(ids=np.zeros((4,), np.int32))}

    with pytest.raises(KeyError, match="missing feed.*label"):
        tr.train_one_batch({"word": good["word"]})
    with pytest.raises(KeyError, match="unknown key.*wrod"):
        tr.train_one_batch({**good, "wrod": good["word"]})
    with pytest.raises(ValueError, match="out of range.*size 50"):
        tr.train_one_batch({**good,
                            "word": Argument(ids=ids + 99, lengths=lens)})
    with pytest.raises(ValueError, match="disagree on batch size"):
        tr.train_one_batch({**good,
                            "label": Argument(ids=np.zeros((2,), np.int32))})
    with pytest.raises(ValueError, match="neither dense values nor ids"):
        tr.train_one_batch({**good, "label": Argument()})
    assert np.isfinite(float(tr.train_one_batch(good)))


def test_gradient_accumulation_matches_concatenated_batches():
    """num_batches_per_send_parameter=N accumulates gradients for N batches
    and applies their mean once (ref: RemoteParameterUpdater.cpp:206) —
    numerically identical to training on the N batches concatenated."""
    import numpy as np
    import jax
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf(bs, accum):
        def c():
            from paddle_tpu.dsl import (MomentumOptimizer, SoftmaxActivation,
                                        TanhActivation, classification_cost,
                                        data_layer, fc_layer, settings)
            settings(batch_size=bs, learning_rate=0.1,
                     learning_method=MomentumOptimizer(momentum=0.9),
                     num_batches_per_send_parameter=accum)
            x = data_layer(name="x", size=12)
            h = fc_layer(input=x, size=16, act=TanhActivation())
            out = fc_layer(input=h, size=3, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=3))
        return c

    rng = np.random.default_rng(0)
    micro = []
    for _ in range(6):
        x = rng.normal(size=(8, 12)).astype(np.float32)
        micro.append((x, rng.integers(0, 3, 8).astype(np.int32)))

    tr_a = Trainer(parse_config_callable(conf(8, 3)), seed=1)
    for x, y in micro:
        tr_a.train_one_batch({"x": Argument(value=x), "y": Argument(ids=y)})

    tr_b = Trainer(parse_config_callable(conf(24, 1)), seed=1)
    for i in range(0, 6, 3):
        x = np.concatenate([micro[j][0] for j in range(i, i + 3)])
        y = np.concatenate([micro[j][1] for j in range(i, i + 3)])
        tr_b.train_one_batch({"x": Argument(value=x), "y": Argument(ids=y)})

    for name in tr_a.params:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(tr_a.params[name])),
            np.asarray(jax.device_get(tr_b.params[name])),
            rtol=2e-5, atol=1e-6,
            err_msg=f"accumulated training diverged at {name!r}")


def test_gradient_accumulation_unequal_batches_and_mesh():
    """Sample-weighted accumulation: micro-batches of different sizes must
    still reproduce the concatenated-batch update exactly, and the
    accumulators place correctly on a mesh."""
    import numpy as np
    import jax
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf(bs, accum):
        def c():
            from paddle_tpu.dsl import (MomentumOptimizer, SoftmaxActivation,
                                        TanhActivation, classification_cost,
                                        data_layer, fc_layer, settings)
            settings(batch_size=bs, learning_rate=0.1,
                     learning_method=MomentumOptimizer(momentum=0.9),
                     num_batches_per_send_parameter=accum)
            x = data_layer(name="x", size=12)
            h = fc_layer(input=x, size=16, act=TanhActivation())
            out = fc_layer(input=h, size=3, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=3))
        return c

    rng = np.random.default_rng(1)
    sizes = [8, 8, 4]                        # one short tail micro-batch
    micro = [(rng.normal(size=(n, 12)).astype(np.float32),
              rng.integers(0, 3, n).astype(np.int32)) for n in sizes]

    tr_a = Trainer(parse_config_callable(conf(8, 3)), seed=1)
    for x, y in micro:
        tr_a.train_one_batch({"x": Argument(value=x), "y": Argument(ids=y)})

    tr_b = Trainer(parse_config_callable(conf(20, 1)), seed=1)
    x = np.concatenate([m[0] for m in micro])
    y = np.concatenate([m[1] for m in micro])
    tr_b.train_one_batch({"x": Argument(value=x), "y": Argument(ids=y)})

    for name in tr_a.params:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(tr_a.params[name])),
            np.asarray(jax.device_get(tr_b.params[name])),
            rtol=2e-5, atol=1e-6)

    # mesh path: accumulators placed, training finite
    tr_m = Trainer(parse_config_callable(conf(8, 2)), seed=1,
                   mesh=make_mesh(data=8))
    acc_leaf = jax.tree.leaves(tr_m.opt_state["grad_accum"])[0]
    assert acc_leaf.sharding is not None
    for x, y in [(rng.normal(size=(8, 12)).astype(np.float32),
                  rng.integers(0, 3, 8).astype(np.int32))] * 4:
        loss = float(tr_m.train_one_batch({"x": Argument(value=x),
                                           "y": Argument(ids=y)}))
        assert np.isfinite(loss)
    assert int(tr_m.opt_state["num_updates"]) == 2
