"""Device-resident multi-step decode (ISSUE 16 tentpole): the scanned
step's exactness and signature discipline.

The contract (docs/serving.md "Multi-step decode"): with
`decode_steps=k`, whenever every live slot is pure-decode the engine runs
ONE jitted lax.scan of k identical decode bodies — pos/gen/tokens/KV
advance on device k tokens per dispatch, eos/max_new retirement applied
by an on-device run mask INSIDE the scan — and the emitted tokens are
BIT-IDENTICAL to decode_steps=1 and to the per-request
`lm_generate(use_cache=True)` oracle, across every sampling knob, eos
mid-window, prefix hits + COW, preempt/replay, chunked prefill
coexistence (mixed steps fall back to k=1 scheduling), and model-axis
sharding.  Dispatch accounting is exact (ceil((max_new-1)/k) scanned
flushes for an undisturbed request), the steady-state scan window stages
NOTHING from the host, and each (slot count, k) is exactly ONE compiled
scan signature at the `serving.scan_step` site.
"""

import math

import numpy as np
import pytest

import jax

import paddle_tpu.serving.engine as engine_mod
from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.obs.compile_watch import get_compile_watch
from paddle_tpu.serving import Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


@pytest.fixture(scope="module")
def tr():
    return _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")


def _prompts(lens, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, vocab, n).astype(np.int32) for n in lens]


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _sampled_reqs(vocab, seed=1, max_new=6):
    """The four sampling modes over mixed prompt lengths — the standard
    exactness matrix from test_serving, rebuilt fresh per run so rng keys
    never alias between the A and B engines."""
    prompts = _prompts((4, 9, 6, 11), vocab, seed=seed)
    knobs = [dict(),                                     # greedy
             dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9),
             dict(temperature=1.1)]                      # full sampling
    return [Request(i, p, max_new=max_new,
                    rng=jax.random.PRNGKey(100 + i), **kw)
            for i, (p, kw) in enumerate(zip(prompts, knobs))]


def _assert_equal_results(a: dict, b: dict, label: str):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]),
            err_msg=f"request {k!r} diverged: {label}")


# ---------------------------------------------------------------------------
# the bit-exactness matrix: scan == k=1 == oracle
# ---------------------------------------------------------------------------


def test_scan_matches_k1_and_oracle_across_sampling_knobs(tr):
    """All four sampling modes, more requests than slots: decode_steps=4
    emits exactly the decode_steps=1 tokens, which are exactly the
    lm_generate oracle — and the whole k=4 workload compiled ONE scan
    signature while actually running scanned flushes."""
    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=64)
    res_1 = base.run(_sampled_reqs(61))

    cw = get_compile_watch()
    sigs0 = cw.signature_count("serving.scan_step")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, decode_steps=4)
    reqs = _sampled_reqs(61)
    res_4 = eng.run(reqs)
    _assert_equal_results(res_1, res_4, "decode_steps=4 vs decode_steps=1")
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), np.asarray(res_4[r.req_id]),
            err_msg=f"request {r.req_id!r} diverged from the "
                    f"lm_generate(use_cache=True) oracle under scan")
    assert eng.n_scan_flushes > 0, "multi-step never actually engaged"
    assert eng.n_scan_steps == eng.decode_steps * eng.n_scan_flushes
    assert cw.signature_count("serving.scan_step") == sigs0 + 1, \
        "one (slot count, k) must be exactly ONE scanned program"
    assert eng._scan_step._cache_size() == 1     # the jit cache agrees
    assert eng._decode_step._cache_size() <= 1   # fallback: at most one
    eng.kv.check_reclaimed()


def test_eos_mid_window_retires_on_device(tr2=None):
    """eos landing MID-window: the on-device run mask freezes the slot at
    the same token the host banking rule cuts at, later scan iterations
    write only garbage that is never read, and the freed slot refills —
    outputs stay exact and at least one request genuinely stops early."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5, 3, 6, 4), 11, seed=3)
    t0, _ = lm_generate(tr.executor, tr.params, prompts[0][None, :],
                        max_new=1, use_cache=True)
    eos = int(np.asarray(t0)[0, prompts[0].size])
    mk = lambda: [Request(i, p, max_new=8, eos_id=eos)   # noqa: E731
                  for i, p in enumerate(prompts)]
    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=32)
    res_1 = base.run(mk())
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32, decode_steps=3)
    reqs = mk()
    res_3 = eng.run(reqs)
    _assert_equal_results(res_1, res_3, "eos mid-window")
    assert eng.n_scan_flushes > 0
    assert any(np.asarray(res_3[r.req_id]).size
               < r.prompt_ids.size + r.max_new for r in reqs), \
        "no request hit eos early — the mid-window case never ran"
    eng.kv.check_reclaimed()


def test_ceil_dispatch_count_single_request(tr):
    """The perf claim, assertable: one undisturbed greedy request that
    emits n tokens runs exactly ceil((n-1)/k) scanned flushes (token 0
    comes from the prefill boundary), each a full k-body scan."""
    k, max_new = 4, 10
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=32,
                        max_context=64, decode_steps=k)
    req = Request("solo", _prompts((5,), 61, seed=4)[0], max_new=max_new)
    out = eng.run([req])
    np.testing.assert_array_equal(_oracle(tr, req),
                                  np.asarray(out["solo"]))
    assert eng.n_scan_flushes == math.ceil((max_new - 1) / k)
    assert eng.n_scan_steps == k * eng.n_scan_flushes
    # every scanned flush counts ONCE as a decode-advancing dispatch;
    # the +1 is the final-chunk prefill step that emitted token 0
    assert eng.n_decode_steps == eng.n_scan_flushes + 1


# ---------------------------------------------------------------------------
# staging discipline: the scan window is device-resident
# ---------------------------------------------------------------------------


class _CountingJnp:
    """Proxy for the engine module's `jnp` binding (the
    test_engine_state idiom): counts asarray calls — the host->device
    staging primitive — while delegating everything else."""

    def __init__(self, real):
        self._real = real
        self.asarray_calls = 0

    def asarray(self, *a, **kw):
        self.asarray_calls += 1
        return self._real.asarray(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_steady_scan_flushes_restage_nothing(monkeypatch):
    """Across a window of scanned flushes with no admission/retire/page
    boundary, the engine performs ZERO host->device transfers — both by
    its own `n_host_stages` counter and by the jnp.asarray proxy.  The
    [k, S] token block readback is device->host and free of staging."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=3, page_size=32,
                        max_context=64, decode_steps=3)
    for i, p in enumerate(_prompts((4, 4, 4), 31, seed=1)):
        eng.add_request(Request(i, p, max_new=20))
    # admit + commit every prompt, then one settling scanned flush so the
    # run mask, eos/max_new operands and slot arrays are staged + cached
    while not all(sl is not None and sl.gen >= 1 for sl in eng.slots):
        assert eng.step()
    assert eng.step()
    assert eng.n_scan_flushes >= 1, "settling step was not a scan flush"

    proxy = _CountingJnp(engine_mod.jnp)
    monkeypatch.setattr(engine_mod, "jnp", proxy)
    stages0, flushes0 = eng.n_host_stages, eng.n_scan_flushes
    for _ in range(3):
        assert eng.step()
    assert eng.n_scan_flushes == flushes0 + 3
    assert eng.n_host_stages == stages0, \
        "steady scanned flushes re-staged host arrays (pos/keys/knobs/" \
        "eos/max_new/table must live on device between boundaries)"
    assert proxy.asarray_calls == 0, \
        "a staging path bypassed the engine's _stage chokepoint"
    monkeypatch.undo()
    results = eng.run()
    assert len(results) == 3
    eng.kv.check_reclaimed()


def test_one_scan_signature_per_k(tr):
    """Each distinct k is ONE scanned program: a k=3 workload then a k=2
    workload on the same engine adds exactly two signatures at the
    serving.scan_step site, and re-running k=3 adds none."""
    cw = get_compile_watch()
    sigs0 = cw.signature_count("serving.scan_step")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, decode_steps=3)
    eng.run(_sampled_reqs(61, seed=5))
    assert cw.signature_count("serving.scan_step") == sigs0 + 1
    eng.set_decode_steps(2)              # idle: boundary by construction
    eng.run(_sampled_reqs(61, seed=6))
    assert cw.signature_count("serving.scan_step") == sigs0 + 2
    eng.set_decode_steps(3)              # back: cached, no new program
    eng.run(_sampled_reqs(61, seed=7))
    assert cw.signature_count("serving.scan_step") == sigs0 + 2
    assert eng._scan_step._cache_size() == 2     # k=3 and k=2, nothing else
    assert eng._decode_step._cache_size() <= 1   # fallback: at most one


def test_set_decode_steps_guards(tr):
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32)
    with pytest.raises(ValueError, match="decode_steps"):
        eng.set_decode_steps(0)
    eng.add_request(Request("x", np.asarray([3, 4, 5], np.int32),
                            max_new=4))
    with pytest.raises(AssertionError, match="idle"):
        eng.set_decode_steps(4)


# ---------------------------------------------------------------------------
# the hard scheduling boundaries: sharing, preemption, chunked prefill
# ---------------------------------------------------------------------------


def test_prefix_hits_and_cow_stay_exact_under_scan():
    """Prefix-cache hits map committed pages read-only into a scanning
    slot; the window tripwire + COW keep every scanned write on private
    pages — outputs bit-match the k=1 engine and the cold oracle."""
    tr = _make("vocab=23,dim=16,layers=2,heads=2,batch_size=4")
    rng = np.random.default_rng(0)
    system = rng.integers(2, 23, 19).astype(np.int32)   # spans 2+ pages

    def mk_reqs():
        knobs = [dict(), dict(temperature=0.8, top_k=5),
                 dict(temperature=0.7, top_p=0.9), dict(temperature=1.1)]
        r2 = np.random.default_rng(1)
        return [Request(f"r{i}",
                        np.concatenate([system,
                                        r2.integers(2, 23, 3 + i)
                                        .astype(np.int32)]),
                        max_new=5, rng=jax.random.PRNGKey(40 + i), **kw)
                for i, kw in enumerate(knobs)]

    def run(decode_steps):
        eng = ServingEngine(tr.executor, tr.params, num_slots=2,
                            page_size=8, max_context=64,
                            decode_steps=decode_steps)
        results = {}
        for r in mk_reqs():               # sequential: later requests
            results.update(eng.run([r]))  # prefix-hit earlier donations
        return eng, results

    eng1, res_1 = run(1)
    eng3, res_3 = run(3)
    _assert_equal_results(res_1, res_3, "prefix hits under scan")
    for r in mk_reqs():
        np.testing.assert_array_equal(_oracle(tr, r),
                                      np.asarray(res_3[r.req_id]))
    assert eng3.n_prefix_hits >= 3 and eng3.n_scan_flushes > 0
    eng3.kv.check_reclaimed()


def test_preempt_replay_at_boundaries_stays_exact():
    """An overcommitted pool preempts between flushes (scheduling only
    ever happens at scan boundaries); the deterministic keys[s, gen]
    schedule makes the replay invisible — k=3 output equals k=1 equals
    the oracle, and every page returns to the free list."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5, 3, 6), 11, seed=3)
    mk = lambda: [Request(i, p, max_new=8)               # noqa: E731
                  for i, p in enumerate(prompts)]
    # 2 slots x 4 pages would want 8; give 6 (incl. trash page 0)
    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                         max_context=16, num_pages=6)
    res_1 = base.run(mk())
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=6, decode_steps=3)
    reqs = mk()
    res_3 = eng.run(reqs)
    _assert_equal_results(res_1, res_3, "preempt/replay under scan")
    for r in reqs:
        np.testing.assert_array_equal(_oracle(tr, r),
                                      np.asarray(res_3[r.req_id]))
    assert eng.n_preemptions > 0, "pool was never actually overcommitted"
    eng.kv.check_reclaimed()


def test_chunked_prefill_coexists_mixed_steps_fall_back(tr):
    """A long prompt chunk-prefilling beside decoders: those dispatches
    are MIXED steps (never scanned); once every live slot is pure-decode
    the scan re-engages — both counters advance and outputs stay exact
    against the k=1 engine and the oracle."""
    def mk_reqs():
        prompts = _prompts((30, 5, 9), 61, seed=8)
        return [Request(i, p, max_new=6,
                        rng=jax.random.PRNGKey(200 + i),
                        **({"temperature": 0.8, "top_k": 5} if i == 1
                           else {}))
                for i, p in enumerate(prompts)]

    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=64, prefill_chunk=8)
    res_1 = base.run(mk_reqs())
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, prefill_chunk=8, decode_steps=4)
    reqs = mk_reqs()
    res_4 = eng.run(reqs)
    _assert_equal_results(res_1, res_4, "chunked prefill + scan")
    for r in reqs:
        np.testing.assert_array_equal(_oracle(tr, r),
                                      np.asarray(res_4[r.req_id]))
    assert eng.n_mixed_steps > 0, "the chunked prompt never mixed-stepped"
    assert eng.n_scan_flushes > 0, "scan never re-engaged after prefill"
    eng.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# checkpoint/restore: flush boundaries are checkpoint boundaries
# ---------------------------------------------------------------------------


def test_save_restore_at_scan_boundary_cross_k(tmp_path, tr):
    """A snapshot taken mid-flight under k=3 restores onto a fresh k=1
    engine AND a fresh k=5 engine (decode_steps is an A/B knob, not
    engine shape — deliberately excluded from the config match) and both
    finish bit-exactly what the uninterrupted k=3 engine produces."""
    def mk_engine(k):
        return ServingEngine(tr.executor, tr.params, num_slots=2,
                             page_size=8, max_context=64,
                             decode_steps=k)

    eng_a = mk_engine(3)
    for r in _sampled_reqs(61, seed=9, max_new=8):
        eng_a.add_request(r)
    # drive to a mid-flight point where scanning has actually happened
    for _ in range(200):
        if eng_a.n_scan_flushes >= 2 and any(
                sl is not None and sl.gen >= 1 for sl in eng_a.slots):
            break
        assert eng_a.step()
    assert eng_a.n_scan_flushes >= 2, "never reached a scanned state"
    path = str(tmp_path / "scan_state.pkl")
    eng_a.save_state(path)
    while eng_a.step():
        pass
    res_a = {k: np.asarray(v) for k, v in eng_a.results.items()}

    for k_restore in (1, 5):
        eng_b = mk_engine(k_restore)
        eng_b.load_state(path)
        while eng_b.step():
            pass
        res_b = {k: np.asarray(v) for k, v in eng_b.results.items()}
        _assert_equal_results(res_a, res_b,
                              f"restore onto decode_steps={k_restore}")
        eng_b.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# model-axis sharding
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs >= 2 devices (conftest provides 8)")
def test_scan_matches_under_model_parallel():
    """`--mesh model=2` + decode_steps=4: the scanned step runs under the
    same shard_map as the k=1 step (the scan body appears once in the
    program, collectives and all) and the token streams are identical to
    the single-device k=1 engine."""
    from paddle_tpu.parallel.mesh import model_mesh
    tr = _make("vocab=64,dim=32,layers=2,heads=4,batch_size=4")
    tr.executor.mesh = None
    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=64)
    res_1 = base.run(_sampled_reqs(64, seed=11))
    tr.executor.mesh = None
    eng = ServingEngine(tr.executor, tr.params, mesh=model_mesh(2),
                        num_slots=2, page_size=8, max_context=64,
                        decode_steps=4)
    res_tp = eng.run(_sampled_reqs(64, seed=11))
    _assert_equal_results(res_1, res_tp, "model=2 scanned decode")
    assert eng.n_scan_flushes > 0
    eng.kv.check_reclaimed()
    tr.executor.mesh = None


# ---------------------------------------------------------------------------
# decode_mode=auto (PR 18): speculation and the scan COMPOSE per window
# ---------------------------------------------------------------------------


def test_auto_mode_composes_spec_and_scan(tr):
    """With spec_k > 0 AND decode_steps > 1 under decode_mode=auto, the
    per-window policy routes drafted windows through the verify step and
    draft-free pure-decode windows through the scan — BOTH counters
    advance in one run, tokens stay bit-exact against the plain engine
    and the oracle, and the composition mints no extra scan or verify
    signatures (one of each)."""
    prompt = _prompts((10,), 61, seed=11)[0]

    def mk_req():
        return Request("c", prompt.copy(), max_new=20)

    full = _oracle(tr, mk_req())

    class ParityReplay:
        """Deterministic in ctx: replays the greedy continuation when the
        context length is even, proposes nothing when odd — so the engine
        alternates between verified chains and draft-free scan windows."""

        def propose(self, ctx, k):
            n = ctx.size
            if n % 2 == 0 and n < full.size and \
                    np.array_equal(full[:n], ctx):
                return full[n:n + k].astype(np.int32)
            return np.zeros(0, np.int32)

    base = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=64)
    res_plain = base.run([mk_req()])
    cw = get_compile_watch()
    scan0 = cw.signature_count("serving.scan_step")
    spec0 = cw.signature_count("serving.spec_step")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, spec_k=2, decode_steps=3,
                        decode_mode="auto", drafter=ParityReplay())
    res = eng.run([mk_req()])
    _assert_equal_results(res_plain, res, "auto spec x scan vs plain")
    np.testing.assert_array_equal(full, np.asarray(res["c"]))
    assert eng.n_spec_steps > 0, "no window ever took the verify step"
    assert eng.n_scan_flushes > 0, \
        "no draft-free window ever scanned — spec_k > 0 must not " \
        "disable multi-step under decode_mode=auto"
    assert eng.n_spec_accepted > 0, "the replay chains never accepted"
    # per-engine: ONE scan program and ONE verify program carried the
    # whole composed run.  (The compile-watch site counts are global
    # and dedup identical signatures across tests, so they bound the
    # delta at <= 1 rather than == 1.)
    assert eng._scan_step._cache_size() == 1
    assert eng._spec_step._cache_size() == 1
    assert cw.signature_count("serving.scan_step") <= scan0 + 1
    assert cw.signature_count("serving.spec_step") <= spec0 + 1, \
        "composition minted extra verify signatures"
    eng.kv.check_reclaimed()


def test_static_mode_keeps_legacy_exclusivity(tr):
    """decode_mode=static restores the old behavior: spec_k > 0 disables
    the scan entirely (the A/B control arm), with identical tokens."""
    prompt = _prompts((8,), 61, seed=12)[0]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, spec_k=2, decode_steps=3,
                        decode_mode="static")
    res = eng.run([Request("s", prompt.copy(), max_new=12)])
    np.testing.assert_array_equal(
        _oracle(tr, Request("s", prompt.copy(), max_new=12)),
        np.asarray(res["s"]))
    assert eng.n_scan_flushes == 0, \
        "static mode must keep the spec-xor-scan exclusivity"
    # the idle toggle flips the policy without rebuilding the engine
    eng.set_decode_mode("auto")
    assert eng.decode_mode == "auto"
    with pytest.raises(ValueError, match="decode_mode"):
        eng.set_decode_mode("sometimes")


def test_admission_never_stalls_behind_scan(tr):
    """The adaptive fallback regression (PR 18 satellite): a request
    admitted MID-FLIGHT while the engine is in scanned steady state must
    start chunk-prefilling on the very next dispatch — the window falls
    back to mixed/verify scheduling instead of making the prompt wait
    out k-step scan windows.  Checked with speculation on (auto mode)
    AND off: no scan flush may occur while a prompt is mid-prefill."""
    for spec_k in (0, 2):
        eng = ServingEngine(tr.executor, tr.params, num_slots=2,
                            page_size=8, max_context=64, prefill_chunk=8,
                            decode_steps=4, decode_mode="auto",
                            spec_k=spec_k)
        short, long_ = _prompts((5, 30), 61, seed=13)
        eng.add_request(Request("short", short, max_new=24))
        # reach scanned steady state before the mid-flight admission
        while eng.n_scan_flushes == 0:
            assert eng.step(), "never reached the scan steady state"
        eng.add_request(Request("long", long_, max_new=4))
        chunks0, flushes0 = eng.n_prefill_chunks, eng.n_scan_flushes
        eng.step()
        assert eng.n_prefill_chunks > chunks0, \
            f"spec_k={spec_k}: the admitted prompt's first chunk did " \
            f"not ride the NEXT dispatch after admission"
        while any(sl is not None and sl.gen == 0
                  for sl in eng.slots if sl is not None):
            assert eng.n_scan_flushes == flushes0, \
                f"spec_k={spec_k}: a k-step scan ran while a prompt " \
                f"was mid-prefill (admission stalled behind the scan)"
            eng.step()
        res = eng.run()
        for r in (Request("short", short.copy(), max_new=24),
                  Request("long", long_.copy(), max_new=4)):
            np.testing.assert_array_equal(
                _oracle(tr, r), np.asarray(res[r.req_id]),
                err_msg=f"spec_k={spec_k}: {r.req_id} diverged")
        eng.kv.check_reclaimed()
