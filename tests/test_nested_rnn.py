"""Nested-vs-flat recurrent group equivalence — the reference's hierarchical
RNN oracle (ref: paddle/gserver/tests/test_RecurrentGradientMachine.cpp
test_reversed_grnn / CalCost over sequence_nest_rnn.conf vs sequence_rnn.conf;
RecurrentGradientMachine.cpp:626-699): a hierarchical RNN whose inner memory
boots from the outer carry must compute exactly what the flat RNN computes on
the concatenated token stream — same cost, same gradients."""

import os
import sys

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.data.feeder import make_batch
from paddle_tpu.data.provider import (integer_value,
                                      integer_value_sequence,
                                      integer_value_sub_sequence)
from paddle_tpu.graph.builder import GraphExecutor
import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


NEST_CFG = os.path.join(REPO, "tests/configs/sequence_nest_rnn.py")
FLAT_CFG = os.path.join(REPO, "tests/configs/sequence_rnn.py")

# the reference's rnn_data_provider data: (subsequences, label)
DATA = [
    [[[1, 3, 2], [4, 5, 2]], 0],
    [[[0, 2], [2, 5], [0, 1, 2]], 1],
]


def _nested_batch():
    samples = [(d[0], d[1]) for d in DATA]
    return make_batch(samples,
                      [integer_value_sub_sequence(10), integer_value(3)],
                      ["word", "label"])


def _flat_batch():
    samples = [([t for ss in d[0] for t in ss], d[1]) for d in DATA]
    return make_batch(samples,
                      [integer_value_sequence(10), integer_value(3)],
                      ["word", "label"])


def _loss_and_grads(cfg_path, batch):
    cfg = parse_config(cfg_path, "")
    ex = GraphExecutor(cfg.model_config)
    params = ex.init_params(jax.random.PRNGKey(7))

    def loss_fn(p):
        loss, _ = ex.loss(p, batch)
        return loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return float(loss), params, grads


def _assert_nested_matches_flat(nested_cfg, flat_cfg):
    """The equivalence oracle: identical parameter sets (same shapes, same
    declaration order, same seed => same values; names legitimately
    differ), identical loss, identical gradients."""
    nl, nparams, ngrads = _loss_and_grads(nested_cfg, _nested_batch())
    fl, fparams, fgrads = _loss_and_grads(flat_cfg, _flat_batch())

    nkeys, fkeys = list(nparams), list(fparams)
    assert len(nkeys) == len(fkeys)
    for nk, fk in zip(nkeys, fkeys):
        np.testing.assert_array_equal(np.asarray(nparams[nk]),
                                      np.asarray(fparams[fk]))
    assert abs(nl - fl) < 1e-5, (nl, fl)
    for nk, fk in zip(nkeys, fkeys):
        np.testing.assert_allclose(np.asarray(ngrads[nk]),
                                   np.asarray(fgrads[fk]),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"{nk} vs {fk}")


def test_nested_matches_flat():
    _assert_nested_matches_flat(NEST_CFG, FLAT_CFG)


def test_nested_multi_input_matches_flat():
    """Two nested in-links (ids + embeddings), inner step embeds its id
    slice (ref: sequence_nest_rnn_multi_input.conf vs
    sequence_rnn_multi_input.conf)."""
    _assert_nested_matches_flat(
        os.path.join(REPO, "tests/configs/sequence_nest_rnn_multi_input.py"),
        os.path.join(REPO, "tests/configs/sequence_rnn_multi_input.py"))


def test_nested_pooling_ops():
    """Nested pooling equals flat pooling over the concatenated tokens."""
    import jax.numpy as jnp

    from paddle_tpu.ops import sequence as seqops

    rng = np.random.default_rng(0)
    B, S, T, D = 2, 3, 4, 5
    x = rng.normal(size=(B, S, T, D)).astype(np.float32)
    # sample 0 ends with an EMPTY valid subsequence (last/first must skip it)
    lengths = np.asarray([3, 3], np.int32)            # valid subseqs
    sub_lengths = np.asarray([[3, 2, 0], [1, 4, 2]], np.int32)

    def flat(b):
        toks = [x[b, s, t] for s in range(lengths[b])
                for t in range(sub_lengths[b, s])]
        return np.stack(toks)

    got_last = np.asarray(seqops.nested_pool_last(
        jnp.asarray(x), jnp.asarray(lengths), jnp.asarray(sub_lengths)))
    got_first = np.asarray(seqops.nested_pool_first(
        jnp.asarray(x), jnp.asarray(lengths), jnp.asarray(sub_lengths)))
    got_max = np.asarray(seqops.nested_pool_max(
        jnp.asarray(x), jnp.asarray(lengths), jnp.asarray(sub_lengths)))
    got_avg = np.asarray(seqops.nested_pool_avg(
        jnp.asarray(x), jnp.asarray(lengths), jnp.asarray(sub_lengths)))
    for b in range(B):
        f = flat(b)
        np.testing.assert_allclose(got_last[b], f[-1], rtol=1e-6)
        np.testing.assert_allclose(got_first[b], f[0], rtol=1e-6)
        np.testing.assert_allclose(got_max[b], f.max(0), rtol=1e-6)
        np.testing.assert_allclose(got_avg[b], f.mean(0), rtol=1e-5)
