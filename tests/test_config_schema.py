"""Schema round-trip tests (ref test analog: config serialization goldens in
python/paddle/trainer_config_helpers/tests)."""

from paddle_tpu.config.schema import (
    ConvConfig, LayerConfig, LayerInput, ModelConfig, OptimizationConfig,
    ParameterConfig, ProjectionConfig, SubModelConfig, TrainerConfig,
)


def test_roundtrip_simple():
    m = ModelConfig(
        layers=[
            LayerConfig(name="in", type="data", size=10),
            LayerConfig(name="fc", type="fc", size=4, active_type="softmax",
                        inputs=[LayerInput(input_layer_name="in",
                                           input_parameter_name="_fc.w0")],
                        bias_parameter_name="_fc.wbias"),
        ],
        parameters=[
            ParameterConfig(name="_fc.w0", size=40, dims=[10, 4]),
            ParameterConfig(name="_fc.wbias", size=4, dims=[1, 4],
                            initial_strategy="zero"),
        ],
        input_layer_names=["in"],
    )
    tc = TrainerConfig(model_config=m, opt_config=OptimizationConfig(batch_size=32))
    js = tc.to_json()
    back = TrainerConfig.from_json(js)
    assert back.model_config.layer("fc").active_type == "softmax"
    assert back.model_config.parameter("_fc.w0").dims == [10, 4]
    assert back.opt_config.batch_size == 32
    assert back.to_json() == js


def test_roundtrip_nested():
    conv = ConvConfig(filter_size=3, channels=8, img_size=32, output_x=30)
    lc = LayerConfig(name="c", type="exconv", size=100, conv=conv,
                     inputs=[LayerInput(input_layer_name="in",
                                        proj=ProjectionConfig(type="conv", conv=conv))])
    m = ModelConfig(layers=[lc], sub_models=[
        SubModelConfig(name="g", is_recurrent_layer_group=True,
                       layer_names=["c"], in_links=["x"])])
    back = ModelConfig.from_json(m.to_json())
    assert back.layers[0].conv.filter_size == 3
    assert back.layers[0].inputs[0].proj.conv.channels == 8
    assert back.sub_models[0].in_links == ["x"]
