"""Evaluator zoo tests — hand-computed oracles per metric
(mirrors ref: gserver/tests/test_Evaluator.cpp strategy of feeding known
arguments and checking the statistic)."""

import numpy as np
import pytest

from paddle_tpu.config.schema import EvaluatorConfig
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.evaluators import (
    EvaluatorSet, host_evaluator_registry, _chunk_segments, _edit_distance,
    _ctc_collapse, _rank_auc_one,
)


def run_host(type_, args, **cfg_kw):
    cfg = EvaluatorConfig(name="e", type=type_, **cfg_kw)
    new, batch, final = host_evaluator_registry[type_]
    state = new()
    batch(cfg, args, state)
    return final(cfg, state)


# -- chunk ------------------------------------------------------------------

def test_chunk_segments_iob():
    # IOB, 2 chunk types: labels B-0=0 I-0=1 B-1=2 I-1=3 O=4
    labels = np.array([0, 1, 4, 2, 3, 3, 0])
    segs = _chunk_segments(labels, "IOB", 2)
    assert segs == [(0, 1, 0), (3, 5, 1), (6, 6, 0)]


def test_chunk_segments_iobes():
    # IOBES, 1 type: B=0 I=1 E=2 S=3 O=4
    labels = np.array([0, 1, 2, 4, 3])
    segs = _chunk_segments(labels, "IOBES", 1)
    assert segs == [(0, 2, 0), (4, 4, 0)]


def test_chunk_f1():
    # one sequence: predicted has 2 segments, gold has 2, 1 correct
    out = Argument(ids=np.array([[0, 1, 4, 0, 4]]), lengths=np.array([5]))
    lbl = Argument(ids=np.array([[0, 1, 4, 4, 0]]), lengths=np.array([5]))
    res = run_host("chunk", [out, lbl], chunk_scheme="IOB", num_chunk_types=2)
    assert res["correct_chunks"] == 1
    assert res["result_chunks"] == 2 and res["true_chunks"] == 2
    assert res["chunk_f1"] == pytest.approx(0.5)


# -- ctc edit distance ------------------------------------------------------

def test_ctc_collapse():
    assert _ctc_collapse([1, 1, 9, 1, 2, 9, 9, 3], blank=9) == [1, 1, 2, 3]


def test_edit_distance():
    assert _edit_distance([1, 2, 3], [1, 2, 3]) == 0
    assert _edit_distance([1, 2, 3], [1, 3]) == 1
    assert _edit_distance([], [1, 2]) == 2
    assert _edit_distance([1, 2], [2, 1]) == 2


def test_ctc_error_evaluator():
    # 3 classes + blank (=3); T=4; argmax path [0,3,1,1] -> collapse [0,1]
    acts = np.zeros((1, 4, 4), np.float32)
    acts[0, 0, 0] = 1; acts[0, 1, 3] = 1; acts[0, 2, 1] = 1; acts[0, 3, 1] = 1
    out = Argument(value=acts, lengths=np.array([4]))
    lbl = Argument(ids=np.array([[0, 1]]), lengths=np.array([2]))
    res = run_host("ctc_edit_distance", [out, lbl])
    assert res["ctc_edit_distance"] == 0.0
    assert res["sequence_error_rate"] == 0.0


# -- pnpair -----------------------------------------------------------------

def test_pnpair():
    # query 0: scores (.9,l=1) (.1,l=0) -> concordant; query 1: (.2,l=1) (.8,l=0) -> discordant
    out = Argument(value=np.array([[.9], [.1], [.2], [.8]], np.float32))
    lbl = Argument(ids=np.array([1, 0, 1, 0]))
    info = Argument(ids=np.array([0, 0, 1, 1]))
    res = run_host("pnpair", [out, lbl, info])
    assert res["pos_pairs"] == pytest.approx(1.0)
    assert res["neg_pairs"] == pytest.approx(1.0)


# -- rankauc ----------------------------------------------------------------

def test_rank_auc_perfect():
    scores = np.array([.9, .5, .1])
    clicks = np.array([1.0, 0.0, 0.0])
    pvs = np.ones(3)
    assert _rank_auc_one(scores, clicks, pvs) == pytest.approx(1.0)


def test_rank_auc_random():
    # reversed ranking -> AUC 0
    scores = np.array([.1, .5, .9])
    clicks = np.array([1.0, 0.0, 0.0])
    assert _rank_auc_one(scores, clicks, np.ones(3)) == pytest.approx(0.0)


def test_rankauc_evaluator_sequences():
    out = Argument(value=np.array([[[.9], [.1], [.5]]], np.float32),
                   lengths=np.array([3]))
    click = Argument(value=np.array([[[1.], [0.], [0.]]], np.float32),
                     lengths=np.array([3]))
    res = run_host("rankauc", [out, click])
    assert res["rankauc"] == pytest.approx(1.0)


# -- seq classification error ----------------------------------------------

def test_seq_classification_error():
    # seq 0 fully right, seq 1 has one wrong frame
    pred = np.zeros((2, 3, 2), np.float32)
    pred[0, :, 1] = 1         # predicts 1,1,1
    pred[1, :, 0] = 1         # predicts 0,0,0
    out = Argument(value=pred, lengths=np.array([3, 3]))
    lbl = Argument(ids=np.array([[1, 1, 1], [0, 1, 0]]), lengths=np.array([3, 3]))
    res = run_host("seq_classification_error", [out, lbl])
    assert res["seq_classification_error"] == pytest.approx(0.5)


# -- integration through the trainer ---------------------------------------

def test_gradient_printer_probe_grad_is_output_grad():
    """gradient_printer (ref: Evaluator.cpp GradientPrinter) receives the
    probed layer's OUTPUT gradient: square_error is the reference's
    0.5*|o-y|^2 (ref: CostLayer.cpp SumOfSquaresCostLayer), so for
    loss = mean_b 0.5(o_b - y_b)^2, dL/do = (o - y)/B — the additive-zero
    probe must reproduce it exactly."""
    import numpy as np
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.trainer.trainer import Trainer
    import jax

    def conf():
        from paddle_tpu.dsl import (
            LinearActivation, MomentumOptimizer, data_layer, fc_layer,
            gradient_printer_evaluator, regression_cost, settings,
        )
        settings(batch_size=4, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.0))
        x = data_layer(name="x", size=8)
        out = fc_layer(input=x, size=1, act=LinearActivation(), name="out")
        gradient_printer_evaluator(input=out)
        regression_cost(input=out, label=data_layer(name="y", size=1))

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=0)
    assert tr._probe_names == ["out"]
    rng = np.random.default_rng(0)
    B = 4
    x = rng.random((B, 8), np.float32)
    y = rng.random((B, 1), np.float32)
    batch = {"x": Argument(value=x), "y": Argument(value=y)}

    # run the (uncompiled) step fn to inspect host_out directly
    _, _, _, loss, _, host_out = tr._train_step_fn(
        tr.params, tr.opt_state, {}, batch, jax.random.PRNGKey(0))
    g = np.asarray(host_out["__grad__out"].value)
    o = x @ np.asarray(tr.params["_out.w0"]) + np.asarray(tr.params["_out.wbias"])
    np.testing.assert_allclose(g, (o - y) / B, rtol=1e-5, atol=1e-6)

    # and through the real compiled path the host printer consumes it
    tr.train_one_batch(batch)
    assert tr._host_acc is not None


def test_maxframe_printer():
    """max_frame_printer (ref: Evaluator.cpp MaxFramePrinter) renders each
    sequence's value-maximizing frame."""
    import numpy as np
    from paddle_tpu.config.schema import EvaluatorConfig
    from paddle_tpu.trainer.evaluators import host_evaluator_registry

    new_state, batch_fn, final = host_evaluator_registry["max_frame_printer"]
    v = np.zeros((2, 4, 3), np.float32)
    v[0, 2, 1] = 5.0      # seq 0 peaks at frame 2
    v[1, 0, 0] = 3.0      # seq 1 peaks at frame 0 (within length 2)
    arg = Argument(value=v, lengths=np.asarray([4, 2], np.int32))
    cfg = EvaluatorConfig(name="mf", type="max_frame_printer",
                          input_layer_names=["l"])
    st = new_state()
    batch_fn(cfg, [arg], st)          # logs; must not raise
    assert st["printed"] == 1
    from paddle_tpu.trainer.evaluators import _max_frame_print
    txt = _max_frame_print(cfg, [arg])
    assert "seq 0: frame 2" in txt and "seq 1: frame 0" in txt


def test_host_evaluator_in_trainer():
    """chunk evaluator wired through a real jitted training step."""
    import numpy as np
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        from paddle_tpu.dsl import (
            AdamOptimizer, chunk_evaluator, classification_cost, data_layer,
            fc_layer, settings, SoftmaxActivation,
        )
        settings(batch_size=4, learning_rate=0.01,
                 learning_method=AdamOptimizer())
        x = data_layer(name="x", size=8)
        out = fc_layer(input=x, size=5, act=SoftmaxActivation())
        lbl = data_layer(name="label", size=5)
        classification_cost(input=out, label=lbl)
        # chunk over maxid of out vs label (as plain scalar "sequences")
        from paddle_tpu.dsl import maxid_layer
        mid = maxid_layer(input=out)
        chunk_evaluator(input=mid, label=lbl, chunk_scheme="IOB",
                        num_chunk_types=2)

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=0)
    assert tr.evaluators.host_configs, "chunk should register as host evaluator"
    rng = np.random.default_rng(0)
    batch = {"x": Argument(value=rng.random((4, 8), np.float32)),
             "label": Argument(ids=rng.integers(0, 5, 4).astype(np.int32))}
    loss = tr.train_one_batch(batch)
    assert np.isfinite(loss)
    stats = tr.evaluators.finalize_host(tr._host_acc)
    assert any("chunk" in k or "true_chunks" in k for k in stats)


# -- last-column-auc --------------------------------------------------------

def test_auc_uses_last_column_and_weight():
    """ref: Evaluator.cpp:857 creates AucEvaluator(-1): score is always the
    LAST output column; optional 3rd input is a per-sample weight."""
    import jax.numpy as jnp
    from paddle_tpu.trainer.evaluators import evaluator_registry

    batch, final = evaluator_registry["last-column-auc"]
    # 3-column output; only the last column separates the classes
    out = Argument(value=jnp.array([[.5, .2, .9], [.5, .2, .1],
                                    [.5, .2, .95]], jnp.float32))
    lbl = Argument(ids=jnp.array([1, 0, 0]))
    w_zero_bad = Argument(value=jnp.array([[1.], [1.], [0.]], jnp.float32))

    cfg2 = EvaluatorConfig(name="a", type="last-column-auc",
                           input_layer_names=["o", "l"])
    res = batch(cfg2, {"o": out, "l": lbl}, {})
    auc = final(cfg2, {k: np.asarray(v) for k, v in res.items()})["auc"]
    assert auc == pytest.approx(0.5)   # one concordant, one discordant pair

    cfg3 = EvaluatorConfig(name="a", type="last-column-auc",
                           input_layer_names=["o", "l", "w"])
    res = batch(cfg3, {"o": out, "l": lbl, "w": w_zero_bad}, {})
    auc = final(cfg3, {k: np.asarray(v) for k, v in res.items()})["auc"]
    assert auc == pytest.approx(1.0)   # the discordant sample has weight 0
