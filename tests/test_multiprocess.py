"""REAL multi-process distributed training over jax.distributed — the
cluster path that single-process virtual-mesh tests cannot exercise
(ref: the pserver fleet's multi-trainer sync-SGD protocol,
paddle/pserver/ParameterServer2.h synchronizeBarriers_; here the
coordinator bootstrap + gloo CPU collectives stand in for ICI/DCN).

Two subprocesses each boot via init_distributed, feed DIFFERENT local
batch shards (per-host data-parallel input), and train over one global
data-parallel mesh.  The step loss is computed from the global batch and
must agree bit-for-bit across processes; the BarrierStat straggler table
must allgather.  This validates the multi-process placement paths
(make_array_from_process_local_data for batches,
make_array_from_callback for replicated/sharded params) that device_put
alone cannot serve."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_data_parallel_training():
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)          # 1 CPU device per process
    env["PYTHONPATH"] = ""              # keep the axon plugin out

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, f"localhost:{port}", "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # one worker dying pre-rendezvous leaves the other blocked in
        # jax.distributed.initialize — never leak it past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    def losses_of(out):
        for ln in out.splitlines():
            if "losses=" in ln:
                return ln.split("losses=")[1].strip()
        raise AssertionError(f"no losses line:\n{out}")

    l0, l1 = losses_of(outs[0]), losses_of(outs[1])
    assert l0 == l1, f"process losses diverged:\n{l0}\n{l1}"
    assert all("straggler_ok" in o for o in outs)
