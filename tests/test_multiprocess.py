"""REAL multi-process distributed training over jax.distributed — the
cluster path that single-process virtual-mesh tests cannot exercise
(ref: the pserver fleet's multi-trainer sync-SGD protocol,
paddle/pserver/ParameterServer2.h synchronizeBarriers_; here the
coordinator bootstrap + gloo CPU collectives stand in for ICI/DCN).

Two subprocesses each boot via init_distributed, feed DIFFERENT local
batch shards (per-host data-parallel input), and train over one global
data-parallel mesh.  The step loss is computed from the global batch and
must agree bit-for-bit across processes; the BarrierStat straggler table
must allgather.  This validates the multi-process placement paths
(make_array_from_process_local_data for batches,
make_array_from_callback for replicated/sharded params) that device_put
alone cannot serve."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]

def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)          # 1 CPU device per process
    env["PYTHONPATH"] = ""              # keep the axon plugin out
    return env


def _run_workers(n: int, mode: str = "dp", timeout: float = 300):
    """Launch n distributed_worker.py processes, return their outputs;
    kills survivors (one worker dying pre-rendezvous leaves the others
    blocked in jax.distributed.initialize)."""
    port = _free_port()
    args_tail = [mode] if mode != "dp" else []
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, f"localhost:{port}", str(n), str(i)]
            + args_tail,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_worker_env(), cwd=REPO)
        for i in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    return outs


def _losses_of(out: str) -> str:
    for ln in out.splitlines():
        if "losses=" in ln:
            return ln.split("losses=")[1].strip()
    raise AssertionError(f"no losses line:\n{out}")


def _oracle_conf(n_rows=2):
    """The exact model distributed_worker.py trains in dp mode (tp
    annotations in tpdp mode are placement-only, so this oracle serves
    both); batch_size mirrors the workers' 8*data_par."""
    def conf():
        from paddle_tpu.dsl import (MomentumOptimizer, SoftmaxActivation,
                                    TanhActivation, classification_cost,
                                    data_layer, fc_layer, settings)
        settings(batch_size=8 * n_rows, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=16)
        h = fc_layer(input=x, size=32, act=TanhActivation())
        out = fc_layer(input=h, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))
    return conf


def _oracle_losses(n_rows: int, steps: int = 4):
    """Single-process training on the concatenated global batches the
    workers fed (one stream per data row) — the test_CompareSparse
    equivalence bar."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    tr = Trainer(parse_config_callable(_oracle_conf(n_rows)), seed=7,
                 mesh=None)
    rngs = [np.random.default_rng(100 + row) for row in range(n_rows)]
    W = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    losses = []
    for _ in range(steps):
        xs, ys = [], []
        for r in rngs:
            x = r.normal(size=(8, 16)).astype(np.float32)
            xs.append(x)
            ys.append(np.argmax(x @ W, -1).astype(np.int32))
        loss = tr.train_one_batch({"x": Argument(value=np.concatenate(xs)),
                                   "y": Argument(ids=np.concatenate(ys))})
        losses.append(float(loss))
    return losses, tr


def _assert_matches_local(worker_out: str, tr):
    """Workers' printed final-param summaries must match the local-run
    oracle (ref: test_CompareSparse.cpp — multi-trainer == local)."""
    import re

    import jax
    import numpy as np
    dist_params = {m.group(1): (float(m.group(2)), float(m.group(3)))
                   for m in re.finditer(
                       r"param (\S+) sum=(\S+) asum=(\S+)", worker_out)}
    assert dist_params, "workers printed no param summaries"
    for name, v in tr.params.items():
        flat = np.asarray(jax.device_get(v)).ravel()
        sm, a = dist_params[name]
        np.testing.assert_allclose([flat.sum(), np.abs(flat).sum()], [sm, a],
                                   rtol=3e-4, atol=2e-5,
                                   err_msg=f"param {name!r} != local run")




def test_two_process_data_parallel_training():
    outs = _run_workers(2, timeout=240)
    l0, l1 = _losses_of(outs[0]), _losses_of(outs[1])
    assert l0 == l1, f"process losses diverged:\n{l0}\n{l1}"
    assert all("straggler_ok" in o for o in outs)

    # -- single-process equivalence oracle (ref: trainer/tests/
    #    test_CompareSparse.cpp:133-152 — multi-trainer training must equal
    #    local training)
    import numpy as np
    local_losses, tr = _oracle_losses(n_rows=2)
    dist_losses = [float(v) for v in l0.split(",")]
    np.testing.assert_allclose(dist_losses, local_losses, rtol=2e-4,
                               atol=1e-6,
                               err_msg="2-process losses != local training")

    _assert_matches_local(outs[0], tr)


def test_four_process_tp_by_dp_training():
    """4 REAL processes over a (data=2, model=2) mesh: tp-annotated weights
    shard ACROSS processes (1/2 per device), data rows shard over the other
    axis, and all 4 processes must agree bit-for-bit on every step loss.
    The 2-process test covers pure dp; this is the tp x dp cell of the
    multi-host matrix."""
    outs = _run_workers(4, mode="tpdp", timeout=300)
    ls = [_losses_of(o) for o in outs]
    assert len(set(ls)) == 1, "process losses diverged:\n" + "\n".join(ls)
    assert all("tp_shard_ok" in o for o in outs), \
        "tp params did not shard across processes"

    # single-process equivalence: same model (tp annotations are placement
    # only), same global batches, mesh=None
    import numpy as np
    local_losses, tr = _oracle_losses(n_rows=2)
    dist_losses = [float(v) for v in ls[0].split(",")]
    np.testing.assert_allclose(dist_losses, local_losses, rtol=2e-4,
                               atol=1e-6,
                               err_msg="tp x dp losses != local training")

    # final params too: a model-axis reconstruction bug (shards tiled in
    # the wrong order by _host_tree) shows up here, not in the losses
    _assert_matches_local(outs[0], tr)


def test_cluster_launch_local_integration(tmp_path):
    """NON-dry-run launcher test: cluster_launch --local starts 2 real
    trainer_main processes under jax.distributed on this machine (the
    submit_local.sh analog of the reference's fabric launcher) and both
    must train the MNIST MLP demo one pass to completion."""
    from paddle_tpu.tools import cluster_launch

    port = _free_port()
    save = tmp_path / "out"
    env_patch = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": "", "XLA_FLAGS": None}
    old = {k: os.environ.get(k) for k in env_patch}
    for k, v in env_patch.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        # --timeout: a grabbed port or wedged rendezvous must fail the
        # test, not hang the suite (the launcher kills the fleet at the
        # deadline and returns nonzero)
        rc = cluster_launch.main([
            "--hosts", "localhost,localhost", "--port", str(port),
            "--local", "--workspace", REPO, "--timeout", "240",
            "--python", sys.executable, "--",
            "--config=demo/mnist/mlp_mnist.py",
            "--config_args=batch_size=32",
            "--num_passes=1", f"--save_dir={save}",
            "--log_period=5",
        ])
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0, "cluster_launch --local run failed"
    # process 0 saved the pass checkpoint
    assert (save / "pass-00000" / "model.npz").exists()
