"""REAL multi-process distributed training over jax.distributed — the
cluster path that single-process virtual-mesh tests cannot exercise
(ref: the pserver fleet's multi-trainer sync-SGD protocol,
paddle/pserver/ParameterServer2.h synchronizeBarriers_; here the
coordinator bootstrap + gloo CPU collectives stand in for ICI/DCN).

Two subprocesses each boot via init_distributed, feed DIFFERENT local
batch shards (per-host data-parallel input), and train over one global
data-parallel mesh.  The step loss is computed from the global batch and
must agree bit-for-bit across processes; the BarrierStat straggler table
must allgather.  This validates the multi-process placement paths
(make_array_from_process_local_data for batches,
make_array_from_callback for replicated/sharded params) that device_put
alone cannot serve."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_data_parallel_training():
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)          # 1 CPU device per process
    env["PYTHONPATH"] = ""              # keep the axon plugin out

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, f"localhost:{port}", "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # one worker dying pre-rendezvous leaves the other blocked in
        # jax.distributed.initialize — never leak it past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    def losses_of(out):
        for ln in out.splitlines():
            if "losses=" in ln:
                return ln.split("losses=")[1].strip()
        raise AssertionError(f"no losses line:\n{out}")

    l0, l1 = losses_of(outs[0]), losses_of(outs[1])
    assert l0 == l1, f"process losses diverged:\n{l0}\n{l1}"
    assert all("straggler_ok" in o for o in outs)

    # -- single-process equivalence oracle (ref: trainer/tests/
    #    test_CompareSparse.cpp:133-152 — multi-trainer training must equal
    #    local training): rebuild the same model/seed in THIS process, feed
    #    the concatenated global batches, and require the same losses and
    #    final parameters the workers printed.
    import numpy as np

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        from paddle_tpu.dsl import (MomentumOptimizer, SoftmaxActivation,
                                    TanhActivation, classification_cost,
                                    data_layer, fc_layer, settings)
        settings(batch_size=16, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=16)
        h = fc_layer(input=x, size=32, act=TanhActivation())
        out = fc_layer(input=h, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    tr = Trainer(parse_config_callable(conf), seed=7, mesh=None)
    rngs = [np.random.default_rng(100 + i) for i in range(2)]
    W = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    local_losses = []
    for _ in range(4):
        xs, ys = [], []
        for r in rngs:        # same per-process streams, concatenated
            x = r.normal(size=(8, 16)).astype(np.float32)
            xs.append(x)
            ys.append(np.argmax(x @ W, -1).astype(np.int32))
        loss = tr.train_one_batch({"x": Argument(value=np.concatenate(xs)),
                                   "y": Argument(ids=np.concatenate(ys))})
        local_losses.append(float(loss))

    dist_losses = [float(v) for v in l0.split(",")]
    np.testing.assert_allclose(dist_losses, local_losses, rtol=2e-4,
                               atol=1e-6,
                               err_msg="2-process losses != local training")

    import re as _re
    import jax as _jax
    dist_params = {m.group(1): (float(m.group(2)), float(m.group(3)))
                   for m in _re.finditer(
                       r"param (\S+) sum=(\S+) asum=(\S+)", outs[0])}
    assert dist_params, "workers printed no param summaries"
    for name, v in tr.params.items():
        flat = np.asarray(_jax.device_get(v)).ravel()
        s, a = dist_params[name]
        np.testing.assert_allclose([flat.sum(), np.abs(flat).sum()], [s, a],
                                   rtol=3e-4, atol=2e-5,
                                   err_msg=f"param {name!r} != local run")
