"""Golden-file generation oracle (ref: paddle/trainer/tests/
test_recurrent_machine_generation.cpp — beam-search output compared against
a committed expectation file): the compiled beam search over seed-fixed
parameters must keep producing byte-identical beams.  Catches silent
drift in the generator (scoring, EOS handling, beam bookkeeping) that
loss-based tests never see."""

import json
import os
import sys

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.generator import generate
from paddle_tpu.parameter.argument import Argument
import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


GOLDEN = os.path.join(REPO, "tests/golden/seq2seq_beam.json")


def test_beam_search_matches_golden():
    with open(GOLDEN) as f:
        golden = json.load(f)

    gcfg = parse_config(os.path.join(REPO, "demo/seqToseq/seqToseq_net.py"),
                        golden["config"])
    gex = GraphExecutor(gcfg.model_config)
    params = gex.init_params(jax.random.PRNGKey(golden["seed"]))

    src = golden["sources"]
    B, T = len(src), max(len(s) for s in src)
    ids = np.zeros((B, T), np.int32)
    for i, s in enumerate(src):
        ids[i, :len(s)] = s
    lengths = np.asarray([len(s) for s in src], np.int32)
    feed = {"source_language_word": Argument(ids=ids, lengths=lengths)}

    seqs, scores = generate(gex, params, feed)
    seqs = np.asarray(seqs)
    scores = np.asarray(scores, np.float64)
    gseqs = np.asarray(golden["sequences"], np.int32)
    gscores = np.asarray(golden["scores"])

    # beam-SET comparison with score tolerance: near-tied beams may legally
    # swap order under neutral numeric changes (fusion/dtype), which is not
    # generator drift.  Every golden beam must appear with the same token
    # sequence and a matching score; the top beam's score must match too.
    np.testing.assert_allclose(scores[:, 0], gscores[:, 0], atol=1e-3)
    for b in range(gseqs.shape[0]):
        # multiset matching: EOS-padded beams can collapse to identical
        # token tuples, so each golden (seq, score) pair must greedily
        # claim a distinct produced pair
        produced = [(tuple(seqs[b, k].tolist()), scores[b, k])
                    for k in range(seqs.shape[1])]
        for k in range(gseqs.shape[1]):
            key = tuple(gseqs[b, k].tolist())
            match = next((i for i, (s, sc) in enumerate(produced)
                          if s == key and abs(sc - gscores[b, k]) < 1e-3),
                         None)
            assert match is not None, (
                f"golden beam {k} of source {b} unmatched: {key} "
                f"score {gscores[b, k]}; produced: {produced}")
            produced.pop(match)
