"""End-to-end slice: DSL -> ModelConfig -> GraphExecutor -> Trainer on a
synthetic separable dataset — the v0 milestone of SURVEY.md §7.4
(ref test analog: paddle/trainer/tests/test_TrainerOnePass.cpp)."""

import numpy as np

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.data.provider import dense_vector, integer_value, provider
from paddle_tpu.dsl import (
    SoftmaxActivation, TanhActivation, classification_cost, data_layer,
    fc_layer, settings, MomentumOptimizer,
)
from paddle_tpu.trainer.trainer import Trainer


def mlp_config(dim=16, classes=4):
    settings(batch_size=32, learning_rate=0.1,
             learning_method=MomentumOptimizer(momentum=0.9))
    img = data_layer(name="features", size=dim)
    h = fc_layer(input=img, size=32, act=TanhActivation())
    out = fc_layer(input=h, size=classes, act=SoftmaxActivation())
    lbl = data_layer(name="label", size=classes)
    classification_cost(input=out, label=lbl)


def synth_data(n=512, dim=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, dim)) * 3.0
    y = rng.integers(0, classes, size=n)
    x = centers[y] + rng.standard_normal((n, dim)).astype(np.float64)
    return x.astype(np.float32), y.astype(np.int32)


@provider(input_types={"features": dense_vector(16), "label": integer_value(4)},
          should_shuffle=True)
def synth_provider(settings, fname):
    x, y = synth_data()
    for i in range(len(y)):
        yield [x[i], int(y[i])]


def test_mlp_trains_to_low_error():
    cfg = parse_config_callable(mlp_config)
    cfg.model_config  # built
    tr = Trainer(cfg, seed=7)

    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(synth_provider, ["dummy"], ["features", "label"],
                        batch_size=32, seed=3)
    first_stats = tr.train_one_pass(batches=feeder.batches())
    for _ in range(4):
        stats = tr.train_one_pass(batches=feeder.batches())
    assert stats["cost"] < first_stats["cost"], "loss should decrease"
    assert stats["cost"] < 0.2, f"final cost too high: {stats}"
    assert stats["classification_error"] < 0.05, stats


def test_checkpoint_roundtrip(tmp_path):
    cfg = parse_config_callable(mlp_config)
    tr = Trainer(cfg, seed=7)
    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(synth_provider, ["dummy"], ["features", "label"],
                        batch_size=32, seed=3)
    tr.train_one_pass(batches=feeder.batches())
    d = tr.save(str(tmp_path))
    tr2 = Trainer(cfg, seed=99)
    tr2.load(d)
    for name in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[name]),
                                   np.asarray(tr2.params[name]), rtol=1e-6)


def test_pre_pass_save_is_labeled_init(tmp_path):
    """A save taken BEFORE pass 0 completes must not occupy pass-00000
    (that slot belongs to the real end-of-pass-0 snapshot), and resuming
    from it must not skip training pass 0."""
    cfg = parse_config_callable(mlp_config)
    tr = Trainer(cfg, seed=7)
    d0 = tr.save(str(tmp_path))
    assert d0.endswith("pass-init")

    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(synth_provider, ["dummy"], ["features", "label"],
                        batch_size=32, seed=3)
    tr.train_one_pass(batches=feeder.batches())
    d1 = tr.save(str(tmp_path))
    assert d1.endswith("pass-00000"), d1   # no collision with the init save

    # resuming from the init snapshot trains pass 0 — even on a trainer
    # whose own pass counter had advanced
    tr2 = Trainer(cfg, seed=99)
    tr2.pass_id = 5
    tr2.load(d0)
    assert tr2.pass_id == 0
    # resuming from the end-of-pass-0 snapshot trains pass 1
    tr3 = Trainer(cfg, seed=99)
    tr3.load(d1)
    assert tr3.pass_id == 1


def test_init_only_save_dir_resumes_and_prunes(tmp_path):
    """Root-dir resume works when pass-init is the ONLY snapshot, and
    keep_last treats pass-init as the oldest prunable entry."""
    import os
    cfg = parse_config_callable(mlp_config)
    tr = Trainer(cfg, seed=7)
    tr.save(str(tmp_path))                       # pass-init only
    tr2 = Trainer(cfg, seed=99)
    tr2.load(str(tmp_path))                      # root-dir resume
    assert tr2.pass_id == 0
    for name in tr.params:
        np.testing.assert_allclose(np.asarray(tr.params[name]),
                                   np.asarray(tr2.params[name]), rtol=1e-6)

    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(synth_provider, ["dummy"], ["features", "label"],
                        batch_size=32, seed=3)
    tr.train_one_pass(batches=feeder.batches())
    tr.save(str(tmp_path), keep_last=1)          # prunes pass-init
    entries = sorted(os.listdir(str(tmp_path)))
    assert entries == ["pass-00000"], entries
