"""Pallas flash attention vs the dense reference — numeric oracle
(the reference's CPU-vs-GPU comparison pattern, ref:
math/tests/test_matrixCompare.cpp; here: interpret-mode pallas vs the
fused-XLA dot_product_attention, forward AND gradients).  On real TPU the
same kernels compile natively.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import blockwise_attention, dot_product_attention
from paddle_tpu.ops.pallas_attention import flash_attention

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")



def _case(rng, B, Tq, Tk, H, D, ragged=True):
    q = jnp.asarray(rng.normal(size=(B, Tq, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, Tk, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, Tk, H, D)), jnp.float32)
    if ragged:
        klens = rng.integers(1, Tk + 1, B)
        qlens = rng.integers(1, Tq + 1, B)
        k_valid = jnp.asarray(np.arange(Tk)[None, :] < klens[:, None])
        q_valid = jnp.asarray(np.arange(Tq)[None, :] < qlens[:, None])
    else:
        k_valid = q_valid = None
    return q, k, v, q_valid, k_valid


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [
    (2, 20, 24, 2, 16),     # ragged, unaligned sizes (exercise padding)
    (1, 128, 128, 4, 32),   # aligned single block
    (2, 130, 70, 2, 8),     # multi-block q, tiny head dim
])
def test_flash_matches_dense(causal, shape):
    rng = np.random.default_rng(0)
    q, k, v, q_valid, k_valid = _case(rng, *shape)

    want = dot_product_attention(q, k, v, q_valid=q_valid,
                                 k_valid=k_valid, causal=causal)
    got = flash_attention(q, k, v, q_valid=q_valid, k_valid=k_valid,
                          causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        def f(q, k, v):
            o = fn(q, k, v, q_valid=q_valid, k_valid=k_valid, causal=causal)
            return jnp.sum(jnp.sin(o))
        return f

    gw = jax.grad(loss(dot_product_attention), argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(lambda q, k, v: loss(
        lambda *a, **kw: flash_attention(*a, block_q=64, block_k=64, **kw)
    )(q, k, v), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gw, gg):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=2e-5)


def test_flash_matches_blockwise_long():
    """Long-sequence case: flash vs the scan-based online-softmax path."""
    rng = np.random.default_rng(1)
    q, k, v, q_valid, k_valid = _case(rng, 1, 384, 384, 2, 16)
    want = blockwise_attention(q, k, v, q_valid=q_valid, k_valid=k_valid,
                               causal=True, block_k=128)
    got = flash_attention(q, k, v, q_valid=q_valid, k_valid=k_valid,
                          causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_fully_masked_rows_zero():
    """A sequence whose keys are ALL invalid must output exactly 0 and
    contribute zero gradient (dot_product_attention's contract)."""
    rng = np.random.default_rng(2)
    q, k, v, _, _ = _case(rng, 2, 8, 8, 1, 8, ragged=False)
    k_valid = jnp.asarray(np.array([[True] * 8, [False] * 8]))
    out = flash_attention(q, k, v, k_valid=k_valid)
    assert np.all(np.asarray(out[1]) == 0.0)

    g = jax.grad(lambda v: jnp.sum(
        flash_attention(q, k, v, k_valid=k_valid)))(v)
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.all(np.asarray(g[1]) == 0.0)


def test_flash_bf16_close():
    rng = np.random.default_rng(3)
    q, k, v, q_valid, k_valid = _case(rng, 2, 33, 47, 2, 16)
    want = dot_product_attention(q, k, v, q_valid=q_valid, k_valid=k_valid)
    got = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16), q_valid=q_valid,
                          k_valid=k_valid)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_layer_selects_flash_when_supported(monkeypatch):
    """multi_head_attention layer picks the pallas kernel for long keys when
    the backend supports it, and the step trains end-to-end."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import numpy as np

    # spy: the layer must actually route through the pallas kernel (a silent
    # fallback to blockwise would train identically on this tiny config)
    import paddle_tpu.graph.layers_attn as layers_attn_mod
    from paddle_tpu.ops import pallas_attention as pa_mod
    calls = []
    real = pa_mod.flash_attention

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(pa_mod, "flash_attention", spy)

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, classification_cost,
        data_layer, fc_layer, multi_head_attention_layer, pooling_layer,
        settings,
    )
    from paddle_tpu.dsl.poolings import AvgPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        settings(batch_size=4, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=16)
        # block_k_min=8 forces the long-key path at T=16
        attn = multi_head_attention_layer(x, size=16, num_heads=2,
                                          causal=True, block_k_min=8,
                                          block_k=8)
        pooled = pooling_layer(input=attn, pooling_type=AvgPooling())
        out = fc_layer(input=pooled, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "x": Argument(value=rng.normal(size=(4, 16, 16)).astype(np.float32),
                      lengths=np.array([16, 12, 16, 7], np.int32)),
        "y": Argument(ids=rng.integers(0, 4, 4).astype(np.int32)),
    }
    losses = [float(tr.train_one_batch(batch)) for _ in range(8)]
    assert calls, "layer did not route through the pallas flash kernel"
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


class TestRingFlash:
    """Ring flash attention (pallas per hop + lse combine) vs the jnp ring
    fold and the full-sequence dense oracle, on the virtual 8-device mesh."""

    def _sharded(self, use_flash, q, k, v, q_valid, k_valid, causal):
        import functools

        from jax.sharding import PartitionSpec as P
        from paddle_tpu.utils.jax_compat import shard_map

        from paddle_tpu.ops.attention import ring_attention
        from paddle_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(seq=4)
        spec = P(None, "seq", None, None)
        vspec = P(None, "seq")

        def local(q, k, v, qm, km):
            return ring_attention(q, k, v, "seq", q_valid=qm, k_valid=km,
                                  causal=causal, use_flash=use_flash)

        # check_vma=False: pallas_call outputs carry no varying-mesh-axes
        # annotation (standard for custom kernels under manual sharding)
        fn = shard_map(local, mesh=mesh,
                       in_specs=(spec, spec, spec, vspec, vspec),
                       out_specs=spec, check_vma=False)
        return fn(q, k, v, q_valid, k_valid)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_jnp_ring_and_dense(self, causal, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
        rng = np.random.default_rng(0)
        B, T, H, D = 2, 64, 2, 16            # 4 shards of 16
        q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        lens = np.array([T, 37])
        valid = jnp.asarray(np.arange(T)[None, :] < lens[:, None])

        from paddle_tpu.ops.attention import dot_product_attention
        want = dot_product_attention(q, k, v, q_valid=valid, k_valid=valid,
                                     causal=causal)
        ring = self._sharded(False, q, k, v, valid, valid, causal)
        flash = self._sharded(True, q, k, v, valid, valid, causal)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(flash), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_jnp_ring(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
        rng = np.random.default_rng(1)
        B, T, H, D = 1, 32, 2, 8             # 4 shards of 8
        q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        lens = np.array([25])
        valid = jnp.asarray(np.arange(T)[None, :] < lens[:, None])

        def loss(use_flash):
            def f(q, k, v):
                o = self._sharded(use_flash, q, k, v, valid, valid, True)
                return jnp.sum(jnp.sin(o))
            return f

        gw = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gw, gg):
            assert np.all(np.isfinite(np.asarray(b)))
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-5, atol=3e-5)


def test_attn_impl_validation():
    """Clear errors for an unknown attn_impl and for ring without a seq
    mesh (rather than an AttributeError deep in the ring plumbing)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, classification_cost,
        data_layer, fc_layer, multi_head_attention_layer, pooling_layer,
        settings,
    )
    from paddle_tpu.dsl.poolings import AvgPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf(impl):
        def f():
            settings(batch_size=2, learning_rate=0.1,
                     learning_method=MomentumOptimizer())
            x = data_layer(name="x", size=8)
            a = multi_head_attention_layer(x, size=8, num_heads=2,
                                           attn_impl=impl)
            p = pooling_layer(input=a, pooling_type=AvgPooling())
            out = fc_layer(input=p, size=2, act=SoftmaxActivation())
            classification_cost(input=out, label=data_layer(name="y", size=2))
        return f

    batch = {"x": Argument(value=np.zeros((2, 4, 8), np.float32),
                           lengths=np.full((2,), 4, np.int32)),
             "y": Argument(ids=np.zeros((2,), np.int32))}

    tr = Trainer(parse_config_callable(conf("Flash")), seed=0)
    with pytest.raises(ValueError, match="unknown attn_impl"):
        tr.train_one_batch(batch)

    tr2 = Trainer(parse_config_callable(conf("ring")), seed=0)
    with pytest.raises(ValueError, match="seq"):
        tr2.train_one_batch(batch)
