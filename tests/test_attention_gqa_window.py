"""Grouped-query (GQA) and sliding-window attention oracles across the
implementations (dense / blockwise / flash / ring) — NEW long-context
capabilities; the oracle is dense attention with explicitly materialized
repeated kv heads and a hand-built window mask.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import (
    blockwise_attention, dot_product_attention, ring_attention)
from paddle_tpu.ops.pallas_attention import flash_attention

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")



def _case(rng, B, T, H, H_kv, D):
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H_kv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H_kv, D)), jnp.float32)
    lens = rng.integers(T // 2, T + 1, B)
    valid = jnp.asarray(np.arange(T)[None, :] < lens[:, None])
    return q, k, v, valid


def _manual_oracle(q, k, v, valid, causal, window):
    """Dense attention with kv heads repeated by hand and the window mask
    built from scratch."""
    B, T, H, D = q.shape
    rep = H // k.shape[2]
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    i = np.arange(T)
    mask = np.ones((T, T), bool)
    if causal:
        mask &= i[None, :] <= i[:, None]
    if window is not None:
        mask &= np.abs(i[:, None] - i[None, :]) < window
    m = jnp.asarray(mask)[None, None] & valid[:, None, None, :] \
        & valid[:, None, :, None]
    s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.any(m, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal,window", [
    (False, None), (True, None), (False, 5), (True, 5),
])
@pytest.mark.parametrize("H,H_kv", [(4, 4), (4, 2), (4, 1)])
def test_single_device_impls_match_oracle(causal, window, H, H_kv):
    rng = np.random.default_rng(0)
    q, k, v, valid = _case(rng, 2, 24, H, H_kv, 8)
    want = _manual_oracle(q, k, v, valid, causal, window)

    impls = {
        "dense": dot_product_attention,
        "blockwise": functools.partial(blockwise_attention, block_k=8),
        "flash": functools.partial(flash_attention, block_q=8, block_k=8),
    }
    for name, fn in impls.items():
        got = fn(q, k, v, q_valid=valid, k_valid=valid, causal=causal,
                 window=window)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-5, atol=3e-5,
            err_msg=f"impl={name} causal={causal} window={window} "
                    f"H={H} H_kv={H_kv}")


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gqa_window_matches_oracle(use_flash, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.utils.jax_compat import shard_map

    from paddle_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(1)
    B, T, H, H_kv, D, window = 1, 32, 2, 1, 8, 6
    q, k, v, valid = _case(rng, B, T, H, H_kv, D)
    want = _manual_oracle(q, k, v, valid, True, window)

    mesh = make_mesh(seq=4)
    qspec = P(None, "seq", None, None)
    vspec = P(None, "seq")

    def local(q, k, v, vm):
        return ring_attention(q, k, v, "seq", q_valid=vm, k_valid=vm,
                              causal=True, use_flash=use_flash,
                              window=window)

    fn = shard_map(local, mesh=mesh, in_specs=(qspec, qspec, qspec, vspec),
                   out_specs=qspec, check_vma=False)
    got = fn(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-5, atol=3e-5)


def test_gqa_grads_flow_to_shared_kv_heads():
    """dk/dv of a grouped kv head must sum its query-head group's
    contributions (the transpose of the head repeat)."""
    rng = np.random.default_rng(2)
    q, k, v, valid = _case(rng, 1, 16, 4, 2, 8)

    def loss(fn):
        def f(k, v):
            o = fn(q, k, v, q_valid=valid, k_valid=valid, causal=True)
            return jnp.sum(jnp.sin(o))
        return f

    gw = jax.grad(loss(dot_product_attention), argnums=(0, 1))(k, v)
    gg = jax.grad(loss(functools.partial(flash_attention, block_q=8,
                                         block_k=8)), argnums=(0, 1))(k, v)
    for a, b in zip(gw, gg):
        assert a.shape == k.shape  # grads stay in kv-head shape
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-5, atol=3e-5)


def test_layer_gqa_window_trains(monkeypatch):
    """multi_head_attention layer with num_kv_heads + window trains
    end-to-end through the DSL (param shapes sized for the kv heads)."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        AdamOptimizer, SoftmaxActivation, classification_cost, data_layer,
        fc_layer, multi_head_attention_layer, pooling_layer, settings,
    )
    from paddle_tpu.dsl.poolings import MaxPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        settings(batch_size=4, learning_rate=0.05,
                 learning_method=AdamOptimizer())
        x = data_layer(name="x", size=16)
        a = multi_head_attention_layer(x, size=16, num_heads=4,
                                       num_kv_heads=2, window=6, causal=True)
        p = pooling_layer(input=a, pooling_type=MaxPooling())
        out = fc_layer(input=p, size=2, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=2))

    cfg = parse_config_callable(conf)
    kv_params = [p for p in cfg.model_config.parameters
                 if p.name.endswith("_1__") or p.name.endswith("_2__")]
    assert all(p.dims == [16, 8] for p in kv_params), \
        [(p.name, p.dims) for p in cfg.model_config.parameters]

    tr = Trainer(cfg, seed=0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 12, 16)).astype(np.float32)
    batch = {"x": Argument(value=x, lengths=np.full((4,), 12, np.int32)),
             "y": Argument(ids=(x[:, :, 0].mean(1) > 0).astype(np.int32))}
    losses = [float(tr.train_one_batch(batch)) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
