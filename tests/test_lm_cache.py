"""KV-cache decode oracle: `lm_generate(use_cache=True)` must reproduce the
whole-prefix re-forward path token for token (greedy), across ragged prompt
lengths, grouped-query heads, sliding windows, and eos early-stop.  The
cached path computes attention incrementally (ops/attention.py:
cached_attention_step) — any positional/masking slip shows up as a token
divergence here."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.trainer.trainer import Trainer


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


def _prompts(B, P, vocab, seed=0, ragged=True):
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, vocab, (B, P)).astype(np.int32)
    lens = (rng.integers(2, P + 1, B).astype(np.int32) if ragged
            else np.full((B,), P, np.int32))
    return ids, lens


@pytest.mark.parametrize("extra,ragged", [
    ("", True),                                   # MHA + rope, ragged
    ("kv_heads=2", False),                        # grouped-query heads
    ("window=5", True),                           # sliding window
    ("block_k_min=4", True),                      # blockwise prefill branch
])
def test_cached_matches_full_greedy(extra, ragged):
    args = "vocab=97,dim=32,layers=2,heads=4,batch_size=4"
    if extra:
        args += "," + extra
    tr = _make(args)
    ids, lens = _prompts(4, 9, 97, ragged=ragged)
    full_toks, full_lens = lm_generate(tr.executor, tr.params, ids,
                                       prompt_lengths=lens, max_new=7)
    c_toks, c_lens = lm_generate(tr.executor, tr.params, ids,
                                 prompt_lengths=lens, max_new=7,
                                 use_cache=True)
    np.testing.assert_array_equal(np.asarray(full_lens), np.asarray(c_lens))
    # compare only the valid region of each row (beyond lengths is junk)
    fl, ct = np.asarray(full_toks), np.asarray(c_toks)
    for b, n in enumerate(np.asarray(full_lens)):
        np.testing.assert_array_equal(fl[b, :n], ct[b, :n])


def test_cached_matches_full_eos_stop():
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    ids, lens = _prompts(3, 6, 11, seed=3)
    kw = dict(prompt_lengths=lens, max_new=8, eos_id=5)
    f_t, f_l = lm_generate(tr.executor, tr.params, ids, **kw)
    c_t, c_l = lm_generate(tr.executor, tr.params, ids, use_cache=True, **kw)
    np.testing.assert_array_equal(np.asarray(f_l), np.asarray(c_l))
    fl, ct = np.asarray(f_t), np.asarray(c_t)
    for b, n in enumerate(np.asarray(f_l)):
        np.testing.assert_array_equal(fl[b, :n], ct[b, :n])


def test_cached_step_op_matches_dense():
    """cached_attention_step over two sequential calls == one dense causal
    attention over the concatenation, per row, with ragged first-call
    lengths."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import (cached_attention_step,
                                          dot_product_attention)

    rng = np.random.default_rng(1)
    B, H, Hkv, D, P, Tmax = 3, 4, 2, 8, 5, 9
    lens = np.array([3, 5, 2], np.int32)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    q1, k1, v1 = mk(B, P, H, D), mk(B, P, Hkv, D), mk(B, P, Hkv, D)
    ck = jnp.zeros((B, Tmax, Hkv, D))
    cv = jnp.zeros((B, Tmax, Hkv, D))
    pos0 = jnp.zeros((B,), jnp.int32)
    o1, ck, cv, pos = cached_attention_step(
        q1, k1, v1, ck, cv, pos0, jnp.asarray(lens))
    # second call: ONE new token per row, placed at each row's length
    q2, k2, v2 = mk(B, 1, H, D), mk(B, 1, Hkv, D), mk(B, 1, Hkv, D)
    o2, _, _, pos = cached_attention_step(
        q2, k2, v2, ck, cv, pos, jnp.ones((B,), jnp.int32))
    assert np.array_equal(np.asarray(pos), lens + 1)

    for b in range(B):
        n = int(lens[b])
        # dense oracle on row b: valid prefix + the new token
        qq = jnp.concatenate([q1[b:b+1, :n], q2[b:b+1]], axis=1)
        kk = jnp.concatenate([k1[b:b+1, :n], k2[b:b+1]], axis=1)
        vv = jnp.concatenate([v1[b:b+1, :n], v2[b:b+1]], axis=1)
        want = dot_product_attention(qq, kk, vv, causal=True)
        np.testing.assert_allclose(np.asarray(o1[b, :n]),
                                   np.asarray(want[0, :n]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(o2[b, 0]),
                                   np.asarray(want[0, n]),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_cache", [False, True])
def test_early_exit_chunks_match_single_scan(use_cache):
    """lm_generate(early_exit_chunk=k) decodes in k-step scans with a host
    all-done check between chunks — tokens, lengths AND the rng stream
    must be bit-identical to the single-scan path (chunk sizes that divide
    max_new and a ragged remainder both)."""
    import jax

    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    ids, lens = _prompts(3, 6, 11, seed=3)
    for chunk, kw in [(3, dict(eos_id=5)),                  # remainder chunk
                      (5, dict(eos_id=5)),                  # divides max_new
                      (4, dict(temperature=0.9, top_k=4,    # sampled stream
                               rng=jax.random.PRNGKey(2)))]:
        base = dict(prompt_lengths=lens, max_new=10, use_cache=use_cache,
                    **kw)
        f_t, f_l = lm_generate(tr.executor, tr.params, ids, **base)
        c_t, c_l = lm_generate(tr.executor, tr.params, ids,
                               early_exit_chunk=chunk, **base)
        np.testing.assert_array_equal(np.asarray(f_l), np.asarray(c_l))
        np.testing.assert_array_equal(np.asarray(f_t), np.asarray(c_t))


def test_early_exit_stops_after_all_rows_done():
    """_chunked_scan must stop dispatching chunks once the host all-done
    check trips: a batch done at step 7 of 29 runs 2 five-step chunks, not
    6 — and leaves the carry exactly as the full scan would (done rows
    freeze, so skipped trailing steps are no-ops)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.graph.lm_decode import _chunked_scan

    def step(carry, key):
        i, done = carry
        i = jnp.where(done, i, i + 1)
        return (i, i >= 7), None

    keys = jnp.arange(29)
    full, _ = jax.lax.scan(step, (jnp.int32(0), jnp.bool_(False)), keys)

    chunks = []
    orig_scan = jax.lax.scan

    def counting_scan(f, init, xs, *a, **kw):
        chunks.append(int(xs.shape[0]))
        return orig_scan(f, init, xs, *a, **kw)

    jax.lax.scan = counting_scan
    try:
        chunked = _chunked_scan(step, (jnp.int32(0), jnp.bool_(False)),
                                keys, chunk=5, done_of=lambda c: c[1])
    finally:
        jax.lax.scan = orig_scan
    assert int(chunked[0]) == int(full[0]) == 7
    assert chunks == [5, 5], f"expected 2 five-step chunks (done at " \
                             f"step 7), got {chunks}"


def test_beam1_equals_greedy_cached():
    from paddle_tpu.graph.lm_decode import lm_beam_generate

    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    ids, lens = _prompts(4, 8, 61, seed=5)
    g_t, g_l = lm_generate(tr.executor, tr.params, ids, prompt_lengths=lens,
                           max_new=6, use_cache=True)
    b_t, b_l, _ = lm_beam_generate(tr.executor, tr.params, ids,
                                   prompt_lengths=lens, beam_size=1,
                                   max_new=6)
    np.testing.assert_array_equal(np.asarray(g_l), np.asarray(b_l)[:, 0])
    gt, bt = np.asarray(g_t), np.asarray(b_t)
    for b, n in enumerate(np.asarray(g_l)):
        np.testing.assert_array_equal(gt[b, :n], bt[b, 0, :n])


def test_beam_scores_match_teacher_forcing():
    """Every returned hypothesis's score must equal the sum of stepwise
    token log-probs recomputed by teacher-forcing the whole sequence
    through the (uncached) model — validates cache reordering, positions,
    and score bookkeeping in one shot.  Also: scores sorted best-first and
    hypotheses within a row distinct."""
    import jax.numpy as jnp

    from paddle_tpu.graph.context import TEST
    from paddle_tpu.graph.lm_decode import lm_beam_generate
    from paddle_tpu.parameter.argument import Argument

    tr = _make("vocab=23,dim=24,layers=2,heads=2,batch_size=3")
    ids, lens = _prompts(3, 6, 23, seed=9)
    K, max_new = 3, 4
    toks, out_lens, scores = lm_beam_generate(
        tr.executor, tr.params, ids, prompt_lengths=lens, beam_size=K,
        max_new=max_new)
    toks, out_lens, scores = (np.asarray(toks), np.asarray(out_lens),
                              np.asarray(scores))
    assert (np.diff(scores, axis=1) <= 1e-5).all(), scores

    for b in range(3):
        hyps = {tuple(toks[b, k, :out_lens[b, k]]) for k in range(K)}
        assert len(hyps) == K, f"row {b}: duplicate hypotheses"
        for k in range(K):
            n, p = int(out_lens[b, k]), int(lens[b])
            feed = {"tokens": Argument(
                ids=jnp.asarray(toks[b, k][None, :n]),
                lengths=jnp.full((1,), n, jnp.int32))}
            outputs, _, _ = tr.executor.forward(tr.params, feed, None, TEST,
                                                None)
            probs = np.asarray(outputs["lm_head"].value)[0]   # [n, V]
            lp = np.log(np.maximum(probs.astype(np.float64), 1e-30))
            want = sum(lp[t - 1, toks[b, k, t]] for t in range(p, n))
            np.testing.assert_allclose(scores[b, k], want, rtol=2e-4,
                                       atol=2e-4)


def test_beam_eos_freezes():
    from paddle_tpu.graph.lm_decode import lm_beam_generate

    tr = _make("vocab=13,dim=16,layers=1,heads=2,batch_size=2")
    ids, lens = _prompts(2, 5, 13, seed=11)
    toks, out_lens, scores = lm_beam_generate(
        tr.executor, tr.params, ids, prompt_lengths=lens, beam_size=4,
        max_new=6, eos_id=3)
    toks, out_lens = np.asarray(toks), np.asarray(out_lens)
    assert np.isfinite(np.asarray(scores)).all()
    # a beam that emitted eos must have stopped growing there
    for b in range(2):
        for k in range(4):
            seq = toks[b, k, int(lens[b]):int(out_lens[b, k])]
            inner_eos = (seq[:-1] == 3) if len(seq) > 1 else np.array([])
            assert not inner_eos.any(), (b, k, seq)
