"""KV-cache decode oracle: `lm_generate(use_cache=True)` must reproduce the
whole-prefix re-forward path token for token (greedy), across ragged prompt
lengths, grouped-query heads, sliding windows, and eos early-stop.  The
cached path computes attention incrementally (ops/attention.py:
cached_attention_step) — any positional/masking slip shows up as a token
divergence here."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.trainer.trainer import Trainer


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


def _prompts(B, P, vocab, seed=0, ragged=True):
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, vocab, (B, P)).astype(np.int32)
    lens = (rng.integers(2, P + 1, B).astype(np.int32) if ragged
            else np.full((B,), P, np.int32))
    return ids, lens


@pytest.mark.parametrize("extra,ragged", [
    ("", True),                                   # MHA + rope, ragged
    ("kv_heads=2", False),                        # grouped-query heads
    ("window=5", True),                           # sliding window
    ("block_k_min=4", True),                      # blockwise prefill branch
])
def test_cached_matches_full_greedy(extra, ragged):
    args = "vocab=97,dim=32,layers=2,heads=4,batch_size=4"
    if extra:
        args += "," + extra
    tr = _make(args)
    ids, lens = _prompts(4, 9, 97, ragged=ragged)
    full_toks, full_lens = lm_generate(tr.executor, tr.params, ids,
                                       prompt_lengths=lens, max_new=7)
    c_toks, c_lens = lm_generate(tr.executor, tr.params, ids,
                                 prompt_lengths=lens, max_new=7,
                                 use_cache=True)
    np.testing.assert_array_equal(np.asarray(full_lens), np.asarray(c_lens))
    # compare only the valid region of each row (beyond lengths is junk)
    fl, ct = np.asarray(full_toks), np.asarray(c_toks)
    for b, n in enumerate(np.asarray(full_lens)):
        np.testing.assert_array_equal(fl[b, :n], ct[b, :n])


def test_cached_matches_full_eos_stop():
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    ids, lens = _prompts(3, 6, 11, seed=3)
    kw = dict(prompt_lengths=lens, max_new=8, eos_id=5)
    f_t, f_l = lm_generate(tr.executor, tr.params, ids, **kw)
    c_t, c_l = lm_generate(tr.executor, tr.params, ids, use_cache=True, **kw)
    np.testing.assert_array_equal(np.asarray(f_l), np.asarray(c_l))
    fl, ct = np.asarray(f_t), np.asarray(c_t)
    for b, n in enumerate(np.asarray(f_l)):
        np.testing.assert_array_equal(fl[b, :n], ct[b, :n])


def test_cached_step_op_matches_dense():
    """cached_attention_step over two sequential calls == one dense causal
    attention over the concatenation, per row, with ragged first-call
    lengths."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import (cached_attention_step,
                                          dot_product_attention)

    rng = np.random.default_rng(1)
    B, H, Hkv, D, P, Tmax = 3, 4, 2, 8, 5, 9
    lens = np.array([3, 5, 2], np.int32)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    q1, k1, v1 = mk(B, P, H, D), mk(B, P, Hkv, D), mk(B, P, Hkv, D)
    ck = jnp.zeros((B, Tmax, Hkv, D))
    cv = jnp.zeros((B, Tmax, Hkv, D))
    pos0 = jnp.zeros((B,), jnp.int32)
    o1, ck, cv, pos = cached_attention_step(
        q1, k1, v1, ck, cv, pos0, jnp.asarray(lens))
    # second call: ONE new token per row, placed at each row's length
    q2, k2, v2 = mk(B, 1, H, D), mk(B, 1, Hkv, D), mk(B, 1, Hkv, D)
    o2, _, _, pos = cached_attention_step(
        q2, k2, v2, ck, cv, pos, jnp.ones((B,), jnp.int32))
    assert np.array_equal(np.asarray(pos), lens + 1)

    for b in range(B):
        n = int(lens[b])
        # dense oracle on row b: valid prefix + the new token
        qq = jnp.concatenate([q1[b:b+1, :n], q2[b:b+1]], axis=1)
        kk = jnp.concatenate([k1[b:b+1, :n], k2[b:b+1]], axis=1)
        vv = jnp.concatenate([v1[b:b+1, :n], v2[b:b+1]], axis=1)
        want = dot_product_attention(qq, kk, vv, causal=True)
        np.testing.assert_allclose(np.asarray(o1[b, :n]),
                                   np.asarray(want[0, :n]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(o2[b, 0]),
                                   np.asarray(want[0, n]),
                                   rtol=2e-5, atol=2e-5)
