"""Training-fleet observability (ISSUE 15 acceptance).

The headline contract: a REAL K=2-trainer x 2-shard run (trainer
subprocesses over real TCP, in-process pserver shards each holding its
OWN Tracer ring — the per-process shape the `trace` RPC snapshots in a
real deployment), pulled via the `trace` RPC and merged with the
trainers' --trace-out files, stitches into ONE valid Perfetto trace in
which a single window's trace_id spans trainer AND shard tracks, with
role-named per-process track groups (pserver/trainer joining the serving
tier's replica/router).  The per-window timing attribution closes
exactly: compute + push + barrier_wait + pull + other == the window
wall (parts contiguous by construction), apply nests inside
barrier_wait, and the per-pass sums ride the trainer's metrics.jsonl
rows next to the throughput gauges.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = "demo/distributed/mlp_dist.py"
CONFIG_ARGS = "samples=128,batch_size=16,dim=16,hidden=32"


def _spawn_trainer(addrs, rank, trainers, passes, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "train_dist.py"),
         "--config", CONFIG, "--config-args", CONFIG_ARGS,
         "--pserver", ",".join(f"127.0.0.1:{p}" for p in addrs),
         "--rank", str(rank), "--trainers", str(trainers),
         "--passes", str(passes), *extra],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _spans_for(spans, tid):
    """Spans carrying `tid` — singular `trace_id` (per-contribution
    spans) or membership in `trace_ids` (a window's commit-lane spans
    name every contributor)."""
    out = []
    for s in spans:
        attrs = s.get("attrs") or {}
        if attrs.get("trace_id") == tid or \
                tid in (attrs.get("trace_ids") or ()):
            out.append(s)
    return out


def test_k2_x_2shard_trace_rpc_stitches_one_perfetto_trace(tmp_path):
    """THE acceptance path: K=2 trainers x N=2 shards, shard rings
    pulled LIVE over the `trace` RPC, trainer rings from --trace-out
    files, stitched by merge_chrome into one trace with four role-named
    process groups — and one window's trace_id crosses from a trainer
    track onto BOTH shard tracks."""
    from paddle_tpu.obs import Tracer, merge_chrome
    from paddle_tpu.pserver.server import ParameterServer
    from paddle_tpu.serving.client import ServingClient
    from tools.trace_dump import load_trace_file

    srvs = []
    for i in range(2):
        tracer = Tracer()
        tracer.enabled = True
        srvs.append(ParameterServer(port=0, shard_index=i, n_shards=2,
                                    beat_timeout_s=60.0, tracer=tracer))
    addrs = [s.start_background()[1] for s in srvs]
    try:
        tr_files = [str(tmp_path / f"r{r}.jsonl") for r in range(2)]
        procs = [_spawn_trainer(addrs, r, 2, 2,
                                extra=("--trace-out", tr_files[r]))
                 for r in range(2)]
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"trainer failed:\n{err[-2000:]}"
            assert "TRAIN_JSON" in out

        # -- collection: shards over the wire, trainers from files -------
        pulls = []
        for port in addrs:
            with ServingClient("127.0.0.1", port, timeout=30) as c:
                pulls.append(c.trace())
        sources = [{"spans": p["spans"], "process": p["process"],
                    "offset_s": p["offset_s"]} for p in pulls]
        for f in tr_files:
            meta, spans = load_trace_file(f)
            assert meta.get("process", {}).get("role") == "trainer"
            sources.append({"spans": spans, "process": meta["process"],
                            "offset_s": 0.0})
        assert all(p["process"]["role"] == "pserver" for p in pulls)
        assert {p["process"]["shard"] for p in pulls} == {0, 1}

        # -- one window's trace_id spans trainer and shard tracks --------
        t0_meta, t0_spans = load_trace_file(tr_files[0])
        windows = [s for s in t0_spans if s["name"] == "window"]
        assert len(windows) >= 4          # 2 passes x >= 2 windows each
        win = windows[1]
        tid = win["attrs"]["trace_id"]
        # the trainer's own phase spans carry it...
        t_names = {s["name"] for s in _spans_for(t0_spans, tid)}
        assert {"grad_compute", "push", "barrier_wait",
                "pull"} <= t_names
        # ...and BOTH shards adopted it (recv_grad at least; the
        # coordinator's update thread also stamps it on accumulate/apply)
        for p in pulls:
            names = {s["name"] for s in _spans_for(p["spans"], tid)}
            assert "recv_grad" in names, \
                f"shard {p['process']['shard']} never adopted {tid}"
        coord = next(p for p in pulls if p["process"]["shard"] == 0)
        coord_names = {s["name"]
                       for s in _spans_for(coord["spans"], tid)}
        assert {"accumulate", "apply", "commit"} <= coord_names

        # -- pass boundaries stitch too: the trainer's pass_barrier span
        # OWNS its boundary context (trace_id + span_id, no dangling
        # parent) and the shard's pass-commit span lists the trace_id
        # among its contributors
        pb = [s for s in t0_spans if s["name"] == "pass_barrier"]
        assert len(pb) == 2               # one per pass
        for s in pb:
            assert s["attrs"]["trace_id"] and s["attrs"]["span_id"]
        pass_commits = [s for s in coord["spans"]
                        if s["name"] == "commit"
                        and (s.get("attrs") or {}).get("kind") == "pass"]
        assert pass_commits, "coordinator recorded no pass-commit span"
        adopted = set()
        for s in pass_commits:
            adopted |= set(s["attrs"].get("trace_ids") or ())
        assert {s["attrs"]["trace_id"] for s in pb} <= adopted

        # -- the merged trace is Perfetto-valid, role-named, 4 tracks ----
        merged = merge_chrome(sources)
        assert set(merged) == {"traceEvents", "displayTimeUnit"}
        procs_ev = [e for e in merged["traceEvents"]
                    if e.get("name") == "process_name"]
        assert len(procs_ev) == 4
        assert len({e["pid"] for e in procs_ev}) == 4
        roles = sorted(e["args"]["name"].split()[0] for e in procs_ev)
        assert roles == ["pserver", "pserver", "trainer", "trainer"]
        for ev in merged["traceEvents"]:
            assert ev["ph"] in ("M", "X", "i")
            if ev["ph"] != "M":
                assert ev["ts"] >= 0.0                 # global rebase
        # the window's trace_id appears on >= 3 distinct merged pids
        # (this trainer + both shards) — the cross-process stitch
        pids = {ev["pid"] for ev in merged["traceEvents"]
                if ev["ph"] != "M"
                and (ev.get("args") or {}).get("trace_id") == tid}
        assert len(pids) >= 3
    finally:
        for s in srvs:
            s.stop_background(drain=False)


def test_window_timing_closure_and_metrics_rows(tmp_path):
    """Per-window attribution: the parts sum to the window wall EXACTLY
    (closure by construction, asserted here), apply_ms (the server-side
    breakdown riding the barrier reply) nests inside barrier_wait_ms,
    and the per-pass sums land in the pass stats, TRAIN_JSON's source
    fields, and the metrics.jsonl row."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.optim.remote_updater import (RemoteParameterUpdater,
                                                 TIMING_PARTS)
    from paddle_tpu.pserver.server import ParameterServer
    from paddle_tpu.trainer.trainer import Trainer

    srv = ParameterServer(port=0, beat_timeout_s=60.0)
    host, port = srv.start_background()
    try:
        cfg = parse_config(CONFIG, CONFIG_ARGS)
        upd = RemoteParameterUpdater(cfg.model_config, cfg.opt_config,
                                     [(host, port)])
        tr = Trainer(cfg, seed=1, updater=upd)
        stats = tr.train_one_pass(batches=None)

        t = upd.last_window_timing
        assert t["window"] is not None
        parts = sum(t[k] for k in TIMING_PARTS)
        # closure: parts are contiguous segments of [t0, t_end] — the
        # identity must hold to rounding (5 parts x 1e-3 rounding)
        assert abs(parts - t["total_ms"]) < 0.01, t
        assert all(t[k] >= 0.0 for k in TIMING_PARTS), t
        # the named phases, not the residual, carry the window
        assert t["other_ms"] <= 0.2 * t["total_ms"] + 5.0, t
        # server-side nesting: the optimizer apply happens INSIDE the
        # barrier wait (sync mode blocks until the window commits)
        assert 0.0 < t["apply_ms"] <= t["barrier_wait_ms"] + 1.0, t

        # per-pass sums ride the pass stats...
        for k in ("push_ms", "barrier_wait_ms", "pull_ms", "apply_ms",
                  "compute_ms"):
            assert stats[k] > 0.0
        assert stats["remote_windows"] == stats["batches"]
        assert stats["async_stale_rejects"] == 0
        # ...and the metrics.jsonl row (satellite: single-file pass
        # history covers distributed runs)
        tr.append_metrics(str(tmp_path), extra=stats)
        with open(tmp_path / "metrics.jsonl") as f:
            rec = json.loads(f.readlines()[-1])
        assert rec["push_ms"] == stats["push_ms"]
        assert rec["barrier_wait_ms"] == stats["barrier_wait_ms"]
        assert rec["pull_ms"] == stats["pull_ms"]
        assert rec["async_stale_rejects"] == 0
        # a second pass resets the sums (per-pass, not cumulative)
        stats2 = tr.train_one_pass(batches=None)
        assert stats2["remote_windows"] == stats2["batches"]
        upd.drain_and_leave()
    finally:
        srv.stop_background(drain=False)


def test_async_timing_counts_stale_rejects():
    """Async mode: the pass row's async_stale_rejects matches the
    server's refusals and the window timing carries push/staleness."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.optim.remote_updater import RemoteParameterUpdater
    from paddle_tpu.pserver.server import ParameterServer
    from paddle_tpu.trainer.trainer import Trainer

    srv = ParameterServer(port=0, mode="async", max_staleness=8,
                          beat_timeout_s=60.0)
    host, port = srv.start_background()
    try:
        cfg = parse_config(CONFIG, CONFIG_ARGS)
        upd = RemoteParameterUpdater(cfg.model_config, cfg.opt_config,
                                     [(host, port)])
        tr = Trainer(cfg, seed=1, updater=upd)
        stats = tr.train_one_pass(batches=None)
        assert stats["push_ms"] > 0.0
        assert stats["async_stale_rejects"] == 0   # single trainer
        t = upd.last_window_timing
        assert "staleness" in t and t["staleness"] >= 0
        # barrier_wait never happened (no barrier in async)
        assert t["barrier_wait_ms"] == 0.0
        upd.drain_and_leave()
    finally:
        srv.stop_background(drain=False)
