"""Benchmark: all five BASELINE.md configs.

#1 small-VGG CIFAR-10 training throughput (samples/sec/chip + MFU) — north star
#2 WMT14-style attention seq2seq: training samples/sec + beam-decode
   tokens/sec — north star
#3-5 (BENCH_EXTENDED=0 skips): MNIST small_vgg, IMDB stacked-LSTM
   sentiment, MovieLens embedding-fusion recommendation

Prints ONE JSON line: the primary (VGG) metric at the top level, with the
others nested under "seq2seq"/"mnist"/"sentiment"/"recommendation" — all
carry `vs_baseline` ratios against the measured reference numbers in
BASELINE.json (see tools/measure_baseline.py for how those were measured).

Measurement shape: batches are staged in device HBM and the full per-batch
training step (loss + backward + optimizer, identical to Trainer.train)
runs inside one `lax.scan` — the TPU-native form of a production input
pipeline, where an async host pipeline keeps data resident ahead of
compute (ref: the reference's DoubleBuffer prefetch,
gserver/dataproviders/DataProvider.h:260).  MFU is reported from XLA's own
flop count for the compiled step against the chip's peak.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

def _chip_peak_tflops(dtype: str) -> float:
    import jax
    kind = jax.devices()[0].device_kind.lower()
    # most specific first: 'v5 lite'/'v5e' must not fall through to the
    # bare 'v5' (v5p) entry — that bug under-reported MFU 2.3x
    if "v5 lite" in kind or "v5e" in kind:
        peak = 197.0
    elif "v5" in kind:
        peak = 459.0
    elif "v6" in kind:
        peak = 918.0
    elif "v4" in kind:
        peak = 275.0
    else:
        peak = 197.0  # assume v5e when unknown
    # fp32 peak is half the bf16 peak on TPU
    return peak if dtype == "bfloat16" else peak / 2.0


def _baseline_ratio(value: float, key: str) -> float:
    """value / measured reference samples/sec (0.0 = baseline not measured)."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            base = json.load(f).get("published", {}).get(key, {})
        ref = float(base.get("samples_per_sec", 0.0))
        return round(value / ref, 2) if ref > 0 else 0.0
    except (OSError, ValueError):
        return 0.0


def _step_mfu(tr, batch, samples_per_sec: float, batch_size: int,
              dtype: str) -> float:
    """MFU from XLA's own flop count of the compiled per-batch step."""
    try:
        import jax
        ca = tr._train_step.lower(
            tr.params, tr.opt_state, tr.net_state, batch,
            jax.random.PRNGKey(0)).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        step_flops = float(ca.get("flops", 0.0))
        achieved = step_flops * (samples_per_sec / batch_size)  # flops/sec
        return achieved / (_chip_peak_tflops(dtype) * 1e12)
    except Exception:
        return 0.0


def bench_vgg(dtype: str) -> dict:
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch_size = int(os.environ.get("BENCH_BATCH_SIZE", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "200"))

    cfg = parse_config("demo/image_classification/vgg_16_cifar.py",
                       f"batch_size={batch_size},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(2 + iters):
        x = rng.random((batch_size, 3 * 32 * 32), np.float32).astype(np.float32) - 0.5
        y = rng.integers(0, 10, batch_size).astype(np.int32)
        batches.append({"image": Argument(value=x), "label": Argument(ids=y)})

    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    value = stats["samples_per_sec"]
    return {
        "metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": _baseline_ratio(value, "vgg16_cifar10"),
        "mfu": round(_step_mfu(tr, batches[0], value, batch_size, dtype), 4),
    }


def bench_seq2seq(dtype: str) -> dict:
    """North-star #2 (ref: demo/seqToseq/seqToseq_net.py:70-120): bi-GRU 512
    encoder + additive-attention GRU 512 decoder, vocab 30k — the WMT14
    training shape on synthetic ids (throughput does not depend on token
    values), plus compiled beam-search decode tokens/sec."""
    import time

    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.graph.builder import GraphExecutor
    from paddle_tpu.graph.generator import generate
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    vocab = int(os.environ.get("BENCH_S2S_VOCAB", "30000"))
    hidden = int(os.environ.get("BENCH_S2S_HIDDEN", "512"))
    batch_size = int(os.environ.get("BENCH_S2S_BATCH", "64"))
    seqlen = int(os.environ.get("BENCH_S2S_LEN", "30"))
    iters = int(os.environ.get("BENCH_S2S_ITERS", "50"))

    cfg = parse_config(
        "demo/seqToseq/seqToseq_net.py",
        f"dict_size={vocab},hidden_dim={hidden},batch_size={batch_size},"
        f"compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    full = np.full((batch_size,), seqlen, np.int32)
    batches = []
    for _ in range(2 + iters):
        src = rng.integers(3, vocab, (batch_size, seqlen)).astype(np.int32)
        trg = rng.integers(3, vocab, (batch_size, seqlen)).astype(np.int32)
        batches.append({
            "source_language_word": Argument(ids=src, lengths=full),
            "target_language_word": Argument(ids=trg, lengths=full),
            "target_language_next_word": Argument(ids=trg, lengths=full),
        })
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    train_sps = stats["samples_per_sec"]

    # beam decode tokens/sec: compiled beam search over the trained params
    beam = int(os.environ.get("BENCH_S2S_BEAM", "3"))
    max_len = int(os.environ.get("BENCH_S2S_MAXLEN", "30"))
    gcfg = parse_config(
        "demo/seqToseq/seqToseq_net.py",
        f"dict_size={vocab},hidden_dim={hidden},is_generating=1,"
        f"beam_size={beam},max_length={max_len},compute_dtype={dtype}")
    gex = GraphExecutor(gcfg.model_config)
    gparams = {p.name: tr.params[p.name]
               for p in gcfg.model_config.parameters}
    feed = {"source_language_word":
            Argument(ids=batches[0]["source_language_word"].ids,
                     lengths=full)}
    seqs, _ = generate(gex, gparams, feed)          # compile + warmup
    np.asarray(seqs)
    # enough reps that per-call dispatch latency jitter (the beam program is
    # one short jitted call) averages out
    reps = int(os.environ.get("BENCH_S2S_DECODE_REPS", "10"))
    t0 = time.perf_counter()
    for _ in range(reps):
        seqs, _ = generate(gex, gparams, feed)
    n_tokens = int(np.asarray(seqs).shape[0]) * max_len * reps
    decode_tps = n_tokens / (time.perf_counter() - t0)

    return {
        "metric": "wmt14_seq2seq_train_samples_per_sec_per_chip",
        "value": round(train_sps, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": _baseline_ratio(train_sps, "wmt14_seq2seq"),
        "beam_decode_tokens_per_sec": round(decode_tps, 2),
    }


def bench_mnist(dtype: str) -> dict:
    """small_vgg on MNIST 1x28x28 (ref: demo/mnist/vgg_16_mnist.py)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch = int(os.environ.get("BENCH_MNIST_BATCH", "128"))
    iters = int(os.environ.get("BENCH_MNIST_ITERS", "50"))
    cfg = parse_config("demo/mnist/vgg_16_mnist.py",
                       f"batch_size={batch},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)
    batches = [{"pixel": Argument(value=(rng.random((batch, 784), np.float32)
                                         .astype(np.float32) - 0.5)),
                "label": Argument(ids=rng.integers(0, 10, batch).astype(np.int32))}
               for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "mnist_vgg_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "mnist_vgg")}


def bench_sentiment(dtype: str) -> dict:
    """stacked_lstm_net on IMDB-shaped data (ref: demo/sentiment/
    trainer_config.py — emb 128, 3 alternating fc+lstm pairs hid 512)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    vocab = int(os.environ.get("BENCH_SENT_VOCAB", "30000"))
    batch = int(os.environ.get("BENCH_SENT_BATCH", "128"))
    seqlen = int(os.environ.get("BENCH_SENT_LEN", "100"))
    iters = int(os.environ.get("BENCH_SENT_ITERS", "30"))
    cfg = parse_config(
        "demo/sentiment/trainer_config.py",
        f"dict_dim={vocab},batch_size={batch},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)
    full = np.full((batch,), seqlen, np.int32)
    batches = [{"word": Argument(ids=rng.integers(0, vocab, (batch, seqlen))
                                 .astype(np.int32), lengths=full),
                "label": Argument(ids=rng.integers(0, 2, batch).astype(np.int32))}
               for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "imdb_sentiment_lstm_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "imdb_sentiment_lstm")}


def bench_recommendation(dtype: str) -> dict:
    """MovieLens embedding-fusion regression at 1M dims (ref:
    demo/recommendation/trainer_config.py; movie 3952, user 6040,
    title vocab 5100, batch 1600)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch = int(os.environ.get("BENCH_REC_BATCH", "1600"))
    iters = int(os.environ.get("BENCH_REC_ITERS", "30"))
    title_len = 15
    cfg = parse_config(
        "demo/recommendation/trainer_config.py",
        f"batch_size={batch},movie_dim=3952,user_dim=6040,title_vocab=5100,"
        f"compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)

    def one():
        ids = lambda n: rng.integers(0, n, batch).astype(np.int32)
        # genres: sparse-row slot — 3 multi-hot ids per sample
        gen = rng.integers(0, 18, (batch, 3)).astype(np.int32)
        return {
            "movie_id": Argument(ids=ids(3952)),
            "title": Argument(ids=rng.integers(0, 5100, (batch, title_len))
                              .astype(np.int32),
                              lengths=np.full((batch,), title_len, np.int32)),
            "genres": Argument(ids=gen,
                               sparse_vals=np.ones((batch, 3), np.float32),
                               sparse_dim=18),
            "user_id": Argument(ids=ids(6040)),
            "gender": Argument(ids=ids(2)),
            "age": Argument(ids=ids(7)),
            "occupation": Argument(ids=ids(21)),
            "rating": Argument(value=(rng.random((batch, 1), np.float32)
                                      .astype(np.float32) * 2 - 1)),
        }

    batches = [one() for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "movielens_recsys_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "movielens_recsys")}


def main() -> None:
    import time
    import traceback

    # bfloat16 is the TPU-native float: fp32 master params, bf16 matmuls on
    # the MXU, fp32 softmax/BN-stats/loss (BENCH_DTYPE=float32 opts out)
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    # wall-clock budget for the non-headline benches: a degraded TPU tunnel
    # (slow remote compiles) must not stall the whole record — whatever
    # doesn't fit is reported as skipped rather than hanging the driver
    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "900"))
    t0 = time.perf_counter()

    vgg = bench_vgg(dtype)
    out = dict(vgg)

    extras = []
    if os.environ.get("BENCH_SKIP_S2S", "0") != "1":
        extras.append(("seq2seq", bench_seq2seq))
    if os.environ.get("BENCH_EXTENDED", "1") != "0":
        # the three remaining BASELINE.md configs (BENCH_EXTENDED=0 skips)
        extras += [("mnist", bench_mnist), ("sentiment", bench_sentiment),
                   ("recommendation", bench_recommendation)]
    for key, fn in extras:
        if time.perf_counter() - t0 > budget:
            out[key] = {"skipped": f"time budget {budget:.0f}s exhausted"}
            continue
        try:
            out[key] = fn(dtype)
        except Exception as e:       # one failing extra must not kill the record
            traceback.print_exc()
            out[key] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
