"""Benchmark: all five BASELINE.md configs.

#1 small-VGG CIFAR-10 training throughput (samples/sec/chip + MFU) — north star
#2 WMT14-style attention seq2seq: training samples/sec + beam-decode
   tokens/sec — north star
#3-5 (BENCH_EXTENDED=0 skips): MNIST small_vgg, IMDB stacked-LSTM
   sentiment, MovieLens embedding-fusion recommendation

Prints ONE JSON line: the primary (VGG) metric at the top level, with the
others nested under "seq2seq"/"mnist"/"sentiment"/"recommendation" — all
carry `vs_baseline` ratios against the measured reference numbers in
BASELINE.json (see tools/measure_baseline.py for how those were measured).

Measurement shape: batches are staged in device HBM and the full per-batch
training step (loss + backward + optimizer, identical to Trainer.train)
runs inside one `lax.scan` — the TPU-native form of a production input
pipeline, where an async host pipeline keeps data resident ahead of
compute (ref: the reference's DoubleBuffer prefetch,
gserver/dataproviders/DataProvider.h:260).  MFU is reported from XLA's own
flop count for the compiled step against the chip's peak.

Failure model (ref: the reference's benchmark mode always emits a timing
record — paddle/trainer/TrainerBenchmark.cpp, TrainerMain.cpp:106-107):
the orchestrating process NEVER imports jax — a wedged TPU tunnel blocks
every in-process backend init forever, so all device work happens in child
processes (`bench.py --bench NAME`) under hard timeouts.  The record always
prints and exits 0: on an unhealthy/dead backend it carries `"error"` plus
clearly-labeled last-known-good numbers from PERF_LOG.jsonl.  Every
successful run is appended to PERF_LOG.jsonl (timestamped) so a failed
end-of-round capture still leaves verifiable on-TPU evidence.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_REPO = os.path.dirname(os.path.abspath(__file__))
# BENCH_PERF_LOG redirects the evidence log — tools/tpu_measure.py's
# --rehearse mode points it at a scratch dir so CPU dry-runs can never
# poison the real last-known-good record
_PERF_LOG = os.environ.get("BENCH_PERF_LOG") or \
    os.path.join(_REPO, "PERF_LOG.jsonl")

def _chip_peak_tflops(dtype: str) -> float:
    import jax
    kind = jax.devices()[0].device_kind.lower()
    # most specific first: 'v5 lite'/'v5e' must not fall through to the
    # bare 'v5' (v5p) entry — that bug under-reported MFU 2.3x
    if "v5 lite" in kind or "v5e" in kind:
        peak = 197.0
    elif "v5" in kind:
        peak = 459.0
    elif "v6" in kind:
        peak = 918.0
    elif "v4" in kind:
        peak = 275.0
    else:
        peak = 197.0  # assume v5e when unknown
    # fp32 peak is half the bf16 peak on TPU
    return peak if dtype == "bfloat16" else peak / 2.0


def _baseline_ratio(value: float, key: str) -> float:
    """value / measured reference samples/sec (0.0 = baseline not measured)."""
    try:
        with open(os.path.join(_REPO, "BASELINE.json")) as f:
            base = json.load(f).get("published", {}).get(key, {})
        ref = float(base.get("samples_per_sec", 0.0))
        return round(value / ref, 2) if ref > 0 else 0.0
    except (OSError, ValueError):
        return 0.0


def _era_gpu_ratio(value: float, key: str) -> float:
    """value / the analytic TITAN-X-era Paddle-GPU bound (BASELINE.md 'The
    honest bar') — the ratio the north-star actually asks about; the
    torch-CPU vs_baseline above runs on this host's single core and mostly
    measures the host, not the target."""
    try:
        with open(os.path.join(_REPO, "BASELINE.json")) as f:
            est = json.load(f).get("analytic_era_gpu", {}).get(key, {})
        ref = float(est.get("titanx_samples_per_sec", 0.0))
        return round(value / ref, 2) if ref > 0 else 0.0
    except (OSError, ValueError):
        return 0.0


def _step_mfu(tr, batch, samples_per_sec: float, batch_size: int,
              dtype: str) -> float:
    """MFU from XLA's own flop count of the compiled per-batch step."""
    try:
        import jax
        ca = tr._train_step.lower(
            tr.params, tr.opt_state, tr.net_state, batch,
            jax.random.PRNGKey(0)).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        step_flops = float(ca.get("flops", 0.0))
        achieved = step_flops * (samples_per_sec / batch_size)  # flops/sec
        return achieved / (_chip_peak_tflops(dtype) * 1e12)
    except Exception:
        return 0.0


def bench_vgg(dtype: str) -> dict:
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch_size = int(os.environ.get("BENCH_BATCH_SIZE", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "200"))

    cfg = parse_config("demo/image_classification/vgg_16_cifar.py",
                       f"batch_size={batch_size},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(2 + iters):
        x = rng.random((batch_size, 3 * 32 * 32), np.float32).astype(np.float32) - 0.5
        y = rng.integers(0, 10, batch_size).astype(np.int32)
        batches.append({"image": Argument(value=x), "label": Argument(ids=y)})

    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    value = stats["samples_per_sec"]
    return {
        "metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": _baseline_ratio(value, "vgg16_cifar10"),
        "vs_era_gpu": _era_gpu_ratio(value, "vgg16_cifar10"),
        "mfu": round(_step_mfu(tr, batches[0], value, batch_size, dtype), 4),
    }


def bench_seq2seq(dtype: str) -> dict:
    """North-star #2 (ref: demo/seqToseq/seqToseq_net.py:70-120): bi-GRU 512
    encoder + additive-attention GRU 512 decoder, vocab 30k — the WMT14
    training shape on synthetic ids (throughput does not depend on token
    values), plus compiled beam-search decode tokens/sec.

    BENCH_S2S_PHASE isolates the wedge-prone halves (the tunnel died inside
    this bench in rounds 2 AND 4; which half kills it was never observed):
    "train" stops after the training measurement, "decode" skips training
    and measures only the compiled beam program (throughput is
    params-value-independent, so freshly-initialized params time the same
    programs), "full" (default) is both.
    """
    import time

    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.graph.builder import GraphExecutor
    from paddle_tpu.graph.generator import generate
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    phase = os.environ.get("BENCH_S2S_PHASE", "full")
    vocab = int(os.environ.get("BENCH_S2S_VOCAB", "30000"))
    hidden = int(os.environ.get("BENCH_S2S_HIDDEN", "512"))
    batch_size = int(os.environ.get("BENCH_S2S_BATCH", "64"))
    seqlen = int(os.environ.get("BENCH_S2S_LEN", "30"))
    iters = int(os.environ.get("BENCH_S2S_ITERS", "50"))

    cfg = parse_config(
        "demo/seqToseq/seqToseq_net.py",
        f"dict_size={vocab},hidden_dim={hidden},batch_size={batch_size},"
        f"compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    full = np.full((batch_size,), seqlen, np.int32)
    batches = []
    for _ in range(2 + iters):
        src = rng.integers(3, vocab, (batch_size, seqlen)).astype(np.int32)
        trg = rng.integers(3, vocab, (batch_size, seqlen)).astype(np.int32)
        batches.append({
            "source_language_word": Argument(ids=src, lengths=full),
            "target_language_word": Argument(ids=trg, lengths=full),
            "target_language_next_word": Argument(ids=trg, lengths=full),
        })

    if phase == "decode":
        record = {
            "metric": "wmt14_seq2seq_beam_decode_tokens_per_sec",
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "phase": "decode-only (BENCH_S2S_PHASE=decode)",
        }
    else:
        stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
        train_sps = stats["samples_per_sec"]

        # bank the train measurement NOW: the tunnel wedged during the
        # decode half of this bench in rounds 2 AND 4, and _spawn recovers
        # the LAST BENCH_JSON line from a killed child's partial output —
        # so a decode wedge must not take the already-measured train number
        # with it.  Built once; decode fields extend this dict at the end.
        record = {
            "metric": "wmt14_seq2seq_train_samples_per_sec_per_chip",
            "value": round(train_sps, 2),
            "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(train_sps, "wmt14_seq2seq"),
            "vs_era_gpu": _era_gpu_ratio(train_sps, "wmt14_seq2seq"),
            "mfu": round(_step_mfu(tr, batches[0], train_sps, batch_size,
                                   dtype), 4),
        }
        if phase == "train":
            record["beam_decode"] = "skipped (BENCH_S2S_PHASE=train)"
            return record
        print("BENCH_JSON:" + json.dumps(
            dict(record, beam_decode="pending (wedge-risk phase; superseded "
                                     "by the final record if decode "
                                     "completes)")), flush=True)

    # beam decode tokens/sec: compiled beam search over the trained params
    beam = int(os.environ.get("BENCH_S2S_BEAM", "3"))
    max_len = int(os.environ.get("BENCH_S2S_MAXLEN", "30"))
    gcfg = parse_config(
        "demo/seqToseq/seqToseq_net.py",
        f"dict_size={vocab},hidden_dim={hidden},is_generating=1,"
        f"beam_size={beam},max_length={max_len},compute_dtype={dtype}")
    gex = GraphExecutor(gcfg.model_config)
    gparams = {p.name: tr.params[p.name]
               for p in gcfg.model_config.parameters}
    feed = {"source_language_word":
            Argument(ids=batches[0]["source_language_word"].ids,
                     lengths=full)}
    seqs, _ = generate(gex, gparams, feed)          # compile + warmup
    np.asarray(seqs)
    # the beam program is one short jitted call, so per-call dispatch
    # jitter dominates — report median +- IQR over fixed reps instead of
    # one mean (PERF.md recorded 58k-105k tok/s run-to-run on the mean)
    reps = int(os.environ.get("BENCH_S2S_DECODE_REPS", "10"))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        seqs, _ = generate(gex, gparams, feed)
        np.asarray(seqs)
        times.append(time.perf_counter() - t0)
    n_tokens = int(np.asarray(seqs).shape[0]) * max_len
    q1, med, q3 = np.percentile(times, [25, 50, 75])

    record.update({
        "beam_decode_tokens_per_sec": round(n_tokens / med, 2),
        "beam_decode_tokens_per_sec_iqr": [round(n_tokens / q3, 2),
                                           round(n_tokens / q1, 2)],
    })
    if phase == "decode":
        record["value"] = record["beam_decode_tokens_per_sec"]
    return record


def bench_mnist(dtype: str) -> dict:
    """small_vgg on MNIST 1x28x28 (ref: demo/mnist/vgg_16_mnist.py)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch = int(os.environ.get("BENCH_MNIST_BATCH", "128"))
    iters = int(os.environ.get("BENCH_MNIST_ITERS", "50"))
    cfg = parse_config("demo/mnist/vgg_16_mnist.py",
                       f"batch_size={batch},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)
    batches = [{"pixel": Argument(value=(rng.random((batch, 784), np.float32)
                                         .astype(np.float32) - 0.5)),
                "label": Argument(ids=rng.integers(0, 10, batch).astype(np.int32))}
               for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "mnist_vgg_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "mnist_vgg")}


def bench_sentiment(dtype: str) -> dict:
    """stacked_lstm_net on IMDB-shaped data (ref: demo/sentiment/
    trainer_config.py — emb 128, 3 alternating fc+lstm pairs hid 512)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    vocab = int(os.environ.get("BENCH_SENT_VOCAB", "30000"))
    batch = int(os.environ.get("BENCH_SENT_BATCH", "128"))
    seqlen = int(os.environ.get("BENCH_SENT_LEN", "100"))
    iters = int(os.environ.get("BENCH_SENT_ITERS", "30"))
    cfg = parse_config(
        "demo/sentiment/trainer_config.py",
        f"dict_dim={vocab},batch_size={batch},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)
    full = np.full((batch,), seqlen, np.int32)
    batches = [{"word": Argument(ids=rng.integers(0, vocab, (batch, seqlen))
                                 .astype(np.int32), lengths=full),
                "label": Argument(ids=rng.integers(0, 2, batch).astype(np.int32))}
               for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "imdb_sentiment_lstm_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "imdb_sentiment_lstm")}


def bench_recommendation(dtype: str) -> dict:
    """MovieLens embedding-fusion regression at 1M dims (ref:
    demo/recommendation/trainer_config.py; movie 3952, user 6040,
    title vocab 5100, batch 1600)."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch = int(os.environ.get("BENCH_REC_BATCH", "1600"))
    iters = int(os.environ.get("BENCH_REC_ITERS", "30"))
    title_len = 15
    cfg = parse_config(
        "demo/recommendation/trainer_config.py",
        f"batch_size={batch},movie_dim=3952,user_dim=6040,title_vocab=5100,"
        f"compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)

    def one():
        ids = lambda n: rng.integers(0, n, batch).astype(np.int32)
        # genres: sparse-row slot — 3 multi-hot ids per sample
        gen = rng.integers(0, 18, (batch, 3)).astype(np.int32)
        return {
            "movie_id": Argument(ids=ids(3952)),
            "title": Argument(ids=rng.integers(0, 5100, (batch, title_len))
                              .astype(np.int32),
                              lengths=np.full((batch,), title_len, np.int32)),
            "genres": Argument(ids=gen,
                               sparse_vals=np.ones((batch, 3), np.float32),
                               sparse_dim=18),
            "user_id": Argument(ids=ids(6040)),
            "gender": Argument(ids=ids(2)),
            "age": Argument(ids=ids(7)),
            "occupation": Argument(ids=ids(21)),
            "rating": Argument(value=(rng.random((batch, 1), np.float32)
                                      .astype(np.float32) * 2 - 1)),
        }

    batches = [one() for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    v = stats["samples_per_sec"]
    return {"metric": "movielens_recsys_train_samples_per_sec_per_chip",
            "value": round(v, 2), "unit": "samples/sec/chip",
            "vs_baseline": _baseline_ratio(v, "movielens_recsys")}


def bench_lm(dtype: str) -> dict:
    """Transformer-LM family (beyond-reference flagship): train tokens/s +
    MFU at a GPT-small-ish shape, and KV-cache greedy decode tokens/s
    (median over reps — the whole decode is one jitted scan, so per-call
    dispatch jitter demands a robust statistic).  The full per-length /
    per-impl sweep lives in tools/bench_lm.py; this is the compact record
    for the driver's BENCH capture."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    vocab = int(os.environ.get("BENCH_LM_VOCAB", "32000"))
    dim = int(os.environ.get("BENCH_LM_DIM", "512"))
    layers = int(os.environ.get("BENCH_LM_LAYERS", "8"))
    heads = int(os.environ.get("BENCH_LM_HEADS", "8"))
    seqlen = int(os.environ.get("BENCH_LM_LEN", "512"))
    batch = int(os.environ.get("BENCH_LM_BATCH", "64"))
    iters = int(os.environ.get("BENCH_LM_ITERS", "20"))

    cfg = parse_config(
        "demo/model_zoo/transformer_lm.py",
        f"vocab={vocab},dim={dim},layers={layers},heads={heads},"
        f"batch_size={batch},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)
    rng = np.random.default_rng(0)
    full = np.full((batch,), seqlen, np.int32)
    batches = [{
        "tokens": Argument(ids=rng.integers(2, vocab, (batch, seqlen))
                           .astype(np.int32), lengths=full),
        "next_tokens": Argument(ids=rng.integers(2, vocab, (batch, seqlen))
                                .astype(np.int32), lengths=full),
    } for _ in range(2 + iters)]
    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    tps = stats["samples_per_sec"] * seqlen

    dec_b = int(os.environ.get("BENCH_LM_DECODE_BATCH", "32"))
    max_new = int(os.environ.get("BENCH_LM_MAX_NEW", "64"))
    reps = int(os.environ.get("BENCH_LM_DECODE_REPS", "5"))
    ids = rng.integers(2, vocab, (dec_b, seqlen - max_new)).astype(np.int32)
    # the one shared timing loop — tools/bench_lm.py's per-context sweep
    # uses the identical methodology
    from tools.bench_lm import time_decode
    times = time_decode(tr, ids, max_new, use_cache=True, reps=reps)
    decode_tps = dec_b * max_new / float(np.median(times))

    return {
        "metric": "transformer_lm_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"vocab={vocab} dim={dim} L={layers} H={heads} T={seqlen}",
        "mfu": round(_step_mfu(tr, batches[0], tps, batch * seqlen,
                               dtype), 4),
        "kv_cache_decode_tokens_per_sec": round(decode_tps, 1),
    }


def bench_serving(dtype: str) -> dict:
    """Continuous-batching LM serving throughput (serving/engine.py): a
    mixed-length greedy workload through the paged-KV slot engine, closed
    loop (all requests at t=0 — peak tokens/sec at full slot pressure).
    Exactness against lm_generate is tests/test_serving.py's job; this
    measures tokens/sec, slot occupancy, and that the decode step stayed
    at ONE compiled signature.  The per-rate occupancy curve lives in
    tools/bench_serving.py; this is the compact record for the driver's
    BENCH capture."""
    import argparse

    import numpy as np

    from tools.bench_serving import (build_engine, make_requests,
                                     run_workload, warm_workload)

    # ONE engine construction recipe — tools/bench_serving.py's — fed from
    # the env knobs, so the banked record and the sweep tool can never
    # measure differently-built engines
    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        dtype=dtype)
    n_reqs = int(os.environ.get("BENCH_SERVE_REQS", "64"))
    lo = int(os.environ.get("BENCH_SERVE_PROMPT_LO", "32"))
    hi = int(os.environ.get("BENCH_SERVE_PROMPT_HI", "256"))
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "64"))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))

    eng = build_engine(args)
    base = dict(n=n_reqs, prompt_lo=lo, prompt_hi=hi, max_new=max_new,
                vocab=args.vocab)
    rep_sets = [make_requests(seed=1 + rep, **base) for rep in range(reps)]
    warm_workload(eng, [make_requests(seed=0, **base)] + rep_sets)
    vals, occs, step_s, req_s = [], [], [], []
    for reqs in rep_sets:
        rec = run_workload(eng, reqs)
        vals.append(rec["tokens"] / rec["seconds"])
        occs.append(rec["occupancy"])
        step_s += rec["step_seconds"]
        req_s += rec["req_seconds"]
    # tracing-overhead probe: the SAME workload (fresh Request objects,
    # same seeds — the buckets are already compiled) with the span tracer
    # AND the flight recorder on (the full serving-observability stack a
    # production replica runs); the acceptance budget is <= 2% off->on,
    # and this keeps the measured number in the perf trajectory
    from paddle_tpu.obs import get_flight_recorder, get_tracer
    tracer = get_tracer()
    flight = get_flight_recorder()
    tracer.enabled = True
    flight.enabled = True
    try:
        on_vals = []
        for rep in range(reps):
            rec = run_workload(eng, make_requests(seed=1 + rep, **base))
            on_vals.append(rec["tokens"] / rec["seconds"])
    finally:
        tracer.enabled = False
        flight.enabled = False
    off_med, on_med = float(np.median(vals)), float(np.median(on_vals))
    overhead_pct = 100.0 * (off_med - on_med) / off_med if off_med else 0.0
    # health-plane sampler-overhead probe (the fleet trace probe's
    # interleaved-cycle discipline): the SAME workload with
    # obs/timeseries.py's HistorySampler ticking at an AGGRESSIVE 50ms
    # period (production runs 5s) against a registry of engine-state
    # collectors, flipped LIVE between passes.  The engine keeps warming
    # monotonically across passes, so a fixed order reads the warming
    # trend as sampler cost — cycles alternate (off,on / on,off) and the
    # MEDIAN of the per-cycle pairwise pcts cancels a linear drift.
    # Budget <= 2% (negative = noise); the scalar rides _assemble_lkg.
    from paddle_tpu.obs.metrics import MetricsRegistry
    from paddle_tpu.obs.timeseries import HistorySampler, MetricHistory

    reg = MetricsRegistry()
    reg.register_collector(lambda: [
        ("serving_tokens_generated_total", "counter", None,
         float(eng.tokens_generated)),
        ("serving_prefix_hits_total", "counter", None,
         float(eng.n_prefix_hits)),
        ("serving_prefix_misses_total", "counter", None,
         float(eng.n_prefix_misses)),
        ("serving_spec_drafted_total", "counter", None,
         float(eng.n_spec_drafted)),
        ("serving_spec_accepted_total", "counter", None,
         float(eng.n_spec_accepted)),
        ("serving_num_slots", "gauge", None, float(len(eng.slots))),
    ])
    sampler = HistorySampler(
        MetricHistory(reg, resolution_s=0.05, retention_s=60.0),
        period_s=0.05)
    sampler.enabled = False
    sampler.start()
    cycle_pcts = []
    try:
        # one DISCARDED pass first: the trace probe just perturbed the
        # engine's rhythm, and the first probe pass re-settles it — its
        # transient must not land on whichever side runs first
        run_workload(eng, make_requests(seed=1, **base))
        cycles = int(os.environ.get("BENCH_SERVE_HISTORY_CYCLES", "3"))
        for cyc in range(cycles):
            order = (False, True) if cyc % 2 == 0 else (True, False)
            pair = {}
            for on in order:
                sampler.enabled = on
                rec = run_workload(
                    eng, make_requests(seed=1 + (cyc % reps), **base))
                pair[on] = rec["tokens"] / rec["seconds"]
            if pair[False]:
                cycle_pcts.append(
                    100.0 * (pair[False] - pair[True]) / pair[False])
    finally:
        sampler.stop()
    history_overhead_pct = float(np.median(cycle_pcts)) if cycle_pcts \
        else 0.0
    tok_p50, tok_p99 = (np.percentile(step_s, [50, 99]) * 1e3
                        if step_s else (0.0, 0.0))
    return {
        "metric": "lm_serving_tok_per_sec",
        "value": round(float(np.median(vals)), 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"vocab={args.vocab} dim={args.dim} L={args.layers} "
                  f"H={args.heads} slots={args.slots} page={args.page_size} "
                  f"prompts={lo}-{hi} max_new={max_new}",
        "occupancy": round(float(np.mean(occs)), 3),
        # the serving-latency companion metric: p99 busy-step duration =
        # p99 inter-token latency a live request observed (the SLO number;
        # tools/bench_serving.py reports the same fields per arrival rate)
        "tok_latency_ms_p50": round(float(tok_p50), 3),
        "lm_serving_p99_tok_latency_ms": round(float(tok_p99), 3),
        "req_latency_ms_p99": round(
            float(np.percentile(req_s, 99) * 1e3) if req_s else 0.0, 3),
        # tok/s cost of lifecycle tracing (negative = noise): tracked so a
        # tracer hot-path regression shows in the perf trajectory
        "lm_serving_trace_overhead_pct": round(overhead_pct, 2),
        # tok/s cost of the health-plane sampler at 100x production rate
        # (negative = noise): a registry-walk hot-path regression shows
        # here before it shows on a fleet
        "lm_serving_history_overhead_pct": round(history_overhead_pct, 2),
        "decode_signatures": eng._decode_step._cache_size(),
    }


def bench_serving_prefix(dtype: str) -> dict:
    """Prefix-cache effectiveness record (serving/prefix_tree.py): the
    Zipf prefix-skew workload through ONE engine, cache off then on —
    tools/bench_serving.py --prefix-skew is the sweep tool, this is the
    compact record for the driver's BENCH capture.  Headline = the hit
    rate; the companions are the prefill tokens saved and the first-token
    p50 against the no-cache baseline (the latency the cache exists to
    cut).  Exactness against lm_generate is tests/test_prefix_cache.py's
    job."""
    import argparse

    from tools.bench_serving import build_engine, measure_prefix_skew

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        dtype=dtype)
    wl = dict(
        n=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prefix_pool=int(os.environ.get("BENCH_SERVE_PREFIX_POOL", "8")),
        prefix_len=int(os.environ.get("BENCH_SERVE_PREFIX_LEN", "128")),
        prefix_skew=float(os.environ.get("BENCH_SERVE_PREFIX_SKEW", "1.0")),
        suffix_lo=int(os.environ.get("BENCH_SERVE_SUFFIX_LO", "16")),
        suffix_hi=int(os.environ.get("BENCH_SERVE_SUFFIX_HI", "64")),
        max_new=int(os.environ.get("BENCH_SERVE_MAX_NEW", "64")),
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))

    eng = build_engine(args)
    m = measure_prefix_skew(eng, wl, reps, seed=0)
    share = wl["prefix_len"] / (
        wl["prefix_len"] + (wl["suffix_lo"] + wl["suffix_hi"]) / 2.0)
    return {
        "metric": "lm_serving_prefix_hit_rate",
        "value": round(m["hit_rate"], 4),
        "unit": "hit fraction",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"pool={wl['prefix_pool']} prefix={wl['prefix_len']} "
                  f"skew={wl['prefix_skew']} "
                  f"suffix={wl['suffix_lo']}-{wl['suffix_hi']} "
                  f"slots={args.slots} page={args.page_size} "
                  f"reqs={wl['n']} max_new={wl['max_new']}",
        "prefix_share_configured": round(share, 3),
        "lm_serving_prefill_tokens_saved_total": m["tokens_saved"],
        "first_tok_ms_p50": m["first_tok_ms_p50"],
        "baseline_first_tok_ms_p50": m["baseline_first_tok_ms_p50"],
        "tokens_per_sec_median": round(m["cached_tok_per_sec"], 1),
        "baseline_tokens_per_sec_median":
            round(m["baseline_tok_per_sec"], 1),
        "prefix_evictions": m["evictions"],
        "prefix_cow": m["cow"],
        "decode_sig_stable": m["decode_sig_stable"],
    }


def bench_serving_chunked(dtype: str) -> dict:
    """Chunked-prefill effectiveness record (mixed prefill/decode steps):
    the heavy-tail prompt workload through ONE engine, chunking off
    (legacy whole-prompt prefill — the head-of-line-blocking baseline)
    then on — tools/bench_serving.py --prompt-dist heavy-tail is the
    sweep tool, this is the compact record for the driver's BENCH
    capture.  Headline = chunked-on p99 inter-token latency (LOWER is
    better — the SLO chunking bounds by construction); companions are
    the baseline p99s and the first-token tails both sides.  Exactness
    against lm_generate is tests/test_chunked_prefill.py's job."""
    import argparse

    from tools.bench_serving import build_engine, measure_chunked

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        dtype=dtype)
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "64"))
    hi = int(os.environ.get("BENCH_SERVE_HT_PROMPT_HI",
                            str(args.max_context - max_new - 1)))
    wl = dict(
        n=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prompt_lo=int(os.environ.get("BENCH_SERVE_PROMPT_LO", "32")),
        prompt_hi=min(hi, args.max_context - max_new - 1),
        max_new=max_new,
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))
    chunk = int(os.environ.get("BENCH_SERVE_CHUNK", "0")) \
        or 4 * args.page_size

    eng = build_engine(args)
    m = measure_chunked(eng, wl, reps, seed=0, prefill_chunk=chunk)
    return {
        "metric": "lm_serving_p99_itl_chunked_ms",
        "value": m["itl_ms_p99"],
        "unit": "ms (lower is better)",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"vocab={args.vocab} dim={args.dim} L={args.layers} "
                  f"H={args.heads} slots={args.slots} "
                  f"page={args.page_size} "
                  f"prompts={wl['prompt_lo']}-{wl['prompt_hi']}(heavy-tail)"
                  f" max_new={max_new} chunk={m['prefill_chunk']} "
                  f"budget={m['max_step_tokens']}",
        **{k: m[k] for k in (
            "baseline_itl_ms_p50", "baseline_itl_ms_p99", "itl_ms_p50",
            "baseline_first_tok_ms_p50", "baseline_first_tok_ms_p99",
            "first_tok_ms_p50", "first_tok_ms_p99",
            "baseline_tok_per_sec", "chunked_tok_per_sec",
            "prefill_chunks", "p99_itl_improved",
            "p99_first_tok_improved", "sig_stable")},
    }


def bench_serving_fleet(dtype: str) -> dict:
    """Fleet-router effectiveness record (paddle_tpu/fleet/): the
    prefix-skew workload through one router + N replica SUBPROCESSES
    (tools/serve.py — real processes, real TCP), A/B'd three ways: one
    replica direct, router with random placement, router with
    KV-aware affinity placement.  Headline = affinity-arm tokens/s;
    the acceptance companion is `affinity_hit_gt_random` (the per-replica
    prefix caches must hit MORE under affinity routing than under random
    on the same workload — the reason the router is KV-aware at all).
    tools/bench_serving.py --fleet N is the sweep tool.  Exactness
    through the router is tests/test_fleet.py's job."""
    import argparse

    from tools.bench_serving import measure_fleet

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        num_requests=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prefix_pool=int(os.environ.get("BENCH_SERVE_PREFIX_POOL", "8")),
        prefix_len=int(os.environ.get("BENCH_SERVE_PREFIX_LEN", "128")),
        prefix_skew=float(os.environ.get("BENCH_SERVE_PREFIX_SKEW", "1.0")),
        suffix_lo=int(os.environ.get("BENCH_SERVE_SUFFIX_LO", "16")),
        suffix_hi=int(os.environ.get("BENCH_SERVE_SUFFIX_HI", "64")),
        max_new=int(os.environ.get("BENCH_SERVE_MAX_NEW", "64")),
        fleet=int(os.environ.get("BENCH_SERVE_FLEET", "2")),
        concurrency=int(os.environ.get("BENCH_SERVE_FLEET_CONC", "8")),
        trace_overhead=os.environ.get("BENCH_SERVE_FLEET_TRACE",
                                      "1") != "0",
        seed=0, dtype=dtype)
    m = measure_fleet(args)
    return {
        "metric": "lm_serving_fleet_tok_per_sec",
        "value": m["tok_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"fleet={m['fleet']} conc={m['concurrency']} "
                  f"vocab={args.vocab} dim={args.dim} L={args.layers} "
                  f"slots={args.slots} page={args.page_size} "
                  f"pool={args.prefix_pool} prefix={args.prefix_len} "
                  f"reqs={args.num_requests} max_new={args.max_new}",
        # tok/s cost of the FULL fleet tracing stack (router ingress/
        # place/relay spans + replica tracing, flipped LIVE over the
        # trace RPC on the SAME fleet, interleaved off/on cycles)
        # through the router path — the single-engine
        # lm_serving_trace_overhead_pct's fleet sibling, same <= 2%
        # budget; read it against the spread (negative / within
        # spread = noise)
        "lm_serving_fleet_trace_overhead_pct": m["trace_overhead_pct"],
        **{k: m[k] for k in (
            "single_tok_per_sec", "random_tok_per_sec",
            "speedup_vs_single", "hit_rate_affinity", "hit_rate_random",
            "hit_rate_single", "affinity_hit_gt_random",
            "first_tok_ms_p50", "random_first_tok_ms_p50",
            "router_sheds", "router_retries", "trace_off_tok_per_sec",
            "trace_on_tok_per_sec", "trace_overhead_spread_pct",
            "ok", "failures")},
    }


def bench_serving_disagg(dtype: str) -> dict:
    """Disaggregated prefill/decode record (docs/serving.md
    "Disaggregated prefill/decode"): the same long-prompt prefix-skew
    workload through a router + 2 colocated role=both replicas vs a
    router + 1 prefill-role + 1 decode-role replica joined by the
    kv_push page-transfer plane.  Headline = disagg-arm tokens/s;
    companions are the colocated arm, first-token p50/p99 both arms,
    and the transfer ledger (pushes, pages shipped, failures,
    fallbacks — the reconcile gate requires pages genuinely shipped
    with zero failures).  tools/bench_serving.py --disagg is the sweep
    tool.  Cross-replica exactness is tests/test_fleet.py's job."""
    import argparse

    from tools.bench_serving import measure_disagg

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        num_requests=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prefix_pool=int(os.environ.get("BENCH_SERVE_PREFIX_POOL", "8")),
        prefix_len=int(os.environ.get("BENCH_SERVE_PREFIX_LEN", "128")),
        prefix_skew=float(os.environ.get("BENCH_SERVE_PREFIX_SKEW", "1.0")),
        suffix_lo=int(os.environ.get("BENCH_SERVE_SUFFIX_LO", "16")),
        suffix_hi=int(os.environ.get("BENCH_SERVE_SUFFIX_HI", "64")),
        max_new=int(os.environ.get("BENCH_SERVE_MAX_NEW", "64")),
        concurrency=int(os.environ.get("BENCH_SERVE_FLEET_CONC", "8")),
        seed=0, dtype=dtype)
    m = measure_disagg(args)
    return {
        "metric": "lm_serving_disagg_tok_per_sec",
        "value": m["tok_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"conc={m['concurrency']} vocab={args.vocab} "
                  f"dim={args.dim} L={args.layers} slots={args.slots} "
                  f"page={args.page_size} pool={args.prefix_pool} "
                  f"prefix={args.prefix_len} reqs={args.num_requests} "
                  f"max_new={args.max_new}",
        **{k: m[k] for k in (
            "coloc_tok_per_sec", "speedup_vs_coloc",
            "first_tok_ms_p50", "first_tok_ms_p99",
            "coloc_first_tok_ms_p50", "coloc_first_tok_ms_p99",
            "kv_pushes", "kv_push_failures", "kv_fallbacks",
            "pages_shipped", "router_sheds", "router_retries",
            "ok", "failures")},
    }


def bench_serving_tp(dtype: str) -> dict:
    """Tensor-parallel sharded-decode record (docs/serving.md "Sharded
    decode"): the same closed-loop workload on a single-device engine vs
    attention-head/KV-pool sharding over `BENCH_SERVE_TP` devices
    (default 2) — tools/bench_serving.py --mesh-model N is the sweep
    tool, this is the compact record.  Headline = sharded-arm tokens/s;
    companions are the single-device arm, the speedup, and the KV pool
    bytes PER SHARD (the per-chip HBM split that lets one replica serve
    a model bigger than a chip).  Needs >= N local devices — on the CPU
    rehearse the tpu_measure step injects
    XLA_FLAGS=--xla_force_host_platform_device_count.  Token exactness
    across shard counts is tests/test_serving_tp.py's job."""
    import argparse

    from tools.bench_serving import measure_tp

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        num_requests=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prompt_lo=int(os.environ.get("BENCH_SERVE_PROMPT_LO", "32")),
        prompt_hi=int(os.environ.get("BENCH_SERVE_PROMPT_HI", "256")),
        max_new=int(os.environ.get("BENCH_SERVE_MAX_NEW", "64")),
        reps=int(os.environ.get("BENCH_SERVE_REPS", "3")),
        mesh_model=int(os.environ.get("BENCH_SERVE_TP", "2")),
        seed=0, dtype=dtype)
    m = measure_tp(args)
    return {
        "metric": "lm_serving_tp_tok_per_sec",
        "value": m["tok_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"tp={m['mesh_model']} vocab={args.vocab} "
                  f"dim={args.dim} L={args.layers} H={args.heads} "
                  f"slots={args.slots} page={args.page_size} "
                  f"prompts={args.prompt_lo}-{args.prompt_hi} "
                  f"max_new={args.max_new}",
        **{k: m[k] for k in (
            "mesh_model", "single_tok_per_sec", "speedup_vs_single",
            "pool_bytes_per_shard", "single_pool_bytes",
            "pool_shrink_vs_single", "sig_stable")},
    }


def bench_serving_spec(dtype: str) -> dict:
    """Speculative-decoding effectiveness record (docs/serving.md
    "Speculative decoding"): the locally-repetitive workload through ONE
    engine, speculation off (sequential decode — the baseline) then on
    at `BENCH_SERVE_SPEC_K` drafts/slot/step — tools/bench_serving.py
    --spec-k is the sweep tool, this is the compact record for the
    driver's BENCH capture.  Headline = spec-on tokens/s; companions
    are the baseline arm, the accept rate, and the drafted/accepted/
    emitted reconciliation (`reconcile_ok` — the counters must account
    for every token).  Token exactness spec-on vs spec-off is
    tests/test_spec_decode.py's job.

    The adaptive-speculation matrix (tools/bench_serving.py --drafter
    model --spec-dynamic) rides the same record: ngram vs batched
    draft-model (self-speculation) vs decode_mode=auto arms on the
    repetitive AND heavy-tail workloads —
    `lm_serving_spec_model_tok_per_sec`, the auto arm, the effective
    per-slot k the dynamic policy converged to, and the model-vs-ngram
    heavy-tail accept gate (`accept_model_gt_ngram` — the model drafter
    must hold its accept rate exactly where prompt lookup collapses)."""
    import argparse

    from tools.bench_serving import (build_engine, measure_spec,
                                     measure_spec_modes)

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        dtype=dtype)
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "64"))
    spec_k = int(os.environ.get("BENCH_SERVE_SPEC_K", "4"))
    wl = dict(
        n=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prompt_lo=int(os.environ.get("BENCH_SERVE_PROMPT_LO", "32")),
        prompt_hi=min(int(os.environ.get("BENCH_SERVE_PROMPT_HI", "256")),
                      args.max_context - max_new - 1),
        max_new=max_new,
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))

    eng = build_engine(args)
    m = measure_spec(eng, wl, reps, seed=0, spec_k=spec_k)
    # the adaptive matrix reuses the SAME engine (idle knob flips, fixed
    # signature sets) — the heavy-tail workload shares the repetitive
    # one's shape envelope so no new prefill/verify signatures appear
    mm = measure_spec_modes(eng, wl, dict(wl), reps, seed=0,
                            spec_k=spec_k)
    return {
        "metric": "lm_serving_spec_tok_per_sec",
        "value": round(m["spec_tok_per_sec"], 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"spec_k={spec_k} vocab={args.vocab} dim={args.dim} "
                  f"L={args.layers} H={args.heads} slots={args.slots} "
                  f"page={args.page_size} "
                  f"prompts={wl['prompt_lo']}-{wl['prompt_hi']}(repetitive)"
                  f" max_new={max_new} budget={m['max_step_tokens']}",
        "lm_serving_spec_accept_rate": round(m["accept_rate"], 4),
        **{k: m[k] for k in (
            "baseline_tok_per_sec", "speedup_vs_baseline", "drafted",
            "accepted", "chains", "spec_tokens", "tokens",
            "baseline_decode_steps", "spec_decode_steps",
            "reconcile_ok", "sig_stable")},
        "lm_serving_spec_model_tok_per_sec":
            round(mm["model_rep_tok_per_sec"], 1),
        "lm_serving_spec_auto_tok_per_sec":
            round(mm["auto_rep_tok_per_sec"], 1),
        "lm_serving_spec_effective_k":
            round(mm["auto_rep_effective_k"], 3),
        "lm_serving_spec_model_accept_rate_heavy":
            mm["model_heavy_accept_rate"],
        "lm_serving_spec_ngram_accept_rate_heavy":
            mm["ngram_heavy_accept_rate"],
        **{f"modes_{k}": mm[k] for k in (
            "accept_model_gt_ngram", "auto_ok_rep", "auto_ok_heavy",
            "auto_heavy_tok_per_sec", "static_rep_tok_per_sec",
            "static_heavy_tok_per_sec", "scan_heavy_tok_per_sec",
            "off_rep_tok_per_sec", "ngram_rep_tok_per_sec",
            "ngram_heavy_tok_per_sec", "model_heavy_tok_per_sec",
            "sig_stable", "reconcile_ok", "ok")},
    }


def bench_serving_scan(dtype: str) -> dict:
    """Multi-step decode record (docs/serving.md "Multi-step decode"):
    the mixed-length closed-loop workload through ONE engine at
    decode_steps=1 (one dispatch per token — the baseline) then with
    `BENCH_SERVE_DECODE_STEPS` scanned decode bodies per dispatch —
    tools/bench_serving.py --decode-steps is the sweep tool, this is the
    compact record for the driver's BENCH capture.  Headline = scan-arm
    tokens/s; companions are the baseline arm, the flush/step counters
    (`scan_steps == k * scan_flushes` — the ceil(n/k) dispatch
    evidence), and `reconcile_ok`.  On CPU expect speedup <= 1 (PERF.md
    "Reading the multi-step bench"); token exactness across k is
    tests/test_multi_step.py's job."""
    import argparse

    from tools.bench_serving import build_engine, measure_scan

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SLOTS", "16")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        dtype=dtype)
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "64"))
    k = int(os.environ.get("BENCH_SERVE_DECODE_STEPS", "4"))
    wl = dict(
        n=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prompt_lo=int(os.environ.get("BENCH_SERVE_PROMPT_LO", "32")),
        prompt_hi=min(int(os.environ.get("BENCH_SERVE_PROMPT_HI", "256")),
                      args.max_context - max_new - 1),
        max_new=max_new,
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))

    eng = build_engine(args)
    m = measure_scan(eng, wl, reps, seed=0, k=k)
    return {
        "metric": "lm_serving_scan_tok_per_sec",
        "value": round(m["scan_tok_per_sec"], 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"decode_steps={k} vocab={args.vocab} dim={args.dim} "
                  f"L={args.layers} H={args.heads} slots={args.slots} "
                  f"page={args.page_size} "
                  f"prompts={wl['prompt_lo']}-{wl['prompt_hi']} "
                  f"max_new={max_new}",
        **{key: m[key] for key in (
            "baseline_tok_per_sec", "speedup_vs_baseline", "scan_flushes",
            "scan_steps", "tokens", "baseline_decode_steps",
            "scan_decode_steps", "reconcile_ok", "sig_stable")},
    }


def bench_serving_spill(dtype: str) -> dict:
    """Host KV spill tier record (docs/serving.md "KV spill tier"): the
    Zipf prefix-skew workload through ONE engine whose page pool is sized
    BELOW the working set (BENCH_SERVE_SPILL_PAGES), spill tier off then
    on — tools/bench_serving.py --spill-budget is the sweep tool, this is
    the compact record for the driver's BENCH capture.  Headline = the
    spill-on hit rate (the off arm destroys cold prefixes under pressure
    and re-pays their prefill; the on arm restores them from host RAM);
    companions are both arms' hit rates / tokens saved / first-token p50,
    the spill/restore page counters, and the reconcile + signature-
    stability verdicts.  Exactness of restored tokens is
    tests/test_kv_spill.py's job."""
    import argparse

    from tools.bench_serving import build_engine, measure_spill

    args = argparse.Namespace(
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
        dim=int(os.environ.get("BENCH_LM_DIM", "512")),
        layers=int(os.environ.get("BENCH_LM_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_LM_HEADS", "8")),
        slots=int(os.environ.get("BENCH_SERVE_SPILL_SLOTS", "4")),
        page_size=int(os.environ.get("BENCH_SERVE_PAGE", "16")),
        max_context=int(os.environ.get("BENCH_SERVE_CONTEXT", "768")),
        num_pages=int(os.environ.get("BENCH_SERVE_SPILL_PAGES", "96")),
        spill_budget=int(os.environ.get("BENCH_SERVE_SPILL_BUDGET",
                                        str(64 << 20))),
        dtype=dtype)
    wl = dict(
        n=int(os.environ.get("BENCH_SERVE_REQS", "64")),
        prefix_pool=int(os.environ.get("BENCH_SERVE_PREFIX_POOL", "8")),
        prefix_len=int(os.environ.get("BENCH_SERVE_PREFIX_LEN", "128")),
        prefix_skew=float(os.environ.get("BENCH_SERVE_PREFIX_SKEW", "1.0")),
        suffix_lo=int(os.environ.get("BENCH_SERVE_SUFFIX_LO", "16")),
        suffix_hi=int(os.environ.get("BENCH_SERVE_SUFFIX_HI", "64")),
        max_new=int(os.environ.get("BENCH_SERVE_MAX_NEW", "64")),
        vocab=int(os.environ.get("BENCH_LM_VOCAB", "32000")))
    reps = int(os.environ.get("BENCH_SERVE_REPS", "3"))

    eng = build_engine(args)
    m = measure_spill(eng, wl, reps, seed=0, budget=args.spill_budget)
    return {
        "metric": "lm_serving_spill_hit_rate",
        "value": round(m["hit_rate"], 4),
        "unit": "hit fraction",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"budget={args.spill_budget} pages={args.num_pages} "
                  f"pool={wl['prefix_pool']} prefix={wl['prefix_len']} "
                  f"skew={wl['prefix_skew']} "
                  f"suffix={wl['suffix_lo']}-{wl['suffix_hi']} "
                  f"slots={args.slots} page={args.page_size} "
                  f"reqs={wl['n']} max_new={wl['max_new']}",
        "lm_serving_spill_tok_per_sec": round(m["tok_per_sec"], 1),
        **{key: m[key] for key in (
            "off_hit_rate", "hit_rate_improved", "off_tok_per_sec",
            "first_tok_ms_p50", "off_first_tok_ms_p50", "tokens_saved",
            "off_tokens_saved", "spilled_pages", "restored_pages",
            "restore_hits", "restore_tokens_saved", "page_nbytes",
            "reconcile_ok", "sig_stable")},
    }


def bench_train_dist(dtype: str) -> dict:
    """Parameter-server training record (paddle_tpu/pserver/,
    docs/distributed_training.md): K sync trainer PROCESSES
    (tools/train_dist.py) over one tools/pserver.py shard vs a 1-trainer
    fleet through the IDENTICAL machinery — the scaling-efficiency A/B
    of the distributed tier itself.  Headline = K-trainer aggregate
    samples/sec; companions are the single-trainer arm, the efficiency
    (agg / K*single — the sync-barrier + wire tax), and the server's
    commit accounting.  Every process runs the CPU backend (K trainers
    cannot share one chip, and the tier under test is the wire/barrier/
    update machinery, not the matmul).  Bit-exactness vs grad_accum=K is
    tests/test_train_dist.py's job.

    The record also carries `train_dist_trace_overhead_pct` — the
    training-fleet sibling of the serving live-flip probes (<= 2%
    budget): ONE warm pserver, tracing flipped LIVE over the `trace`
    RPC (no restart) between alternating-order off/on fleet runs (the
    on-arms' trainers run --trace-out too, so the probe pays the FULL
    training tracing stack: window/push/barrier/pull spans + wire
    context + shard-side recv/apply spans), median of per-cycle
    pairwise deltas against the reported spread."""
    import signal
    import statistics
    import subprocess
    import tempfile
    import time as _time

    trainers = int(os.environ.get("BENCH_DIST_TRAINERS", "2"))
    passes = int(os.environ.get("BENCH_DIST_PASSES", "2"))
    samples = int(os.environ.get("BENCH_DIST_SAMPLES", "2048"))
    batch = int(os.environ.get("BENCH_DIST_BATCH", "32"))
    dim = int(os.environ.get("BENCH_DIST_DIM", "64"))
    hidden = int(os.environ.get("BENCH_DIST_HIDDEN", "256"))
    to_s = float(os.environ.get("BENCH_DIST_TIMEOUT_S", "600"))
    cfg_args = (f"samples={samples},batch_size={batch},dim={dim},"
                f"hidden={hidden}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn_pserver():
        ps = subprocess.Popen(
            [sys.executable, "tools/pserver.py", "--port", "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        import select

        line = ""
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline and ps.poll() is None:
            # select-gate the read: a bound-but-silent pserver must
            # trip THIS deadline, not block readline() until the
            # queue's outer hard timeout kills the bench undiagnosed
            r, _w, _x = select.select([ps.stdout], [], [], 1.0)
            if not r:
                continue
            line = ps.stdout.readline()
            if line.startswith("PSERVER_JSON:"):
                break
        if not line.startswith("PSERVER_JSON:"):
            stop_pserver(ps)
            raise RuntimeError("pserver never printed its bind line "
                               "within 120s")
        return ps, json.loads(line.split("PSERVER_JSON:", 1)[1])["port"]

    def stop_pserver(ps) -> None:
        if ps.poll() is None:
            ps.send_signal(signal.SIGTERM)
            try:
                ps.wait(timeout=60)
            except subprocess.TimeoutExpired:
                ps.kill()

    def run_trainers(port: int, k: int, extra=()) -> dict:
        procs = [subprocess.Popen(
            [sys.executable, "tools/train_dist.py",
             "--config", "demo/distributed/mlp_dist.py",
             "--config-args", cfg_args,
             "--pserver", f"127.0.0.1:{port}",
             "--rank", str(r), "--trainers", str(k),
             "--passes", str(passes),
             *(a.format(rank=r) for a in extra)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True) for r in range(k)]
        stats = []
        for p in procs:
            out, _err = p.communicate(timeout=to_s)
            if p.returncode != 0:
                raise RuntimeError(f"trainer rc={p.returncode}")
            for ln in out.splitlines():
                if ln.startswith("TRAIN_JSON:"):
                    stats.append(json.loads(
                        ln.split("TRAIN_JSON:", 1)[1]))
        assert len(stats) == k
        total = sum(s["samples"] for s in stats)
        wall = max(s["seconds"] for s in stats)
        return {"samples": total, "wall_s": wall,
                "samples_per_sec": total / wall if wall else 0.0}

    def run_fleet(k: int) -> dict:
        ps, port = spawn_pserver()
        try:
            return run_trainers(port, k)
        finally:
            stop_pserver(ps)

    single = run_fleet(1)
    fleet = run_fleet(trainers)
    eff = (fleet["samples_per_sec"]
           / (trainers * single["samples_per_sec"])
           if single["samples_per_sec"] else 0.0)

    overhead: dict = {}
    if os.environ.get("BENCH_DIST_TRACE", "1") != "0":
        # the live-flip probe: one pserver across every probe arm (fresh
        # servers would read jit warm-up as tracing cost — the PR 13
        # fleet-probe lesson), alternating off/on order so the machine's
        # monotonic warming cancels out of the pairwise deltas.  One
        # discarded fleet first: it pays the server-side compile so
        # neither measured side inherits the transient.
        from paddle_tpu.serving.client import ServingClient

        cycles = max(1, int(os.environ.get("BENCH_DIST_TRACE_CYCLES",
                                           "3")))
        ps, port = spawn_pserver()
        try:
            with tempfile.TemporaryDirectory() as td:
                run_trainers(port, trainers)           # discarded warmup

                def set_tracing(on: bool) -> None:
                    with ServingClient("127.0.0.1", port,
                                       timeout=30) as c:
                        c.trace(pings=1, enable=on)

                offs, ons, pcts = [], [], []
                for cyc in range(cycles):
                    order = (False, True) if cyc % 2 == 0 \
                        else (True, False)
                    pair = {}
                    for on in order:
                        set_tracing(on)
                        extra = (("--trace-out",
                                  os.path.join(td, f"c{cyc}-r{{rank}}"
                                                   f".jsonl"),)
                                 if on else ())
                        r = run_trainers(port, trainers, extra=extra)
                        pair[on] = r["samples_per_sec"]
                        (ons if on else offs).append(r["samples_per_sec"])
                    if pair.get(False):
                        pcts.append(100.0 * (pair[False] - pair[True])
                                    / pair[False])
                overhead = {
                    # training-fleet tracing cost through the full stack;
                    # <= 2% budget, read against the spread (negative /
                    # within spread = noise)
                    "train_dist_trace_overhead_pct":
                        round(statistics.median(pcts), 2) if pcts else 0.0,
                    "trace_overhead_spread_pct":
                        round(max(pcts) - min(pcts), 2) if pcts else 0.0,
                    "trace_off_samples_per_sec":
                        round(statistics.mean(offs), 2) if offs else 0.0,
                    "trace_on_samples_per_sec":
                        round(statistics.mean(ons), 2) if ons else 0.0,
                }
        except Exception as e:  # noqa: BLE001
            # the probe is severable (BENCH_DIST_TRACE=0 is the knob):
            # a transient trainer crash in a probe arm must not discard
            # the already-measured headline record — the freshness
            # gate's need_field check forces a re-probe next window
            overhead = {"trace_probe_error": f"{type(e).__name__}: {e}"}
        finally:
            stop_pserver(ps)

    return {
        "metric": "train_dist_samples_per_sec",
        "value": round(fleet["samples_per_sec"], 2),
        "unit": "samples/sec (fleet aggregate)",
        "vs_baseline": 0.0,       # beyond-reference family: no paddle analog
        "config": f"trainers={trainers} passes={passes} "
                  f"samples={samples} batch={batch} dim={dim} "
                  f"hidden={hidden} (cpu trainers — the tier under test "
                  f"is the wire/barrier/update machinery)",
        "single_samples_per_sec": round(single["samples_per_sec"], 2),
        "scaling_efficiency": round(eff, 4),
        "trainers": trainers,
        "fleet_wall_s": round(fleet["wall_s"], 3),
        **overhead,
    }


BENCHES = {
    "vgg": bench_vgg,
    "seq2seq": bench_seq2seq,
    "lm": bench_lm,
    "serving": bench_serving,
    "serving_prefix": bench_serving_prefix,
    "serving_chunked": bench_serving_chunked,
    "serving_fleet": bench_serving_fleet,
    "serving_disagg": bench_serving_disagg,
    "serving_tp": bench_serving_tp,
    "serving_spec": bench_serving_spec,
    "serving_scan": bench_serving_scan,
    "serving_spill": bench_serving_spill,
    "train_dist": bench_train_dist,
    "mnist": bench_mnist,
    "sentiment": bench_sentiment,
    "recommendation": bench_recommendation,
}


def _child(name: str) -> None:
    """Run ONE bench in this (child) process; print its result as a
    BENCH_JSON line.  A bench may print interim BENCH_JSON lines as
    phases complete (seq2seq banks its train number before the
    wedge-risk decode) — the parent takes the LAST line, so interim
    lines only matter when the child is killed mid-phase.

    Exceptions become {"error": ...} — the child always exits 0 so the
    parent distinguishes "bench failed" (JSON with error) from "backend
    wedged" (timeout/no output).
    """
    import traceback

    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    try:
        out = BENCHES[name](dtype)
    except Exception as e:
        traceback.print_exc()
        out = {"error": f"{type(e).__name__}: {e}"}
    print("BENCH_JSON:" + json.dumps(out), flush=True)


# ---------------------------------------------------------------------------
# Orchestrator (parent) — pure stdlib, never imports jax.
# ---------------------------------------------------------------------------

def _run_group(argv: list[str], timeout_s: float):
    """Run argv in its OWN process group under a hard timeout, SIGKILLing
    the whole group on expiry.  subprocess.run's timeout only kills the
    direct child; a wedged jax child can leave a helper process holding the
    pipe, blocking the parent's drain forever — exactly the tunnel-death
    scenario this orchestrator must survive.  Returns (rc, stdout, stderr);
    rc None => timed out."""
    import signal
    import subprocess

    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        return None, out, err


def _spawn(name: str, timeout_s: float) -> dict:
    """Run `bench.py --bench name` in a subprocess under a hard timeout."""
    rc, stdout, stderr = _run_group(
        [sys.executable, os.path.abspath(__file__), "--bench", name],
        timeout_s)
    if rc is None:
        # a killed child may have banked interim BENCH_JSON lines before
        # the wedge (seq2seq prints its train record before the decode
        # phase) — recover the last one instead of losing the measurement
        for line in reversed((stdout or "").splitlines()):
            if line.startswith("BENCH_JSON:"):
                try:
                    result = json.loads(line[len("BENCH_JSON:"):])
                except ValueError:
                    break
                result["partial"] = (f"child killed after {timeout_s:.0f}s "
                                     f"(backend wedged?); interim record")
                # provenance: this number was measured inside a DEGRADED
                # window (the backend wedged moments later — the r04/r05
                # init-hang pattern), so last-known-good assembly must
                # skip it explicitly rather than trust timestamp ordering
                # to bury it under a healthy re-measurement
                result["degraded"] = True
                return result
        return {"error": f"timeout after {timeout_s:.0f}s (backend wedged?)"}
    for line in reversed((stdout or "").splitlines()):
        if line.startswith("BENCH_JSON:"):
            try:
                result = json.loads(line[len("BENCH_JSON:"):])
            except ValueError:
                break
            if "error" in result and stderr:
                # keep the child's traceback in the driver log — the JSON
                # record carries only the one-line error
                sys.stderr.write(f"--- bench {name} child stderr ---\n"
                                 f"{stderr[-4000:]}\n")
            return result
    tail = ((stderr or "") + (stdout or ""))[-400:]
    return {"error": f"no result (rc={rc}): {tail!r}"}


def _health_check(timeout_s: float) -> dict:
    """Probe the backend from a throwaway process; never wedges the parent."""
    code = ("import jax; d = jax.devices(); "
            "print('HEALTH:' + d[0].platform + ':' + d[0].device_kind)")
    rc, stdout, stderr = _run_group([sys.executable, "-c", code], timeout_s)
    if rc is None:
        return {"ok": False, "why": f"backend init hung >{timeout_s:.0f}s"}
    for line in (stdout or "").splitlines():
        if line.startswith("HEALTH:"):
            _, platform, kind = line.split(":", 2)
            return {"ok": True, "platform": platform, "device_kind": kind}
    return {"ok": False, "why": f"rc={rc}: {(stderr or '')[-300:]!r}"}


_METRIC_OF = {
    "vgg": "vgg16_cifar10_train_samples_per_sec_per_chip",
    "seq2seq": "wmt14_seq2seq_train_samples_per_sec_per_chip",
    "lm": "transformer_lm_train_tokens_per_sec_per_chip",
    "serving": "lm_serving_tok_per_sec",
    "serving_prefix": "lm_serving_prefix_hit_rate",
    "serving_chunked": "lm_serving_p99_itl_chunked_ms",
    "serving_fleet": "lm_serving_fleet_tok_per_sec",
    "serving_disagg": "lm_serving_disagg_tok_per_sec",
    "serving_tp": "lm_serving_tp_tok_per_sec",
    "serving_spec": "lm_serving_spec_tok_per_sec",
    "serving_scan": "lm_serving_scan_tok_per_sec",
    "serving_spill": "lm_serving_spill_hit_rate",
    "train_dist": "train_dist_samples_per_sec",
    "mnist": "mnist_vgg_train_samples_per_sec_per_chip",
    "sentiment": "imdb_sentiment_lstm_train_samples_per_sec_per_chip",
    "recommendation": "movielens_recsys_train_samples_per_sec_per_chip",
}


def _perf_log_records() -> list[dict]:
    """PERF_LOG entries, newest first."""
    try:
        with open(_PERF_LOG) as f:
            lines = f.readlines()
    except OSError:
        return []
    out = []
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec.get("record"), dict):
            out.append(rec)
    return out


def _ts_newer(a, b) -> bool:
    """True if timestamp `a` is strictly newer than `b`.  measured_at
    values mix formats across PERF_LOG eras (aware '+00:00', 'Z'-suffixed,
    naive rec-ts fallbacks), where lexicographic comparison can rank a
    stale part above a newer one (e.g. any non-UTC offset) — so ISO-parse
    both sides (naive = UTC) and string-compare only when either side does
    not parse (ADVICE r5)."""
    import datetime

    def parse(x):
        s = str(x)
        d = datetime.datetime.fromisoformat(
            s[:-1] + "+00:00" if s.endswith("Z") else s)
        if d.tzinfo is None:
            d = d.replace(tzinfo=datetime.timezone.utc)
        return d

    try:
        return parse(a) > parse(b)
    except ValueError:
        return str(a) > str(b)


def _assemble_lkg() -> dict | None:
    """Per-part last-known-good: for the headline and EVERY extra, the
    newest PERF_LOG occurrence — whether it was measured in a full run
    (nested under the vgg headline) or in a per-config run (its own
    top-level record, the short-tunnel-window queue shape).  Records and
    parts carrying the `degraded` provenance flag (a wedged child's
    interim numbers — the r04/r05 backend-init-hang pattern — or parts
    echoed into a degraded fallback record) are skipped EXPLICITLY, not
    left to timestamp ordering.  Each part is
    stamped `measured_at` so a same-round measurement is distinguishable
    from stale data (VERDICT r4 weak #1)."""
    recs = _perf_log_records()
    if not recs:
        return None

    def newest_toplevel(metric, keep_platform=False):
        drop = ("degraded",) if keep_platform else (
            "platform", "device_kind", "degraded")
        for rec in recs:
            r = rec["record"]
            if r.get("metric") == metric and "error" not in r \
                    and not r.get("degraded") and r.get("value"):
                part = {k: v for k, v in r.items()
                        if not isinstance(v, dict) and k not in drop}
                part["measured_at"] = r.get("measured_at", rec.get("ts"))
                return part
        return None

    head = newest_toplevel(_METRIC_OF["vgg"], keep_platform=True)
    # no vgg headline banked must not discard the per-config parts the
    # BENCH_ONLY queue DID measure — fall back to an explicit zero headline
    out = dict(head) if head is not None else {
        "metric": _METRIC_OF["vgg"], "value": 0.0,
        "unit": "samples/sec/chip", "vs_baseline": 0.0}
    found_any = head is not None
    for key in ("lm", "serving", "serving_prefix", "serving_chunked",
                "serving_fleet", "serving_disagg", "serving_tp",
                "serving_spec", "serving_scan", "serving_spill",
                "train_dist", "mnist", "sentiment", "recommendation",
                "seq2seq"):
        # (a) newest nested occurrence under any headline...
        part = None
        for rec in recs:
            v = rec["record"].get(key)
            # degraded provenance is checked on BOTH the part and its
            # parent record: a wedged child's interim numbers (the part
            # flag) and parts echoed into a degraded fallback record (the
            # parent flag) are equally untrustworthy as last-known-good
            if isinstance(v, dict) and "error" not in v and \
                    "skipped" not in v and not v.get("degraded") and \
                    not rec["record"].get("degraded") and v.get("value"):
                part = dict(v)
                part.setdefault("measured_at",
                                rec["record"].get("measured_at", rec["ts"]))
                break
        # (b) ...or newest per-config top-level record
        top = newest_toplevel(_METRIC_OF[key])
        if top is not None and (part is None or
                                _ts_newer(top["measured_at"],
                                          part.get("measured_at", ""))):
            part = top
        if key == "seq2seq" and (part is None or
                                 "beam_decode_tokens_per_sec" not in part):
            # decode is measured by its own phase-isolated step — merge the
            # newest decode-only record into the train part (or surface it
            # alone when the train phase never banked: a measured number
            # must not vanish from the fallback)
            dec = newest_toplevel("wmt14_seq2seq_beam_decode_tokens_per_sec")
            if dec is not None:
                if part is None:
                    part = dec
                else:
                    for f in ("beam_decode_tokens_per_sec",
                              "beam_decode_tokens_per_sec_iqr"):
                        if f in dec:
                            part[f] = dec[f]
                    part["beam_decode_measured_at"] = dec["measured_at"]
        if part is not None:
            out[key] = part
            found_any = True
    return out if found_any else None


def _append_perf_log(record: dict) -> None:
    import datetime

    entry = {"ts": datetime.datetime.now(datetime.timezone.utc)
                     .isoformat(timespec="seconds"),
             "record": record}
    try:
        with open(_PERF_LOG, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass


def _degraded_record(err: str) -> dict:
    """The always-parseable fallback: `error` + clearly-labeled
    last-known-good numbers (or an explicit zero record if none exist).
    Every part carries its own `measured_at` (see _assemble_lkg)."""
    out = {"error": err, "degraded": True}
    lkg = _assemble_lkg()
    if lkg:
        out.update(lkg)
        out["degraded_source"] = ("per-part last-known-good assembled from "
                                  "PERF_LOG.jsonl; see each measured_at")
    else:
        out.update({"metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
                    "value": 0.0, "unit": "samples/sec/chip",
                    "vs_baseline": 0.0})
    return out


def main() -> None:
    import time

    t0 = time.perf_counter()
    # wall-clock budget for the whole record: a degraded tunnel (slow remote
    # compiles) must not stall the driver — whatever doesn't fit is reported
    # as skipped rather than hanging
    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "1800"))
    per_bench = float(os.environ.get("BENCH_SUBPROC_TIMEOUT_S", "900"))
    health_timeout = float(os.environ.get("BENCH_HEALTH_TIMEOUT_S", "90"))

    def _left() -> float:
        return budget - (time.perf_counter() - t0)

    # -- backend health, with one bounded retry (the axon tunnel sometimes
    #    recovers on its own after a transient death); clamped to the
    #    remaining budget like everything else
    health = _health_check(min(health_timeout, max(_left(), 5)))
    if not health["ok"] and _left() > 10:
        time.sleep(min(float(os.environ.get("BENCH_HEALTH_RETRY_DELAY_S",
                                            "60")), max(_left() - 5, 0)))
        health = _health_check(min(health_timeout, max(_left(), 5)))

    if not health["ok"]:
        # Backend unrecoverable: emit a degraded-but-parseable record.
        print(json.dumps(
            _degraded_record(f"TPU backend unavailable: {health['why']}")))
        return

    # BENCH_ONLY=sentiment (or a comma list: first entry is the headline,
    # rest nest under it) runs a subset — the short-tunnel-window queue
    # (tools/tpu_measure.py) banks one config per step this way, and
    # _assemble_lkg stitches the per-config PERF_LOG records back into a
    # complete fallback at driver time
    only = [s for s in os.environ.get("BENCH_ONLY", "").split(",") if s]
    headline_key = only[0] if only else "vgg"

    # -- headline. One in-place retry after a fresh health check: a
    #    mid-bench tunnel death shows up as a timeout/error here.  Every
    #    spawn/check is clamped to the remaining overall budget so the
    #    documented wall-clock bound holds even through the retry path.
    degraded = False
    if _left() <= 30:
        degraded = True
        out = _degraded_record(
            f"budget {budget:.0f}s exhausted before the headline bench")
    else:
        out = _spawn(headline_key, min(per_bench, _left()))
    if not degraded and "error" in out:
        first_err = out["error"]
        if _left() > 2 * health_timeout and \
                _health_check(min(health_timeout, _left()))["ok"] and \
                _left() > 30:
            out = _spawn(headline_key, min(per_bench, _left()))
        if "error" in out:
            degraded = True
            out = _degraded_record(
                f"headline {headline_key} failed twice: "
                f"{first_err} / {out['error']}")
    if not degraded:
        # only stamp fresh measurements — a merged last-known-good record
        # keeps the platform fields + measured_at of the run that measured it
        import datetime
        import subprocess
        out["platform"] = health.get("platform", "?")
        out["device_kind"] = health.get("device_kind", "?")
        out["measured_at"] = datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds")
        try:
            # --dirty: a measurement from an uncommitted tree must not be
            # attributed to the last commit's exact code
            out["rev"] = subprocess.run(
                ["git", "describe", "--always", "--dirty", "--abbrev=7"],
                cwd=_REPO, capture_output=True, text=True,
                timeout=10).stdout.strip() or "?"
        except Exception:
            out["rev"] = "?"

    # seq2seq goes LAST: its bench is where the tunnel wedged in rounds 2
    # AND 4 (PERF_LOG 2026-07-31T01:20), so everything else must already
    # be in the record when it runs
    if only:
        extras = only[1:]
    else:
        extras = []
        if os.environ.get("BENCH_SKIP_LM", "0") != "1":
            extras.append("lm")
        if os.environ.get("BENCH_SKIP_SERVING", "0") != "1":
            extras.append("serving")
        if os.environ.get("BENCH_EXTENDED", "1") != "0":
            # the three remaining BASELINE.md configs (BENCH_EXTENDED=0 skips)
            extras += ["mnist", "sentiment", "recommendation"]
        if os.environ.get("BENCH_SKIP_S2S", "0") != "1":
            extras.append("seq2seq")
    for key in extras:
        if degraded:
            # the backend just failed the headline twice — spawning more
            # benches against it would only overwrite the last-known-good
            # extras merged above with fresh timeouts
            if key not in out:
                out[key] = {"skipped": "backend degraded before extras"}
            continue
        left = _left()
        if left <= 30:
            out[key] = {"skipped": f"time budget {budget:.0f}s exhausted"}
            continue
        out[key] = _spawn(key, min(per_bench, left))

    if "error" not in out and out.get("value"):
        _append_perf_log(out)
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--bench":
        _child(sys.argv[2])
    else:
        main()
