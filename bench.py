"""Benchmark: small-VGG CIFAR-10 training throughput (north-star #1).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Runs on whatever backend JAX selects (real TPU under the driver).

Measurement shape: batches are staged in device HBM and the full per-batch
training step (loss + backward + optimizer, identical to Trainer.train)
runs inside one `lax.scan` — the TPU-native form of a production input
pipeline, where an async host pipeline keeps data resident ahead of
compute (ref: the reference's DoubleBuffer prefetch,
gserver/dataproviders/DataProvider.h:260).  MFU is reported from XLA's own
flop count for the compiled step against the chip's peak.

`vs_baseline` compares against the measured reference baseline recorded in
BASELINE.json (reference paddle_trainer --job=time; see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# bf16 peak TFLOP/s per chip by TPU generation (v5 lite = v5e)
_PEAK_TFLOPS = {"v4": 275.0, "v5 lite": 197.0, "v5": 459.0, "v6": 918.0}


def _chip_peak_tflops(dtype: str) -> float:
    import jax
    kind = jax.devices()[0].device_kind.lower()
    peak = 197.0  # assume v5e when unknown
    for k, v in _PEAK_TFLOPS.items():
        if k in kind:
            peak = v
    # fp32 peak is half the bf16 peak on TPU
    return peak if dtype == "bfloat16" else peak / 2.0


def _baseline_ratio(value: float, key: str) -> float:
    """value / measured reference samples/sec (0.0 = baseline not measured)."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            base = json.load(f).get("published", {}).get(key, {})
        ref = float(base.get("samples_per_sec", 0.0))
        return round(value / ref, 2) if ref > 0 else 0.0
    except (OSError, ValueError):
        return 0.0


def main() -> None:
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch_size = int(os.environ.get("BENCH_BATCH_SIZE", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "200"))
    # bfloat16 is the TPU-native float: fp32 master params, bf16 matmuls on
    # the MXU, fp32 softmax/BN-stats/loss (BENCH_DTYPE=float32 opts out)
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    cfg = parse_config("demo/image_classification/vgg_16_cifar.py",
                       f"batch_size={batch_size},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(2 + iters):
        x = rng.random((batch_size, 3 * 32 * 32), np.float32).astype(np.float32) - 0.5
        y = rng.integers(0, 10, batch_size).astype(np.int32)
        batches.append({"image": Argument(value=x), "label": Argument(ids=y)})

    stats = tr.benchmark(iter(batches), warmup=2, iters=iters, scan=True)
    value = stats["samples_per_sec"]

    # MFU from XLA's flop count of the compiled per-batch step
    mfu = 0.0
    try:
        import jax
        ca = tr._train_step.lower(
            tr.params, tr.opt_state, tr.net_state, batches[0],
            jax.random.PRNGKey(0)).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        step_flops = float(ca.get("flops", 0.0))
        achieved = step_flops * (value / batch_size)  # flops/sec
        mfu = achieved / (_chip_peak_tflops(dtype) * 1e12)
    except Exception:
        pass

    print(json.dumps({
        "metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": _baseline_ratio(value, "vgg16_cifar10"),
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    main()
