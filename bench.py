"""Benchmark: small-VGG CIFAR-10 training throughput (north-star #1).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Runs on whatever backend JAX selects (real TPU under the driver).
`vs_baseline` compares against the reference paddle's GPU-era qualitative
target (BASELINE.json publishes no numbers, so 0.0 = unknown baseline ratio).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.data.feeder import make_batch
    from paddle_tpu.data.provider import dense_vector, integer_value
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch_size = int(os.environ.get("BENCH_BATCH_SIZE", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    # bfloat16 is the TPU-native float: fp32 master params, bf16 matmuls on
    # the MXU, fp32 softmax/BN-stats/loss (BENCH_DTYPE=float32 opts out)
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    cfg = parse_config("demo/image_classification/vgg_16_cifar.py",
                       f"batch_size={batch_size},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(3 + iters):
        x = rng.random((batch_size, 3 * 32 * 32), np.float32).astype(np.float32) - 0.5
        y = rng.integers(0, 10, batch_size).astype(np.int32)
        batches.append({"image": Argument(value=x), "label": Argument(ids=y)})

    stats = tr.benchmark(iter(batches), warmup=3, iters=iters)
    print(json.dumps({
        "metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
        "value": round(stats["samples_per_sec"], 2),
        "unit": "samples/sec/chip",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
