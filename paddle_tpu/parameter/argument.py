"""Argument — the inter-layer data record.

TPU-native analog of the reference's `Argument` (ref:
paddle/parameter/Argument.h:76-100: {value, ids, grad, sequenceStartPositions,
subSequenceStartPositions, frameHeight/Width}).  Key re-design: sequences are
*padded dense* [B, T, ...] plus a `lengths` vector instead of a flat ragged
matrix + start positions — static shapes are what XLA wants.  Gradients don't
live here (autodiff), and it is a registered pytree so Arguments flow through
jit/scan directly.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclass
class Argument:
    # dense value: [B, D] for plain data, [B, T, D] for sequences
    value: Optional[Array] = None
    # integer ids: [B] or [B, T] (sparse/label inputs)
    ids: Optional[Array] = None
    # [B] valid lengths; None => not a sequence
    lengths: Optional[Array] = None
    # nested sequences: [B, S] per-subsequence lengths; value is [B, S, T, D]
    sub_lengths: Optional[Array] = None
    # per-example weight (ref: Argument.weight)
    weight: Optional[Array] = None
    # sparse row representation (ref: SparseRowMatrix.h:31-301, the reference's
    # sparse_binary_vector / sparse_vector slots): `ids` holds [..., K] nonzero
    # column indices, `sparse_vals` the matching [..., K] values (1/0 validity
    # mask for binary slots), and sparse_dim the logical row width.  Memory is
    # proportional to nnz, not dim; consuming layers gather parameter rows
    # instead of densifying.  `value` stays None so unsupported layers fail
    # loudly rather than silently mixing representations.
    sparse_vals: Optional[Array] = None
    sparse_dim: int = dataclasses.field(default=0, metadata=dict(static=True))
    # image geometry (static, aux data): (height, width)
    frame_height: int = dataclasses.field(default=0, metadata=dict(static=True))
    frame_width: int = dataclasses.field(default=0, metadata=dict(static=True))
    # True => value is a [B, H, W, C] image tensor (TPU-native channels-last
    # layout kept between image layers); False => the reference's flat
    # C-major [B, C*H*W] row layout.  Conversion happens lazily at the
    # flat-row API boundary (ForwardContext.get_input / flatten_image).
    nhwc: bool = dataclasses.field(default=False, metadata=dict(static=True))

    # -- helpers ----------------------------------------------------------
    @property
    def is_sequence(self) -> bool:
        return self.lengths is not None

    @property
    def data(self) -> Array:
        """The primary payload: value if present else ids."""
        if self.value is not None:
            return self.value
        assert self.ids is not None, "empty Argument"
        return self.ids

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        assert self.is_sequence
        return self.data.shape[1]

    def mask(self, dtype=jnp.bool_) -> Optional[Array]:
        """[B, T] validity mask for sequence arguments."""
        if self.lengths is None:
            return None
        return (jnp.arange(self.max_len)[None, :] < self.lengths[:, None]).astype(dtype)

    def replace(self, **kw: Any) -> "Argument":
        return dataclasses.replace(self, **kw)

    def to_dense(self) -> "Argument":
        """Materialize a sparse-row argument as a dense [..., dim] value —
        an explicit (memory ∝ dim) escape hatch for layers/tools that need
        the full row; the training path should never call this."""
        if not self.sparse_dim:
            return self
        # scatter-add keeps peak memory at the [..., dim] result itself
        # (a one_hot intermediate would be K x larger)
        lead = self.ids.shape[:-1]
        K = self.ids.shape[-1]
        flat_ids = self.ids.reshape(-1, K)
        flat_vals = self.sparse_vals.reshape(-1, K)
        rows = jnp.arange(flat_ids.shape[0])[:, None]
        dense = jnp.zeros((flat_ids.shape[0], self.sparse_dim),
                          self.sparse_vals.dtype)
        dense = dense.at[rows, flat_ids].add(flat_vals)
        return Argument(value=dense.reshape(lead + (self.sparse_dim,)),
                        lengths=self.lengths,
                        sub_lengths=self.sub_lengths, weight=self.weight)

    def flatten_image(self) -> "Argument":
        """NHWC image -> the reference's flat C-major [B, C*H*W] rows
        (identity for non-image arguments)."""
        if not self.nhwc:
            return self
        B, H, W, C = self.value.shape
        flat = self.value.transpose(0, 3, 1, 2).reshape(B, C * H * W)
        return self.replace(value=flat, nhwc=False,
                            frame_height=H, frame_width=W)
