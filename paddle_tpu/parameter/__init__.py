from paddle_tpu.parameter.argument import Argument  # noqa: F401
from paddle_tpu.parameter.init import init_parameter  # noqa: F401
