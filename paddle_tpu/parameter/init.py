"""Parameter initialization.

Matches the reference's init rules (ref: paddle/parameter/Parameter.cpp
randomize(): normal(mean, std) by default with std = 1/sqrt(dim0) unless
explicitly set; uniform for sparse; config_parser.py's "smart" init scales by
fan-in) so stock configs reproduce the reference's training curves.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import ParameterConfig


def default_std(cfg: ParameterConfig) -> float:
    """Reference default: std = 1/sqrt(fan_in) where fan_in = dims[0]
    (ref: config_parser.py Parameters.__init__ initial_std smart default)."""
    if cfg.initial_smart and cfg.dims:
        fan_in = max(cfg.dims[0], 1)
        return 1.0 / math.sqrt(fan_in)
    return cfg.initial_std


def init_parameter(cfg: ParameterConfig, key: jax.Array) -> jax.Array:
    shape = tuple(cfg.dims) if cfg.dims else (cfg.size,)
    dtype = jnp.dtype(cfg.dtype)
    strategy = cfg.initial_strategy
    if cfg.initial_smart:
        strategy = "normal"
    if strategy == "zero":
        return jnp.zeros(shape, dtype)
    std = default_std(cfg)
    if strategy == "uniform":
        return jax.random.uniform(
            key, shape, dtype, minval=cfg.initial_mean - std, maxval=cfg.initial_mean + std)
    return cfg.initial_mean + std * jax.random.normal(key, shape, dtype)
