from paddle_tpu.optim.schedulers import learning_rate_at  # noqa: F401
from paddle_tpu.optim.updater import ParameterUpdater  # noqa: F401
