"""First-order optimizer zoo.

Matches the reference's optimizer family (ref:
paddle/parameter/FirstOrderOptimizer.{h,cpp}: SgdOptimizer,
SparseMomentumParameterOptimizer, AdagradParameterOptimizer,
AdaDeltaParameterOptimizer, RMSPropParameterOptimizer,
DecayedAdagradParameterOptimizer, AdamParameterOptimizer,
AdamaxParameterOptimizer; sgdUpdate kernel in ParameterUpdateFunctions.cpp).

Each optimizer is a pair of pure functions over a single parameter tensor —
(init_slots, update) — applied per-leaf by the ParameterUpdater.  Update rules
follow the reference's math, e.g. momentum:
    v <- momentum * v - lr * grad ; p <- p + v        (ref: sgdUpdate)
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import OptimizationConfig

Array = jax.Array

# registry: name -> (init_slots(param) -> dict, update(p, g, slots, lr, opt, t) -> (p, slots))
optimizer_registry: dict[str, tuple[Callable, Callable]] = {}


def _register(*names: str):
    def deco(pair):
        for n in names:
            optimizer_registry[n] = pair
        return pair
    return deco


def _momentum_init(p: Array, opt: OptimizationConfig) -> dict:
    return {"momentum": jnp.zeros_like(p)}


def _momentum_update(p, g, slots, lr, opt, t, mom_override=None):
    mom = opt.momentum if mom_override is None else mom_override
    v = mom * slots["momentum"] - lr * g
    return p + v, {"momentum": v}


_register("momentum", "sgd", "sparse_momentum")((_momentum_init, _momentum_update))


def _adagrad_init(p, opt):
    return {"accum": jnp.zeros_like(p)}


def _adagrad_update(p, g, slots, lr, opt, t, **_):
    accum = slots["accum"] + jnp.square(g)
    upd = g / (jnp.sqrt(accum) + opt.ada_epsilon)
    return p - lr * upd, {"accum": accum}


_register("adagrad")((_adagrad_init, _adagrad_update))


def _decayed_adagrad_init(p, opt):
    return {"accum": jnp.zeros_like(p)}


def _decayed_adagrad_update(p, g, slots, lr, opt, t, **_):
    accum = opt.ada_rho * slots["accum"] + (1.0 - opt.ada_rho) * jnp.square(g)
    upd = g / jnp.sqrt(accum + opt.ada_epsilon)
    return p - lr * upd, {"accum": accum}


_register("decayed_adagrad")((_decayed_adagrad_init, _decayed_adagrad_update))


def _adadelta_init(p, opt):
    return {"accum": jnp.zeros_like(p), "accum_update": jnp.zeros_like(p)}


def _adadelta_update(p, g, slots, lr, opt, t, **_):
    rho, eps = opt.ada_rho, opt.ada_epsilon
    accum = rho * slots["accum"] + (1.0 - rho) * jnp.square(g)
    upd = g * jnp.sqrt((slots["accum_update"] + eps) / (accum + eps))
    accum_update = rho * slots["accum_update"] + (1.0 - rho) * jnp.square(upd)
    return p - lr * upd, {"accum": accum, "accum_update": accum_update}


_register("adadelta")((_adadelta_init, _adadelta_update))


def _rmsprop_init(p, opt):
    return {"accum_g2": jnp.zeros_like(p), "accum_g": jnp.zeros_like(p)}


def _rmsprop_update(p, g, slots, lr, opt, t, **_):
    """Graves-style RMSProp with first-moment correction
    (ref: RMSPropParameterOptimizer::update: E[g^2], E[g])."""
    rho, eps = opt.ada_rho, opt.ada_epsilon
    g2 = rho * slots["accum_g2"] + (1.0 - rho) * jnp.square(g)
    g1 = rho * slots["accum_g"] + (1.0 - rho) * g
    upd = g / jnp.sqrt(g2 - jnp.square(g1) + eps)
    return p - lr * upd, {"accum_g2": g2, "accum_g": g1}


_register("rmsprop")((_rmsprop_init, _rmsprop_update))


def _adam_init(p, opt):
    return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}


def _adam_update(p, g, slots, lr, opt, t, **_):
    """(ref: AdamParameterOptimizer::update)."""
    b1, b2, eps = opt.adam_beta1, opt.adam_beta2, opt.adam_epsilon
    m = b1 * slots["m"] + (1.0 - b1) * g
    v = b2 * slots["v"] + (1.0 - b2) * jnp.square(g)
    tf = t.astype(jnp.float32)
    mhat = m / (1.0 - jnp.power(b1, tf))
    vhat = v / (1.0 - jnp.power(b2, tf))
    return p - lr * mhat / (jnp.sqrt(vhat) + eps), {"m": m, "v": v}


_register("adam")((_adam_init, _adam_update))


def _adamax_init(p, opt):
    return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}


def _adamax_update(p, g, slots, lr, opt, t, **_):
    """(ref: AdamaxParameterOptimizer::update)."""
    b1, b2 = opt.adam_beta1, opt.adam_beta2
    m = b1 * slots["m"] + (1.0 - b1) * g
    u = jnp.maximum(b2 * slots["u"], jnp.abs(g))
    tf = t.astype(jnp.float32)
    lr_t = lr / (1.0 - jnp.power(b1, tf))
    return p - lr_t * m / (u + 1e-12), {"m": m, "u": u}


_register("adamax")((_adamax_init, _adamax_update))


def get_optimizer(name: str):
    try:
        return optimizer_registry[name]
    except KeyError:
        raise ValueError(f"unknown learning_method {name!r}; "
                         f"known: {sorted(optimizer_registry)}")
