"""Learning-rate schedules.

Matches the reference's scheduler registry (ref:
paddle/parameter/LearningRateScheduler.cpp:51-173: constant, poly, caffe_poly,
exp, discexp, linear, manual, pass_manual), where the schedule argument is the
number of processed *samples* (or pass id for pass_manual).  Pure jnp math so
it runs inside the jitted update step.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.config.schema import OptimizationConfig


def _parse_segments(args: str):
    """'seg0:lr0,seg1:lr1,...' (ref: BaseLearningRateScheduler manual)."""
    segs = []
    for part in args.split(","):
        if not part:
            continue
        a, _, b = part.partition(":")
        segs.append((float(a), float(b)))
    return segs


def learning_rate_at(opt: OptimizationConfig, num_samples, pass_id=0):
    """Global LR at this point in training; `num_samples` may be a traced
    jnp scalar (ref: LearningRateScheduler.cpp)."""
    lr = opt.learning_rate
    a = opt.learning_rate_decay_a
    b = opt.learning_rate_decay_b
    x = jnp.asarray(num_samples, jnp.float32)
    sched = opt.learning_rate_schedule
    if sched == "constant":
        return jnp.asarray(lr, jnp.float32)
    if sched == "poly":
        return lr * jnp.power(1.0 + a * x, -b)
    if sched == "caffe_poly":
        return lr * jnp.power(jnp.maximum(1.0 - x / a, 0.0), b)
    if sched == "exp":
        return lr * jnp.power(a, x / b)
    if sched == "discexp":
        return lr * jnp.power(a, jnp.floor(x / b))
    if sched == "linear":
        return jnp.maximum(lr - a * x, b)
    if sched in ("manual", "pass_manual"):
        segs = _parse_segments(opt.learning_rate_args)
        pos = jnp.asarray(pass_id if sched == "pass_manual" else num_samples, jnp.float32)
        rate = jnp.asarray(segs[-1][1] if segs else 1.0, jnp.float32)
        # walk segments backwards: pick first whose boundary covers pos
        for bound, r in reversed(segs[:-1] if segs else []):
            rate = jnp.where(pos <= bound, r, rate)
        return lr * rate
    raise ValueError(f"unknown learning_rate_schedule {sched!r}")
