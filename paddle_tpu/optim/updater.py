"""ParameterUpdater — applies optimizer + schedule + regularization.

TPU-native collapse of the reference's updater family (ref:
paddle/trainer/ParameterUpdater.h SgdLocalUpdater,
ThreadParameterUpdater.h SgdThreadUpdater, RemoteParameterUpdater.h — local,
thread-sharded, and parameter-server variants).  On TPU all three become one
pure `step()` fused into the jitted train step: the optimizer math runs
sharded next to the gradients, and data-parallel gradient reduction is an XLA
psum (see parallel/), not a ring of threads or a remote server.

Handles, per parameter (ref: parameter/ParameterConfig + OptimizationConfig):
  - per-parameter learning-rate multipliers and momentum overrides
  - L1/L2 weight decay (global default, per-param override)
  - elementwise gradient clipping (global or per-param threshold)
  - the LR schedule by processed-sample count
  - model averaging (ref: AverageOptimizer) as an extra slot
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import ModelConfig, OptimizationConfig, ParameterConfig
from paddle_tpu.optim.optimizers import get_optimizer
from paddle_tpu.optim.schedulers import learning_rate_at

Array = jax.Array


class ParameterUpdater:
    def __init__(self, model: ModelConfig, opt: OptimizationConfig):
        self.model = model
        self.opt = opt
        self.param_cfgs: dict[str, ParameterConfig] = {p.name: p for p in model.parameters}
        self.init_slots_fn, self.update_fn = get_optimizer(opt.learning_method)
        self.use_average = opt.average_window > 0
        self._masks: dict[str, Array] = {}   # built by apply_init_hooks

    # -- updater hooks (ref: ParameterUpdaterHook.cpp:32,167) --------------
    def apply_init_hooks(self, params: dict[str, Array]) -> dict[str, Array]:
        """Build pruning masks and apply them to the initial values — the
        StaticPruningHook's init() (mask the parameter) + the per-update
        gradient masking happens in step().  Mask sources:
          - sparsity_ratio r: zero the r-fraction smallest-|w| entries of
            the initial value (the magnitude criterion later Paddle uses);
          - mask_filename: a .npy 0/1 array of the parameter's shape (the
            re-design of the reference's packed-bit mask file format)."""
        import numpy as np

        out = dict(params)
        for name, cfg in self.param_cfgs.items():
            for hook in cfg.update_hooks:
                if hook.get("type") != "pruning":
                    raise ValueError(f"unknown updater hook {hook!r}")
                p = np.asarray(out[name])
                if "mask_filename" in hook:
                    mask = np.load(hook["mask_filename"]).astype(p.dtype)
                    assert mask.shape == p.shape, (
                        f"mask {mask.shape} vs param {p.shape}")
                else:
                    r = float(hook.get("sparsity_ratio", 0.0))
                    k = int(r * p.size)
                    mask = np.ones(p.size, p.dtype)
                    if k > 0:
                        order = np.argsort(np.abs(p.reshape(-1)),
                                           kind="stable")
                        mask[order[:k]] = 0.0
                    mask = mask.reshape(p.shape)
                self._masks[name] = jnp.asarray(mask)
                out[name] = jnp.asarray(p * mask)
        return out

    def init_state(self, params: dict[str, Array]) -> dict[str, Any]:
        slots = {name: self.init_slots_fn(p, self.opt)
                 for name, p in params.items()
                 if not self.param_cfgs[name].is_static}
        state: dict[str, Any] = {
            "slots": slots,
            "num_samples": jnp.zeros((), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32),
            "num_updates": jnp.zeros((), jnp.int32),
            "pass_id": jnp.zeros((), jnp.int32),
        }
        if self._masks:
            # masks travel INSIDE the optimizer state so a mask rebuilt
            # after checkpoint load reaches the already-compiled train step
            # (a closure read would bake the first trace's values in as
            # constants)
            state["masks"] = dict(self._masks)
        if self.use_average:
            state["average"] = {name: jnp.array(p) for name, p in params.items()}
            state["average_count"] = jnp.zeros((), jnp.int32)
        if self.accum_n > 1:
            # accumulate in >= fp32: summing N low-precision gradients with
            # a rounding per add would break the concatenated-batch
            # equivalence exactly for the configs accumulation targets
            def acc_zeros(p):
                dt = jnp.promote_types(p.dtype, jnp.float32) if \
                    jnp.issubdtype(p.dtype, jnp.floating) else p.dtype
                return jnp.zeros(p.shape, dt)
            state["grad_accum"] = {
                name: acc_zeros(p) for name, p in params.items()
                if not self.param_cfgs[name].is_static}
            state["grad_accum_count"] = jnp.zeros((), jnp.int32)
            state["grad_accum_samples"] = jnp.zeros((), jnp.int32)
        return state

    @property
    def accum_n(self) -> int:
        """Gradient-accumulation window (ref: RemoteParameterUpdater.cpp:206
        num_batches_per_send_parameter — gradients accumulate locally for N
        batches before one parameter update)."""
        return max(int(self.opt.num_batches_per_send_parameter), 1)

    def step(
        self,
        params: dict[str, Array],
        grads: dict[str, Array],
        state: dict[str, Any],
        batch_size: int,
    ) -> tuple[dict[str, Array], dict[str, Any]]:
        """One training-step update; pure, call under jit.  With
        num_batches_per_send_parameter = N > 1, gradients accumulate and
        the optimizer applies once per N batches on their mean — identical
        math to training on the N batches concatenated.

        Scan-fusion contract (trainer --steps_per_dispatch > 1 hosts this
        whole function inside a lax.scan body): the returned (params,
        state) pytrees must keep the INPUT structure and shapes — the
        accumulate-or-apply branch below is a lax.cond, never a Python
        if, so a window boundary inside a fused k-group stays a single
        compiled program and the k=1 trajectory is reproduced exactly."""
        N = self.accum_n
        if N == 1:
            return self._apply(params, grads, state, batch_size)

        # sample-weighted: each micro-batch's MEAN gradient re-scales by its
        # size, so unequal micro-batches (drop_last=False tails,
        # calc_batch_size mode) still reproduce the concatenated-batch mean
        acc = {name: state["grad_accum"][name]
               + batch_size * grads[name].astype(state["grad_accum"][name].dtype)
               for name in state["grad_accum"] if name in grads}
        for name in state["grad_accum"]:       # params without grads this step
            acc.setdefault(name, state["grad_accum"][name])
        cnt = state["grad_accum_count"] + 1
        n_samples = state["grad_accum_samples"] + batch_size
        core = {k: v for k, v in state.items()
                if k not in ("grad_accum", "grad_accum_count",
                             "grad_accum_samples")}

        def apply_branch(_):
            denom = n_samples.astype(jnp.float32)
            mean = {n: (a / denom).astype(a.dtype) for n, a in acc.items()}
            p2, s2 = self._apply(params, mean, core, n_samples)
            s2 = dict(s2)
            s2["grad_accum"] = jax.tree.map(jnp.zeros_like, acc)
            s2["grad_accum_count"] = jnp.zeros((), jnp.int32)
            s2["grad_accum_samples"] = jnp.zeros((), jnp.int32)
            return p2, s2

        def skip_branch(_):
            s2 = dict(core)
            s2["grad_accum"] = acc
            s2["grad_accum_count"] = cnt
            s2["grad_accum_samples"] = n_samples
            return dict(params), s2

        return jax.lax.cond(cnt >= N, apply_branch, skip_branch, None)

    def _apply(
        self,
        params: dict[str, Array],
        grads: dict[str, Array],
        state: dict[str, Any],
        batch_size: int,
    ) -> tuple[dict[str, Array], dict[str, Any]]:
        """One optimizer application; pure, call under jit."""
        opt = self.opt
        num_samples = state["num_samples"] + batch_size
        t = state["num_updates"] + 1
        base_lr = learning_rate_at(opt, num_samples, state["pass_id"])

        new_params: dict[str, Array] = {}
        new_slots: dict[str, Any] = {}
        for name, p in params.items():
            cfg = self.param_cfgs[name]
            if cfg.is_static or name not in grads:
                new_params[name] = p
                if name in state["slots"]:
                    new_slots[name] = state["slots"][name]
                continue
            g = grads[name]
            # pruning-mask hook: masked entries receive no gradient and the
            # value is re-masked after the update (ref: StaticPruningHook::
            # update — grad dotMul mask)
            mask = state.get("masks", {}).get(name)
            if mask is not None:
                g = g * mask.astype(g.dtype)
            # gradient clipping (elementwise, ref: ParameterOptimizer clipping);
            # per-param None inherits the global, 0.0 disables explicitly
            thr = (cfg.gradient_clipping_threshold
                   if cfg.gradient_clipping_threshold is not None
                   else opt.gradient_clipping_threshold)
            if thr:
                g = jnp.clip(g, -thr, thr)
            # weight decay (ref: Regularizer.cpp applied at update time)
            l2 = cfg.decay_rate if cfg.decay_rate is not None else opt.l2_weight
            if l2:
                g = g + l2 * p
            l1 = cfg.decay_rate_l1 if cfg.decay_rate_l1 is not None else opt.l1_weight
            if l1:
                g = g + l1 * jnp.sign(p)
            lr = base_lr * cfg.learning_rate
            mom_override = cfg.momentum
            new_p, slots = self.update_fn(
                p, g, state["slots"][name], lr, opt, t,
                **({"mom_override": mom_override} if mom_override is not None
                   and opt.learning_method in ("momentum", "sgd", "sparse_momentum")
                   else {}))
            if mask is not None:
                # weight decay / averaging must not resurrect pruned weights
                new_p = new_p * mask.astype(new_p.dtype)
            new_params[name] = new_p
            new_slots[name] = slots

        new_state: dict[str, Any] = {
            "slots": new_slots,
            "num_samples": num_samples,
            "num_updates": t,
            "pass_id": state["pass_id"],
        }
        if "masks" in state:
            new_state["masks"] = state["masks"]
        if self.use_average:
            # cumulative average with window reset
            # (ref: AverageOptimizer — maintains an averaged copy for eval)
            cnt = state["average_count"] + 1
            max_win = opt.max_average_window or 0
            if max_win:
                reset = cnt > max_win
                cnt = jnp.where(reset, 1, cnt)
            avg = {}
            for name, p in new_params.items():
                prev = state["average"][name]
                if max_win:
                    prev = jnp.where(reset, p, prev)
                avg[name] = prev + (p - prev) / cnt.astype(p.dtype)
            new_state["average"] = avg
            new_state["average_count"] = cnt
        return new_params, new_state

    def start_pass(self, state):
        return state

    def finish_pass(self, state):
        state = dict(state)
        state["pass_id"] = state["pass_id"] + 1
        if "grad_accum" in state:
            # a partially-filled accumulation window does not straddle the
            # pass boundary (its batches would otherwise apply under the
            # next pass's LR schedule); the trailing < N batches are
            # dropped, the same convention as the feeder's drop_last
            state["grad_accum"] = jax.tree.map(jnp.zeros_like,
                                               state["grad_accum"])
            state["grad_accum_count"] = jnp.zeros((), jnp.int32)
            state["grad_accum_samples"] = jnp.zeros((), jnp.int32)
        return state

    def averaged_params(self, params, state):
        """Parameters to evaluate with (ref: AverageOptimizer::setupBeforeLoad)."""
        if self.use_average:
            return state["average"]
        return params
