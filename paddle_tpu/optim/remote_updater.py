"""RemoteParameterUpdater — the parameter-server member of the updater
family, finally complete.

The reference had three updaters behind one interface (ref:
paddle/trainer/ParameterUpdater.h SgdLocalUpdater,
ThreadParameterUpdater.h, RemoteParameterUpdater.{h,cpp}): local,
thread-sharded, and remote (gradients to a pserver fleet, fresh
parameters back).  `optim/updater.py` collapsed the first two into the
jitted train step; this class is the third: it presents the SAME
interface to the Trainer, but `is_remote = True` makes the Trainer build
a GRAD-ONLY jitted step and route each batch through `remote_step()` —
gradients to every `paddle_tpu/pserver/` shard, a sync barrier at the
coordinator, fresh parameters pulled back (ref: RemoteParameterUpdater::
finishBatch's sendAndReceiveParameter round trip).

All optimizer state (slots, LR-schedule counters, model-averaging
copies) lives SERVER-side, applied with the same `optim/updater.py` math
at block granularity — sync mode is bit-exact against a single-process
`grad_accum=K` run (tests/test_train_dist.py pins it).  Async mode
contributes without a barrier under the server's bounded-staleness
guard and pulls on the `num_batches_per_get_parameter` cadence (ref:
RemoteParameterUpdater.cpp:206 — the same knob family).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from paddle_tpu.config.schema import ModelConfig, OptimizationConfig


class RemoteParameterUpdater:
    """ParameterUpdater-interface facade over a ParameterClient."""

    is_remote = True

    def __init__(self, model: ModelConfig, opt: OptimizationConfig,
                 addrs: list, mode: Optional[str] = None,
                 rank: Optional[int] = None, timeout: float = 300.0,
                 beat_interval_s: float = 1.0,
                 connect_attempts: int = 5):
        self.model = model
        self.opt = opt
        self.param_cfgs = {p.name: p for p in model.parameters}
        for p in model.parameters:
            if p.update_hooks:
                raise NotImplementedError(
                    f"parameter {p.name!r} declares updater hooks "
                    f"(pruning masks): masks are built from full-"
                    f"parameter magnitudes, which the block-sharded "
                    f"parameter server does not reproduce — use the "
                    f"local ParameterUpdater for this config")
        if int(opt.num_batches_per_send_parameter) > 1:
            raise NotImplementedError(
                "num_batches_per_send_parameter > 1 with the remote "
                "updater: the sync window IS the trainer fleet (K "
                "trainers reproduce grad_accum=K exactly) — local "
                "pre-accumulation before the send is not implemented; "
                "scale the fleet or use the local updater")
        self.addrs = list(addrs)
        self.rank = rank
        self.timeout = float(timeout)
        self.beat_interval_s = float(beat_interval_s)
        self.connect_attempts = int(connect_attempts)
        self.use_average = opt.average_window > 0
        self.client = None             # ParameterClient, once connected
        self.mode = mode               # None = adopt the server's
        self.pull_every = max(int(opt.num_batches_per_get_parameter), 1)
        self._async_since_pull = 0
        self._batch_seq = 0

    # -- interface parity with ParameterUpdater -----------------------------
    @property
    def accum_n(self) -> int:
        return 1

    def apply_init_hooks(self, params: dict) -> dict:
        return params                  # hooks refused in __init__

    def init_state(self, params: dict) -> dict[str, Any]:
        """The Trainer-side state is a stub: every real counter (samples,
        updates, pass, averaging) lives on the server."""
        return {"remote": True}

    def step(self, params, grads, state, batch_size):
        raise RuntimeError(
            "RemoteParameterUpdater.step cannot run inside the jitted "
            "train step (it does network I/O) — the Trainer routes "
            "remote batches through remote_step(); this call means a "
            "code path missed the is_remote branch")

    def start_pass(self, state):
        return state

    def finish_pass(self, state):
        """Pass boundary = a fleet-wide barrier; the server bumps its
        pass_id (LR pass schedules) exactly once."""
        if self.client is not None:
            self.client.pass_barrier()
        return state

    def averaged_params(self, params, state):
        """Eval-time parameters (ref: AverageOptimizer): pulled from the
        server's averaging slots when averaging is on."""
        if not self.use_average or self.client is None:
            return params
        import jax.numpy as jnp

        pulled = self.client.pull(want="average")
        return {n: jnp.asarray(v) for n, v in pulled.items()}

    # -- remote lifecycle ----------------------------------------------------
    def connect_and_sync(self, params_host: dict[str, np.ndarray],
                         config_json: Optional[str] = None
                         ) -> dict[str, np.ndarray]:
        """Join the fleet and return the authoritative parameters: the
        first trainer seeds the server with its (seed-deterministic)
        initial values, later joiners adopt the current state."""
        from paddle_tpu.pserver.client import ParameterClient

        self.client = ParameterClient(
            self.addrs, timeout=self.timeout,
            connect_attempts=self.connect_attempts,
            beat_interval_s=self.beat_interval_s)
        server_mode = self.client.mode
        if self.mode is not None and self.mode != server_mode:
            raise ValueError(
                f"trainer requested {self.mode!r} mode but the server "
                f"fleet runs {server_mode!r} — the mode is a server "
                f"(tools/pserver.py --mode) decision")
        self.mode = server_mode
        self.client.join(rank=self.rank)
        self.rank = self.client.rank
        return self.client.init_or_fetch(
            params_host, self.opt.to_dict(),
            {n: c.to_dict() for n, c in self.param_cfgs.items()},
            config_json=config_json)

    def remote_step(self, grads_host: dict[str, np.ndarray],
                    batch_size: int, tag: Optional[str] = None
                    ) -> Optional[dict[str, np.ndarray]]:
        """One batch's contribution; returns fresh full parameters (sync:
        every batch; async: on the num_batches_per_get_parameter cadence,
        else None = keep training on the current ones)."""
        assert self.client is not None, "connect_and_sync first"
        if tag is None:
            tag = f"r{self.rank}b{self._batch_seq}"
        self._batch_seq += 1
        out = self.client.push_grads(grads_host, batch_size, tag=tag)
        if self.mode == "sync":
            return out
        self._async_since_pull += 1
        if self._async_since_pull >= self.pull_every:
            self._async_since_pull = 0
            return self.client.pull()
        return None

    def drain_and_leave(self) -> None:
        if self.client is not None:
            try:
                self.client.drain()
                self.client.leave()
            finally:
                self.client.close()
                self.client = None
