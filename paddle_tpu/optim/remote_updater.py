"""RemoteParameterUpdater — the parameter-server member of the updater
family, finally complete.

The reference had three updaters behind one interface (ref:
paddle/trainer/ParameterUpdater.h SgdLocalUpdater,
ThreadParameterUpdater.h, RemoteParameterUpdater.{h,cpp}): local,
thread-sharded, and remote (gradients to a pserver fleet, fresh
parameters back).  `optim/updater.py` collapsed the first two into the
jitted train step; this class is the third: it presents the SAME
interface to the Trainer, but `is_remote = True` makes the Trainer build
a GRAD-ONLY jitted step and route each batch through `remote_step()` —
gradients to every `paddle_tpu/pserver/` shard, a sync barrier at the
coordinator, fresh parameters pulled back (ref: RemoteParameterUpdater::
finishBatch's sendAndReceiveParameter round trip).

All optimizer state (slots, LR-schedule counters, model-averaging
copies) lives SERVER-side, applied with the same `optim/updater.py` math
at block granularity — sync mode is bit-exact against a single-process
`grad_accum=K` run (tests/test_train_dist.py pins it).  Async mode
contributes without a barrier under the server's bounded-staleness
guard and pulls on the `num_batches_per_get_parameter` cadence (ref:
RemoteParameterUpdater.cpp:206 — the same knob family).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from paddle_tpu.config.schema import ModelConfig, OptimizationConfig
from paddle_tpu.obs.trace import get_tracer, new_span_id, new_trace_id

#: the per-window timing parts that sum to the window wall (the closure
#: contract tier-1 asserts); `other_ms` absorbs the sub-ms gaps between
#: the contiguous segments, so the identity is exact by construction
TIMING_PARTS = ("compute_ms", "push_ms", "barrier_wait_ms", "pull_ms",
                "other_ms")


class RemoteParameterUpdater:
    """ParameterUpdater-interface facade over a ParameterClient."""

    is_remote = True

    def __init__(self, model: ModelConfig, opt: OptimizationConfig,
                 addrs: list, mode: Optional[str] = None,
                 rank: Optional[int] = None, timeout: float = 300.0,
                 beat_interval_s: float = 1.0,
                 connect_attempts: int = 5):
        self.model = model
        self.opt = opt
        self.param_cfgs = {p.name: p for p in model.parameters}
        for p in model.parameters:
            if p.update_hooks:
                raise NotImplementedError(
                    f"parameter {p.name!r} declares updater hooks "
                    f"(pruning masks): masks are built from full-"
                    f"parameter magnitudes, which the block-sharded "
                    f"parameter server does not reproduce — use the "
                    f"local ParameterUpdater for this config")
        # num_batches_per_send_parameter = N > 1: buffer N batches'
        # gradients HOST-SIDE as one sample-weighted fp32 sum and push it
        # once per window with the send_grad pre_accum flag (ref:
        # RemoteParameterUpdater.cpp sendParallel's batch cadence) — the
        # wire then carries 1/N of the gradient frames.  The local ladder
        # is the SAME jitted accumulate op as the server's (and the local
        # updater's grad_accum branch), so one trainer at N reproduces
        # the grad_accum=N oracle bit for bit.
        self.accum = max(int(opt.num_batches_per_send_parameter), 1)
        self._acc_add = None
        if self.accum > 1:
            from functools import partial

            import jax
            import jax.numpy as jnp

            @partial(jax.jit, static_argnums=(2,))
            def _acc_add(acc, g, bsz):
                return acc + bsz * g.astype(acc.dtype)

            def _acc_zeros(g):
                dt = jnp.promote_types(g.dtype, jnp.float32) if \
                    jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating) \
                    else jnp.asarray(g).dtype
                return jnp.zeros(np.shape(g), dt)

            self._acc_add = _acc_add
            self._acc_zeros = _acc_zeros
        self._buf_acc: Optional[dict] = None   # name -> fp32 device sum
        self._buf_n = 0                        # batches buffered
        self._buf_samples = 0
        self._buf_t0 = 0.0                     # first batch's compute t0
        self._buf_compute_s = 0.0              # summed compute durations
        self.dropped_partial_batches = 0       # finish_pass drop-last
        self.addrs = list(addrs)
        self.rank = rank
        self.timeout = float(timeout)
        self.beat_interval_s = float(beat_interval_s)
        self.connect_attempts = int(connect_attempts)
        self.use_average = opt.average_window > 0
        self.client = None             # ParameterClient, once connected
        self.mode = mode               # None = adopt the server's
        self.pull_every = max(int(opt.num_batches_per_get_parameter), 1)
        self._async_since_pull = 0
        self._batch_seq = 0
        self.last_window_timing: dict = {}
        self._pass_t: dict = {}        # per-pass sums, reset by pass_timing
        self._pass_windows = 0
        self._rejects_at_pass_start = 0

    # -- interface parity with ParameterUpdater -----------------------------
    @property
    def accum_n(self) -> int:
        return self.accum

    def apply_init_hooks(self, params: dict) -> dict:
        return params                  # hooks refused in __init__

    def init_state(self, params: dict) -> dict[str, Any]:
        """The Trainer-side state is a stub: every real counter (samples,
        updates, pass, averaging) lives on the server."""
        return {"remote": True}

    def step(self, params, grads, state, batch_size):
        raise RuntimeError(
            "RemoteParameterUpdater.step cannot run inside the jitted "
            "train step (it does network I/O) — the Trainer routes "
            "remote batches through remote_step(); this call means a "
            "code path missed the is_remote branch")

    def start_pass(self, state):
        return state

    def finish_pass(self, state):
        """Pass boundary = a fleet-wide barrier; the server bumps its
        pass_id (LR pass schedules) exactly once.  The boundary frame
        carries its own trace context like every window frame.  A
        partial pre-accumulation buffer (pass length not divisible by N)
        is DROPPED here — the same drop-last convention as the local
        updater's partial grad_accum window, counted loudly."""
        if self._buf_n:
            self.dropped_partial_batches += self._buf_n
            self._buf_acc = None
            self._buf_n = 0
            self._buf_samples = 0
            self._buf_compute_s = 0.0
        if self.client is not None:
            self.client.pass_barrier(
                trace={"trace_id": new_trace_id(), "parent": new_span_id()})
        return state

    def averaged_params(self, params, state):
        """Eval-time parameters (ref: AverageOptimizer): pulled from the
        server's averaging slots when averaging is on."""
        if not self.use_average or self.client is None:
            return params
        import jax.numpy as jnp

        pulled = self.client.pull(want="average")
        return {n: jnp.asarray(v) for n, v in pulled.items()}

    # -- remote lifecycle ----------------------------------------------------
    def connect_and_sync(self, params_host: dict[str, np.ndarray],
                         config_json: Optional[str] = None
                         ) -> dict[str, np.ndarray]:
        """Join the fleet and return the authoritative parameters: the
        first trainer seeds the server with its (seed-deterministic)
        initial values, later joiners adopt the current state."""
        from paddle_tpu.pserver.client import ParameterClient

        self.client = ParameterClient(
            self.addrs, timeout=self.timeout,
            connect_attempts=self.connect_attempts,
            beat_interval_s=self.beat_interval_s)
        server_mode = self.client.mode
        if self.mode is not None and self.mode != server_mode:
            raise ValueError(
                f"trainer requested {self.mode!r} mode but the server "
                f"fleet runs {server_mode!r} — the mode is a server "
                f"(tools/pserver.py --mode) decision")
        self.mode = server_mode
        if self.accum > 1 and not self.client.pre_accum_capable:
            raise RuntimeError(
                f"num_batches_per_send_parameter="
                f"{self.accum} needs the pre_accum send_grad capability "
                f"on every shard — a shard in this fleet predates it; "
                f"upgrade the servers or run with "
                f"num_batches_per_send_parameter=1")
        self.client.join(rank=self.rank)
        self.rank = self.client.rank
        return self.client.init_or_fetch(
            params_host, self.opt.to_dict(),
            {n: c.to_dict() for n, c in self.param_cfgs.items()},
            config_json=config_json)

    def remote_step(self, grads_host: dict[str, np.ndarray],
                    batch_size: int, tag: Optional[str] = None,
                    compute: Optional[tuple] = None
                    ) -> Optional[dict[str, np.ndarray]]:
        """One batch's contribution; returns fresh full parameters (sync:
        every batch; async: on the num_batches_per_get_parameter cadence,
        else None = keep training on the current ones).

        `compute` is the grad fetch's (t0, dur) — the window's compute
        phase, measured by the caller.  Mints ONE trace_id per window,
        stamped on every wire frame of the round (send_grad/barrier/
        get_params) so shard-side spans adopt it; records the window +
        grad_compute spans on the `remote` lane; and assembles
        `last_window_timing` — contiguous phase walls whose TIMING_PARTS
        sum to `total_ms` exactly (closure by construction, asserted in
        tier-1)."""
        assert self.client is not None, "connect_and_sync first"
        if tag is None:
            tag = f"r{self.rank}b{self._batch_seq}"
        self._batch_seq += 1
        pre = False
        if self.accum > 1:
            # trainer-side pre-accumulation: fold this batch into the
            # fp32 sample-weighted sum; only every Nth batch reaches the
            # wire.  The buffered window's compute part is the SUM of
            # the N grad-fetch walls, anchored at the first batch's t0 —
            # the inter-batch gaps land in other_ms like any other
            # untracked host time.
            if self._buf_n == 0:
                self._buf_t0 = compute[0] if compute \
                    else time.perf_counter()
                self._buf_compute_s = 0.0
                self._buf_acc = {}
            if compute:
                self._buf_compute_s += compute[1]
            for name, g in grads_host.items():
                a = self._buf_acc.get(name)
                if a is None:
                    a = self._acc_zeros(g)
                self._buf_acc[name] = self._acc_add(a, g, int(batch_size))
            self._buf_n += 1
            self._buf_samples += int(batch_size)
            if self._buf_n < self.accum:
                return None            # window still open: keep training
            grads_host = {name: np.asarray(a)
                          for name, a in self._buf_acc.items()}
            batch_size = self._buf_samples
            compute = (self._buf_t0, self._buf_compute_s)
            self._buf_acc = None
            self._buf_n = 0
            self._buf_samples = 0
            self._buf_compute_s = 0.0
            pre = True
        t_start = compute[0] if compute else time.perf_counter()
        compute_ms = (compute[1] * 1e3) if compute else 0.0
        span_id = new_span_id()
        tctx = {"trace_id": new_trace_id(), "parent": span_id}
        tr = get_tracer()
        if tr.enabled and compute:
            tr.add("grad_compute", compute[0], compute[1], track="remote",
                   attrs=dict(tctx))
        out = self.client.push_grads(grads_host, batch_size, tag=tag,
                                     trace=tctx, pre_accum=pre)
        async_pull_ms = 0.0
        if self.mode != "sync":
            self._async_since_pull += 1
            if self._async_since_pull >= self.pull_every:
                self._async_since_pull = 0
                out = self.client.pull(trace=tctx)
                # the cadence pull is THIS window's dominant phase when
                # it fires — attribute it, don't let it hide in other_ms
                async_pull_ms = self.client.last_pull_ms
        t_end = time.perf_counter()
        ct = dict(self.client.last_timing)
        total_ms = (t_end - t_start) * 1e3
        parts = {"compute_ms": round(compute_ms, 3),
                 "push_ms": ct.get("push_ms", 0.0),
                 "barrier_wait_ms": ct.get("barrier_wait_ms", 0.0),
                 "pull_ms": ct.get("pull_ms",
                                   round(async_pull_ms, 3))}
        other = total_ms - sum(parts.values())
        # each of the 4 parts is rounded to 1e-3 ms (+5e-4 worst case
        # apiece), so a genuinely-closed window can read up to 2e-3 ms
        # of phantom excess against the unrounded wall
        assert other > -2.5e-3, "window timing parts exceed the wall"
        parts["other_ms"] = round(max(other, 0.0), 3)
        self.last_window_timing = {
            "window": ct.get("window"), "total_ms": round(total_ms, 3),
            **parts,
            # server-side nesting (accumulate/apply happen INSIDE
            # barrier_wait for sync — attribution, not closure parts)
            "accum_ms": ct.get("accum_ms", 0.0),
            "apply_ms": ct.get("apply_ms", 0.0),
            "skew_ms": ct.get("skew_ms", 0.0),
            **({"staleness": ct["staleness"]} if "staleness" in ct
               else {}),
        }
        for k in (*TIMING_PARTS, "accum_ms", "apply_ms", "total_ms"):
            self._pass_t[k] = self._pass_t.get(k, 0.0) + \
                self.last_window_timing.get(k, 0.0)
        self._pass_windows += 1
        if tr.enabled:
            tr.add("window", t_start, t_end - t_start, track="remote",
                   attrs={"trace_id": tctx["trace_id"],
                          "span_id": span_id,
                          "window": ct.get("window")})
        return out

    def pass_timing(self, reset: bool = True) -> dict:
        """Per-pass remote-updater attribution sums — the fields the
        trainer folds into its pass stats (and so into metrics.jsonl and
        TRAIN_JSON): push/barrier_wait/pull/compute/apply ms, windows,
        and the async stale-reject count for the pass."""
        rejects = getattr(self.client, "stale_rejects", 0)
        out = {k: round(v, 3) for k, v in self._pass_t.items()}
        out["remote_windows"] = self._pass_windows
        out["async_stale_rejects"] = rejects - self._rejects_at_pass_start
        if reset:
            self._pass_t = {}
            self._pass_windows = 0
            self._rejects_at_pass_start = rejects
        return out

    def drain_and_leave(self) -> None:
        if self.client is not None:
            try:
                self.client.drain()
                self.client.leave()
            finally:
                self.client.close()
                self.client = None
