"""Deterministic block map + array wire codec for the parameter server.

The reference partitions every parameter into fixed-size blocks and deals
them across server shards (ref: ParameterServer2.h:120-145 BlockInfo /
BlockIdMap, ParameterConfig blocks) so no shard needs a whole large
parameter and update work load-balances.  This module is the TPU-native
re-expression: each parameter's FLAT value is cut into `block_size`-element
runs, and block `g` (a global counter over parameters in sorted-name
order) lives on shard `g % n_shards`.  The map is a pure function of
(sorted param specs, block_size, n_shards) — every trainer and every
server shard derives the identical map from the `ps_init` config, nothing
is negotiated.

Because the optimizer family (optim/optimizers.py) is elementwise, a
block-granular update is bit-identical to the whole-parameter update —
the property the sync-mode exactness oracle rests on.

Wire codec: arrays travel as {"dtype", "shape", "b64"} with the raw
little-endian bytes base64'd inside the JSON frame — bit-exact by
construction (no float/decimal round trip), debuggable with `nc` like the
rest of the protocol.  On the `send_grad`/`get_params` hot paths, peers
that both advertise the "bin_blocks" hello capability switch to
encode_blocks_bin/decode_blocks_bin: block bytes ride RAW behind a binary
wire frame (serving/wire.py) — ~25% fewer bytes and no base64 encode on
every training step, same bit-exact arrays.  numpy + stdlib only; no jax
(the client side must stay importable on a box with no accelerator
stack).
"""

from __future__ import annotations

import base64
from typing import Iterable, Optional

import numpy as np

#: default elements per block — small enough that a shard map over a few
#: MLP layers actually spreads, large enough that framing overhead stays
#: trivial for real models
DEFAULT_BLOCK_SIZE = 1 << 16


def encode_array(arr: np.ndarray) -> dict:
    """Array -> JSON-safe wire dict; bit-exact round trip."""
    a = np.ascontiguousarray(arr)
    return {"dtype": a.dtype.name, "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d: dict) -> np.ndarray:
    """Wire dict -> array (owns its buffer; writable)."""
    raw = base64.b64decode(d["b64"])
    a = np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
    return a.reshape([int(x) for x in d["shape"]]).copy()


def encode_blocks_bin(blocks: dict) -> tuple[dict, bytes]:
    """{bid: array} -> (JSON-safe meta, one concatenated raw payload) for
    a binary wire frame (serving/wire.py encode_bin): meta records each
    block's dtype/shape plus its [off, off+n) byte span in the payload —
    no base64, no per-element JSON, bit-exact by construction.  Blocks
    are laid out in sorted-bid order (determinism; the meta offsets are
    authoritative either way)."""
    meta = {}
    parts = []
    off = 0
    for bid in sorted(blocks):
        a = np.ascontiguousarray(blocks[bid])
        raw = a.tobytes()
        meta[bid] = {"dtype": a.dtype.name, "shape": list(a.shape),
                     "off": off, "n": len(raw)}
        parts.append(raw)
        off += len(raw)
    return meta, b"".join(parts)


def decode_blocks_bin(meta: dict, payload: bytes) -> dict:
    """(meta, payload) -> {bid: array} (each owns its buffer; writable)
    — the inverse of encode_blocks_bin, same contract as decode_array."""
    out = {}
    view = memoryview(payload)
    for bid, d in meta.items():
        off, n = int(d["off"]), int(d["n"])
        if off < 0 or off + n > len(payload):
            raise ValueError(f"block {bid}: byte span [{off}, {off + n}) "
                             f"overruns the {len(payload)}-byte payload")
        a = np.frombuffer(view[off:off + n], dtype=np.dtype(d["dtype"]))
        out[bid] = a.reshape([int(x) for x in d["shape"]]).copy()
    return out


class BlockRef:
    """One block of one parameter: flat range [start, stop) on `shard`."""

    __slots__ = ("name", "idx", "start", "stop", "shard")

    def __init__(self, name: str, idx: int, start: int, stop: int,
                 shard: int):
        self.name, self.idx = name, idx
        self.start, self.stop = start, stop
        self.shard = shard

    @property
    def bid(self) -> str:
        return f"{self.name}#{self.idx}"

    @property
    def size(self) -> int:
        return self.stop - self.start

    def __repr__(self):
        return (f"BlockRef({self.bid}, [{self.start}:{self.stop}) "
                f"-> s{self.shard})")


class BlockMap:
    """The deterministic (param specs, block_size, n_shards) -> shard map.

    `specs` is {name: (shape tuple, dtype name)}; iteration is ALWAYS over
    sorted names, so two processes building from the same specs hold the
    same global block numbering and therefore the same shard assignment.
    """

    def __init__(self, specs: dict[str, tuple], n_shards: int = 1,
                 block_size: int = DEFAULT_BLOCK_SIZE):
        assert n_shards >= 1 and block_size >= 1
        self.n_shards = int(n_shards)
        self.block_size = int(block_size)
        self.specs = {str(n): (tuple(int(d) for d in shape), str(dtype))
                      for n, (shape, dtype) in specs.items()}
        self.blocks: dict[str, list[BlockRef]] = {}
        g = 0
        for name in sorted(self.specs):
            shape, _ = self.specs[name]
            size = int(np.prod(shape)) if shape else 1
            refs = []
            for i, start in enumerate(range(0, max(size, 1),
                                            self.block_size)):
                stop = min(size, start + self.block_size)
                refs.append(BlockRef(name, i, start, stop,
                                     g % self.n_shards))
                g += 1
            self.blocks[name] = refs
        self.n_blocks = g
        self._by_bid = {r.bid: r for refs in self.blocks.values()
                        for r in refs}

    @classmethod
    def from_arrays(cls, params: dict[str, np.ndarray], n_shards: int = 1,
                    block_size: int = DEFAULT_BLOCK_SIZE) -> "BlockMap":
        return cls({n: (np.shape(a), np.asarray(a).dtype.name)
                    for n, a in params.items()},
                   n_shards=n_shards, block_size=block_size)

    # -- wire config (what ps_init carries) --------------------------------
    def config(self) -> dict:
        return {"block_size": self.block_size, "n_shards": self.n_shards,
                "params": {n: [list(shape), dtype]
                           for n, (shape, dtype) in self.specs.items()}}

    @classmethod
    def from_config(cls, cfg: dict) -> "BlockMap":
        return cls({n: (tuple(shape), dtype)
                    for n, (shape, dtype) in cfg["params"].items()},
                   n_shards=int(cfg["n_shards"]),
                   block_size=int(cfg["block_size"]))

    # -- lookups -----------------------------------------------------------
    def ref(self, bid: str) -> BlockRef:
        return self._by_bid[bid]

    def shard_blocks(self, shard: int) -> list[BlockRef]:
        """This shard's blocks, in global (sorted-name, block-idx) order —
        the canonical iteration order everywhere."""
        return [r for name in sorted(self.blocks)
                for r in self.blocks[name] if r.shard == shard]

    def shard_of(self, bid: str) -> int:
        return self._by_bid[bid].shard

    # -- split / assemble --------------------------------------------------
    def split(self, name: str, arr: np.ndarray,
              shard: Optional[int] = None) -> dict[str, np.ndarray]:
        """One parameter -> {bid: flat block} (optionally only `shard`'s)."""
        flat = np.ascontiguousarray(arr).reshape(-1)
        out = {}
        for r in self.blocks[name]:
            if shard is not None and r.shard != shard:
                continue
            out[r.bid] = flat[r.start:r.stop]
        return out

    def split_all(self, params: dict[str, np.ndarray],
                  shard: Optional[int] = None) -> dict[str, np.ndarray]:
        out = {}
        for name in sorted(self.blocks):
            out.update(self.split(name, params[name], shard=shard))
        return out

    def assemble(self, name: str,
                 blocks: dict[str, np.ndarray]) -> np.ndarray:
        """{bid: flat block} (superset ok) -> the full parameter."""
        shape, dtype = self.specs[name]
        refs = self.blocks[name]
        parts = []
        for r in refs:
            if r.bid not in blocks:
                raise KeyError(f"assemble({name!r}): missing block {r.bid} "
                               f"— pulled from too few shards?")
            part = np.asarray(blocks[r.bid]).reshape(-1)
            if part.size != r.size:
                raise ValueError(f"block {r.bid}: got {part.size} elements, "
                                 f"map says {r.size}")
            parts.append(part)
        flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return flat.reshape(shape).astype(np.dtype(dtype), copy=False)

    def assemble_all(self, blocks: dict[str, np.ndarray]
                     ) -> dict[str, np.ndarray]:
        return {name: self.assemble(name, blocks)
                for name in sorted(self.specs)}

    def names(self) -> Iterable[str]:
        return sorted(self.specs)

    def __eq__(self, other):
        return (isinstance(other, BlockMap)
                and self.config() == other.config())

    def __repr__(self):
        return (f"BlockMap({len(self.specs)} params, {self.n_blocks} "
                f"blocks x <= {self.block_size}, {self.n_shards} shards)")
