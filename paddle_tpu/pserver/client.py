"""ParameterClient: one trainer's connection to the pserver fleet.

The TPU-native ParameterClient2 (ref: paddle/pserver/ParameterClient2.
{h,cpp}: sendAndReceiveParameter, per-server send threads): one blocking
socket per server shard (plus a dedicated CONTROL connection to shard 0
carrying membership — join, heartbeats, drain/leave), speaking the
serving wire framing through `connect_with_backoff(expect_role=
"pserver")`, so a trainer pointed at a serving replica or fleet router
port fails with an error naming both roles instead of a cryptic frame
error several RPCs later.

Deliberately jax-free (numpy + stdlib + serving/wire.py + the retry/
handshake helpers of serving/client.py): the gradient push/param pull
path must be liftable onto any box.  The sync-mode batch flow is:

    send_grad -> every shard (acked = buffered everywhere)
    barrier   -> shard 0 (blocks until the window commits; the reply
                 carries the rank-ordered commit set)
    get_params-> every shard (relaying the commit set, which is what
                 triggers the identical apply on shards 1..N-1)

so a trainer only ever advances on parameters every shard has committed
identically.  Heartbeats ride the control connection from a daemon
thread; an abrupt trainer death drops both sockets and the server
discards its in-flight contribution immediately.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from paddle_tpu.obs.trace import get_tracer
from paddle_tpu.pserver.blocks import (BlockMap, decode_array,
                                       decode_blocks_bin,
                                       encode_array, encode_blocks_bin)
from paddle_tpu.serving import wire
from paddle_tpu.serving.client import connect_with_backoff


class PServerError(RuntimeError):
    """The parameter server answered an error frame."""


class StaleTrainerError(PServerError):
    """This trainer was evicted (heartbeat expiry / connection loss) and
    its window is gone — rejoin and pull fresh parameters."""


class ParameterClient:
    def __init__(self, addrs: list, timeout: float = 300.0,
                 connect_attempts: int = 5,
                 beat_interval_s: float = 1.0):
        """`addrs` = [(host, port), ...] in SHARD ORDER (shard 0 first —
        the membership coordinator)."""
        self.addrs = [(h, int(p)) for h, p in addrs]
        self.timeout = float(timeout)
        self.socks: list[socket.socket] = []
        self.hellos: list[dict] = []
        for i, (h, p) in enumerate(self.addrs):
            sock, hello = connect_with_backoff(
                h, p, timeout, attempts=connect_attempts,
                expect_role="pserver")
            if int(hello.get("shard", -1)) != i:
                sock.close()
                raise PServerError(
                    f"--pserver list order is wrong: {h}:{p} is shard "
                    f"{hello.get('shard')} of {hello.get('n_shards')}, "
                    f"but position {i} in the list — pass the shards in "
                    f"shard-index order")
            if int(hello.get("n_shards", 1)) != len(self.addrs):
                sock.close()
                raise PServerError(
                    f"{h}:{p} serves a {hello.get('n_shards')}-shard "
                    f"fleet but {len(self.addrs)} address(es) were "
                    f"given — every shard must be listed")
            self.socks.append(sock)
            self.hellos.append(hello)
        self.mode = self.hellos[0].get("mode", "sync")
        # hot-path framing: binary block frames only if EVERY shard
        # advertises the capability (an old shard keeps getting JSON)
        self._bin = all("bin_blocks" in (h.get("capabilities") or ())
                        for h in self.hellos)
        # trainer-side pre-accumulation (num_batches_per_send > 1): only
        # usable when EVERY shard knows the send_grad pre_accum flag —
        # an old shard would sample-weight the summed blocks a second
        # time and silently break the grad_accum equivalence
        self.pre_accum_capable = all(
            "pre_accum" in (h.get("capabilities") or ())
            for h in self.hellos)
        # dedicated control connection to the coordinator: membership +
        # heartbeats, so a beat never interleaves with a blocked barrier
        self._ctl, _ = connect_with_backoff(
            self.addrs[0][0], self.addrs[0][1], timeout,
            attempts=connect_attempts, expect_role="pserver")
        self._ctl_lock = threading.Lock()
        self.tid: Optional[str] = None
        self.rank: Optional[int] = None
        self.window = 0
        self.version = 0
        self.pass_id = 0
        self.block_map: Optional[BlockMap] = None
        self._beat_thread: Optional[threading.Thread] = None
        self._beat_stop = threading.Event()
        self._beat_interval = float(beat_interval_s)
        # per-window timing attribution (docs/distributed_training.md
        # "Observability"): push_grads/pull stamp contiguous phase walls
        # into `last_timing`; the RemoteParameterUpdater folds them into
        # the trainer's per-pass metrics.jsonl rows.  The tracer (obs is
        # stdlib-only, so the jax-free claim holds) records the same
        # phases as push[shard]/barrier_wait/pull spans on the `remote`
        # lane — all from the training thread, the single-writer rule.
        self.tracer = get_tracer()
        self.last_timing: dict = {}
        self.last_pull_timings: dict = {}   # shard -> relay-apply timing
        self.last_pull_ms = 0.0
        self.stale_rejects = 0         # async: grads refused as stale
        # wire accounting: every send_grad frame's full on-wire size
        # (length prefix + header + payload) summed here — the counter
        # the pre-accumulation N-fold reduction is proved against
        self.grad_bytes_sent = 0

    # -- plumbing ------------------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        self._beat_stop.set()
        for s in self.socks + [self._ctl]:
            try:
                s.close()
            except OSError:
                pass

    def _rpc(self, shard: int, msg: dict, reply_types: tuple,
             payload: Optional[bytes] = None) -> dict:
        sock = self.socks[shard]
        frame = (wire.encode(msg) if payload is None
                 else wire.encode_bin(msg, payload))
        if msg.get("type") == "send_grad":
            self.grad_bytes_sent += len(frame)
        sock.sendall(frame)
        while True:
            reply = wire.read_frame_sync(sock)
            if reply is None:
                raise ConnectionError(
                    f"pserver shard {shard} closed the connection")
            t = reply.get("type")
            if t == "error":
                err = reply.get("error", "unknown pserver error")
                if "rejoin" in err:
                    raise StaleTrainerError(err)
                raise PServerError(err)
            if t in reply_types:
                return reply
            # pserver connections are strictly request/reply per socket;
            # anything else is protocol drift worth failing loudly on
            raise PServerError(f"unexpected {t!r} frame awaiting "
                               f"{reply_types}")

    def _ctl_rpc(self, msg: dict, reply_types: tuple) -> dict:
        with self._ctl_lock:
            wire.write_frame_sync(self._ctl, msg)
            while True:
                reply = wire.read_frame_sync(self._ctl)
                if reply is None:
                    raise ConnectionError("pserver coordinator closed the "
                                          "control connection")
                t = reply.get("type")
                if t == "error":
                    raise PServerError(reply.get("error", "?"))
                if t in reply_types:
                    return reply

    # -- membership ----------------------------------------------------------
    def join(self, rank: Optional[int] = None) -> dict:
        msg = {"type": "ps_join"}
        if rank is not None:
            msg["rank"] = int(rank)
        reply = self._ctl_rpc(msg, ("ps_join",))
        self.tid = reply["tid"]
        self.rank = int(reply["rank"])
        self.window = int(reply["window"])
        self.version = int(reply["version"])
        self.pass_id = int(reply["pass_id"])
        self._beat_stop.clear()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="pserver-beat", daemon=True)
        self._beat_thread.start()
        return reply

    def _beat_loop(self) -> None:
        while not self._beat_stop.wait(self._beat_interval):
            try:
                with self._ctl_lock:
                    wire.write_frame_sync(
                        self._ctl, {"type": "ps_beat", "tid": self.tid})
            except OSError:
                return                 # server gone: the data path will
                #                        surface the real error loudly

    def drain(self) -> None:
        """Announce departure: the barrier stops waiting for this trainer
        while any already-sent contribution still counts."""
        self._ctl_rpc({"type": "ps_drain", "tid": self.tid},
                      ("ps_drain",))

    def leave(self) -> None:
        self._beat_stop.set()
        try:
            self._ctl_rpc({"type": "ps_leave", "tid": self.tid},
                          ("ps_leave",))
        except (OSError, ConnectionError):
            pass                       # best effort; EOF tells the server

    # -- init / pull ---------------------------------------------------------
    def init_or_fetch(self, params: dict[str, np.ndarray],
                      opt_config_dict: dict, param_cfg_dicts: dict,
                      config_json: Optional[str] = None
                      ) -> dict[str, np.ndarray]:
        """First trainer up seeds the server with its (deterministically
        seeded) initial values; every later trainer verifies the config
        hash and adopts the server's current parameters.  Returns the
        authoritative full parameter dict either way."""
        from paddle_tpu.pserver.blocks import DEFAULT_BLOCK_SIZE
        bm = BlockMap.from_arrays(
            params, n_shards=len(self.addrs),
            block_size=int(self.hellos[0].get("block_size")
                           or DEFAULT_BLOCK_SIZE))
        self.block_map = bm
        cfg = {"map": bm.config(), "opt": opt_config_dict,
               "params": param_cfg_dicts}
        flags = []
        for s in range(len(self.addrs)):
            blocks = bm.split_all(params, shard=s)
            reply = self._rpc(s, {
                "type": "ps_init", "config": cfg,
                "config_json": config_json,
                "blocks": {bid: encode_array(a)
                           for bid, a in blocks.items()}}, ("ps_init",))
            flags.append(bool(reply.get("initialized")))
        if all(flags):
            return dict(params)        # this trainer seeded the fleet
        if any(flags):
            # a single shard restarted mid-job: it just took our FRESH
            # init while the others hold trained state — training on
            # that mix would silently blend pass-N and pass-0 blocks
            fresh = [i for i, f in enumerate(flags) if f]
            raise PServerError(
                f"shard(s) {fresh} had no state and took this trainer's "
                f"fresh init while the other shard(s) hold trained "
                f"parameters — a shard restarted mid-job; restore the "
                f"fleet from its streaming checkpoint (or restart every "
                f"shard together) before rejoining")
        return self.pull()

    def pull(self, want: str = "params",
             apply_members: Optional[list] = None,
             window: Optional[int] = None,
             trace: Optional[dict] = None) -> dict[str, np.ndarray]:
        """Fetch and assemble the full tree from every shard.  With
        `apply_members`, relays the coordinator's commit set so shards
        1..N-1 apply the window before answering.  A plain pull (the
        joiner path) reads shard 0 FIRST and version-gates the rest:
        a shard the commit-set relay has not reached yet answers only
        once it has caught up, so the assembled state always existed
        fleet-wide."""
        t0 = time.perf_counter()
        blocks: dict[str, np.ndarray] = {}
        self.last_pull_timings = {}    # shard -> its window-apply timing
        for s in range(len(self.addrs)):
            msg: dict = {"type": "get_params", "want": want}
            if self._bin:
                msg["bin"] = True
            if trace:
                msg["trace"] = trace
            if apply_members is not None and s != 0:
                msg["apply"] = {"window": window, "members": apply_members}
            elif s != 0:
                msg["min_version"] = self.version
            reply = self._rpc(s, msg, ("params",))
            if s == 0:
                self.version = int(reply["version"])
                self.pass_id = int(reply["pass_id"])
            if reply.get("timing"):
                # a commit-relay reply: this shard just applied the
                # window before answering — its breakdown nests inside
                # the caller's pull phase
                self.last_pull_timings[s] = reply["timing"]
            if wire.PAYLOAD_KEY in reply:
                blocks.update(decode_blocks_bin(reply["blocks"],
                                                reply[wire.PAYLOAD_KEY]))
            else:
                for bid, d in reply["blocks"].items():
                    blocks[bid] = decode_array(d)
        self.last_pull_ms = (time.perf_counter() - t0) * 1e3
        if self.tracer.enabled:
            self.tracer.add("pull", t0, time.perf_counter() - t0,
                            track="remote",
                            attrs={"want": want, **(trace or {})})
        return self.block_map.assemble_all(blocks)

    # -- the batch flow ------------------------------------------------------
    def push_grads(self, grads: dict[str, np.ndarray], samples: int,
                   tag: Optional[str] = None,
                   trace: Optional[dict] = None,
                   pre_accum: bool = False):
        """Sync: contribute one batch's gradients, barrier, return the
        post-window full parameters.  Async: contribute against the last
        pulled version; returns None (pair with pull() on the trainer's
        num_batches_per_get_parameter cadence) — a stale rejection also
        returns None after recording the fleet's version so the next
        pull re-bases.

        `pre_accum=True` marks the blocks as a trainer-side sample-
        weighted fp32 SUM over several batches (`samples` = the summed
        batch sizes): the server adds them to the window accumulator
        with weight 1 instead of re-weighting by `samples`.  Requires
        every shard to advertise the `pre_accum` capability
        (`pre_accum_capable`).

        `trace` ({"trace_id", "parent"}) stamps the window's wire trace
        context on every frame; `last_timing` afterwards holds the
        window's contiguous phase walls (push/barrier_wait/pull ms, plus
        the server-reported apply/skew) — the parts the updater's
        closure-checked per-window attribution is built from."""
        bm = self.block_map
        w = self.window
        tr = self.tracer
        async_t: dict = {}
        t_push0 = time.perf_counter()
        for s in range(len(self.addrs)):
            shard_blocks: dict = {}
            for name in bm.names():
                if name in grads:
                    shard_blocks.update(bm.split(name, grads[name],
                                                 shard=s))
            msg = {"type": "send_grad", "tid": self.tid, "window": w,
                   "samples": int(samples)}
            payload = None
            if self._bin:
                msg["blocks"], payload = encode_blocks_bin(shard_blocks)
            else:
                msg["blocks"] = {bid: encode_array(a)
                                 for bid, a in shard_blocks.items()}
            if tag is not None:
                msg["tag"] = tag
            if trace:
                msg["trace"] = trace
            if pre_accum:
                if not self.pre_accum_capable:
                    raise PServerError(
                        "pre_accum push but a shard lacks the pre_accum "
                        "capability — upgrade the fleet or run "
                        "num_batches_per_send_parameter=1")
                msg["pre_accum"] = True
            if self.mode == "async":
                msg["base_version"] = self.version
            t_s0 = time.perf_counter()
            ack = self._rpc(s, msg, ("grad_ack",), payload=payload)
            if tr.enabled:
                tr.add("push", t_s0, time.perf_counter() - t_s0,
                       track="remote",
                       attrs={"shard": s, "window": w, **(trace or {})})
            if self.mode == "async":
                self.version = int(ack["version"])
                if ack.get("rejected"):
                    self.stale_rejects += 1
                    self.last_timing = {
                        "window": w, "rejected": True,
                        "staleness": int(ack.get("staleness", 0)),
                        "push_ms": round(
                            (time.perf_counter() - t_push0) * 1e3, 3)}
                    return None
                async_t = {"staleness": int(ack.get("staleness", 0)),
                           **(ack.get("timing") or {})}
        t_push1 = time.perf_counter()
        if self.mode == "async":
            self.last_timing = {
                "window": w,
                "push_ms": round((t_push1 - t_push0) * 1e3, 3),
                "apply_ms": async_t.get("apply_ms", 0.0),
                "staleness": async_t.get("staleness", 0)}
            return None
        bmsg = {"type": "barrier", "tid": self.tid, "window": w}
        if trace:
            bmsg["trace"] = trace
        reply = self._rpc(0, bmsg, ("barrier",))
        t_bar1 = time.perf_counter()
        srv_t = reply.get("timing") or {}
        if tr.enabled:
            tr.add("barrier_wait", t_push1, t_bar1 - t_push1,
                   track="remote",
                   attrs={"window": w, "skew_ms": srv_t.get("skew_ms"),
                          **(trace or {})})
        self.window = int(reply["window"]) + 1
        members = reply["members"]
        out = self.pull(apply_members=members, window=w, trace=trace)
        t_end = time.perf_counter()
        # contiguous segments over [t_push0, t_end]: the three parts sum
        # to the client-side window wall EXACTLY (the updater adds the
        # grad_compute segment in front and asserts the closure)
        self.last_timing = {
            "window": w,
            "push_ms": round((t_push1 - t_push0) * 1e3, 3),
            "barrier_wait_ms": round((t_bar1 - t_push1) * 1e3, 3),
            "pull_ms": round((t_end - t_bar1) * 1e3, 3),
            "apply_ms": srv_t.get("apply_ms", 0.0),
            "accum_ms": srv_t.get("accum_ms", 0.0),
            "skew_ms": srv_t.get("skew_ms", 0.0),
            # shards 1..N-1 apply DURING the pull (the commit-set relay
            # triggers them) — the slowest relay apply nests inside
            # pull_ms the way shard 0's apply_ms nests in barrier_wait
            "relay_apply_ms": max(
                (t.get("apply_ms", 0.0)
                 for t in self.last_pull_timings.values()), default=0.0),
        }
        return out

    def pass_barrier(self, trace: Optional[dict] = None) -> int:
        """End-of-pass synchronization: the coordinator runs finish_pass
        once, then the boundary is RELAYED to every other shard (like
        window commit sets ride get_params) so pass-dependent LR
        schedules and snapshot pass labels never drift per shard.
        Returns the new pass_id."""
        t0 = time.perf_counter()
        msg = {"type": "barrier", "tid": self.tid, "kind": "pass"}
        if trace:
            msg["trace"] = trace
        reply = self._rpc(0, msg, ("barrier",))
        self.pass_id = int(reply["pass_id"])
        self.window = int(reply["window"])
        for s in range(1, len(self.addrs)):
            relay = {"type": "barrier", "kind": "pass",
                     "pass_id": self.pass_id}
            if trace:
                relay["trace"] = trace
            self._rpc(s, relay, ("barrier",))
        if self.tracer.enabled:
            # this span OWNS the boundary context's parent id: shard-side
            # pass-commit spans list the trace_id in their trace_ids
            self.tracer.add("pass_barrier", t0,
                            time.perf_counter() - t0, track="remote",
                            attrs={"pass": self.pass_id,
                                   **({"trace_id": trace["trace_id"],
                                       "span_id": trace["parent"]}
                                      if trace else {})})
        return self.pass_id

    # -- ops -----------------------------------------------------------------
    def stats(self, shard: int = 0) -> dict:
        return self._rpc(shard, {"type": "stats"}, ("stats",))

    def metrics(self, shard: int = 0) -> str:
        return self._rpc(shard, {"type": "metrics"}, ("metrics",))["text"]

    def commit_log(self, last: int = 0) -> list[dict]:
        return self._rpc(0, {"type": "ps_log", "last": int(last)},
                         ("ps_log",))["commits"]
