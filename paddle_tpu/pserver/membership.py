"""Elastic trainer membership: the pserver's join/drain/leave state machine.

The heartbeat + state-machine pattern of `fleet/replica.py`, ported to the
OTHER side of the wire: there the router tracks serving replicas, here the
parameter server tracks the trainers contributing gradients.  Like its
sibling it is plain bookkeeping — no sockets, no clocks of its own (every
method takes `now`) — so the tier-1 join/drain/leave tests drive it
deterministically.

State machine (one `TrainerMember` per joined trainer):

    --ps_join--> ACTIVE --ps_drain--> DRAINING --ps_leave--> gone
    ACTIVE/DRAINING --conn lost / heartbeat expiry--> gone (DEAD)

The sync barrier only ever WAITS for ACTIVE members: a DRAINING trainer's
contribution still counts if it arrives (its final in-flight batch is not
lost), but its absence never stalls the fleet; a DEAD trainer's buffered
in-flight contribution is discarded by the server and the barrier
re-evaluates immediately — the pass continues with the surviving ranks.

Ranks: each member carries a `rank`, the data-shard index that also fixes
its position in the gradient reduction order (the exactness contract
reduces contributions in rank order, so K trainers reproduce the
single-process batch order).  Auto-assigned ranks reuse the smallest free
slot, so a restarted trainer slides back into the shard it drained from.
"""

from __future__ import annotations

import time
from typing import Optional

ACTIVE = "active"
DRAINING = "draining"
DEAD = "dead"        # terminal; the member is dropped from the table
LEFT = "left"        # terminal; clean ps_leave

#: states whose members may still contribute to a window
CONTRIBUTING = (ACTIVE, DRAINING)


class TrainerMember:
    """One joined trainer, as the server sees it."""

    __slots__ = ("tid", "rank", "state", "joined_t", "last_beat_t",
                 "grads_sent", "windows_joined")

    def __init__(self, tid: str, rank: int, now: float):
        self.tid = tid
        self.rank = int(rank)
        self.state = ACTIVE
        self.joined_t = now
        self.last_beat_t = now
        self.grads_sent = 0       # contributions received from this trainer
        self.windows_joined = 0   # windows it was part of the commit set

    def beat_age(self, now: float) -> float:
        return now - self.last_beat_t

    def summary(self) -> dict:
        return {"tid": self.tid, "rank": self.rank, "state": self.state,
                "grads_sent": self.grads_sent,
                "windows_joined": self.windows_joined}


class Membership:
    """All live trainers, keyed by server-assigned id t0, t1, ..."""

    def __init__(self):
        self._seq = 0
        self.members: dict[str, TrainerMember] = {}
        self.ever_joined = 0      # total joins over the server's lifetime

    # -- lifecycle ---------------------------------------------------------
    def join(self, rank: Optional[int] = None,
             now: Optional[float] = None) -> TrainerMember:
        """Register one trainer; auto-rank = smallest unused (a restarted
        trainer re-occupies its old data shard)."""
        now = time.monotonic() if now is None else now
        if rank is None:
            used = {m.rank for m in self.members.values()}
            rank = 0
            while rank in used:
                rank += 1
        elif any(m.rank == int(rank) for m in self.members.values()):
            raise ValueError(
                f"rank {rank} is already held by a live trainer — two "
                f"trainers on one data shard would double-count its "
                f"gradients; pick a distinct --rank or let the server "
                f"auto-assign")
        m = TrainerMember(f"t{self._seq}", int(rank), now)
        self._seq += 1
        self.ever_joined += 1
        self.members[m.tid] = m
        return m

    def get(self, tid: str) -> Optional[TrainerMember]:
        return self.members.get(tid)

    def beat(self, tid: str, now: Optional[float] = None) -> bool:
        m = self.members.get(tid)
        if m is None:
            return False
        m.last_beat_t = time.monotonic() if now is None else now
        return True

    def drain(self, tid: str) -> bool:
        """ACTIVE -> DRAINING: stop waiting for this trainer at barriers;
        contributions it still sends are honored."""
        m = self.members.get(tid)
        if m is None or m.state not in (ACTIVE, DRAINING):
            return False
        m.state = DRAINING
        return True

    def undrain(self, tid: str) -> bool:
        m = self.members.get(tid)
        if m is None or m.state != DRAINING:
            return False
        m.state = ACTIVE
        return True

    def leave(self, tid: str) -> Optional[TrainerMember]:
        """Clean departure (ps_leave after the final batch)."""
        m = self.members.pop(tid, None)
        if m is not None:
            m.state = LEFT
        return m

    def drop_dead(self, tid: str) -> Optional[TrainerMember]:
        """Connection lost / heartbeat expired: the trainer is gone NOW;
        the server discards its in-flight contribution."""
        m = self.members.pop(tid, None)
        if m is not None:
            m.state = DEAD
        return m

    def expire(self, timeout_s: float,
               now: Optional[float] = None) -> list[TrainerMember]:
        """Drop every member whose heartbeat is older than `timeout_s`."""
        now = time.monotonic() if now is None else now
        stale = [m for m in self.members.values()
                 if m.beat_age(now) > timeout_s]
        for m in stale:
            self.drop_dead(m.tid)
        return stale

    # -- barrier / commit queries ------------------------------------------
    def required(self, arrived: set) -> set:
        """Tids the sync barrier must still wait for: every ACTIVE member
        not in `arrived`.  DRAINING members never stall the fleet."""
        return {tid for tid, m in self.members.items()
                if m.state == ACTIVE and tid not in arrived}

    def in_rank_order(self, tids) -> list[str]:
        """`tids` filtered to live members, sorted by rank — the gradient
        reduction order of the exactness contract."""
        live = [self.members[t] for t in tids if t in self.members]
        return [m.tid for m in sorted(live, key=lambda m: m.rank)]

    def counts(self) -> dict:
        out = {ACTIVE: 0, DRAINING: 0}
        for m in self.members.values():
            out[m.state] = out.get(m.state, 0) + 1
        return out

    def __len__(self):
        return len(self.members)

    def __iter__(self):
        return iter(list(self.members.values()))

    def summary(self) -> list[dict]:
        return [m.summary() for m in
                sorted(self.members.values(), key=lambda m: m.rank)]
