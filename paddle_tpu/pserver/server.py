"""ParameterServer: the authoritative parameter + optimizer-state tier.

The TPU-native rebuild of the reference's ParameterServer2 (ref:
paddle/pserver/ParameterServer2.{h,cpp}: addGradient :501,
sendBackParameter, per-server parameter blocks :120-145; ProtoServer RPC)
over the serving wire protocol (`serving/wire.py` length-prefixed JSON
frames, hello role "pserver").  One process per shard; a shard holds the
blocks `pserver/blocks.py`'s deterministic map assigns it, plus their
optimizer slots, and applies updates with the REPO'S OWN
`optim/updater.py` math at block granularity — separately jitted but
bit-identical to the fused local train step (the optimizer family is
elementwise; tests/test_train_dist.py pins the oracle).

Architecture — three threads, mirroring the serving server's discipline:

  * the ASYNCIO LOOP owns frames, membership and window bookkeeping
    (single-writer, no cross-thread mutation);
  * an UPDATE THREAD owns the jax math (accumulate + apply), fed by a
    job queue so a slow optimizer apply never blocks heartbeats, and so
    commits are strictly ordered;
  * a SNAPSHOT THREAD streams checkpoints: it captures `(params, state,
    version)` by REFERENCE under a brief lock (updates replace arrays
    wholesale — jax arrays are immutable, so the capture is copy-on-write
    for free) and serializes into the atomic `trainer/checkpoint.py`
    pass-dir format while `send_grad` traffic keeps committing.

Sync mode: a window commits when every ACTIVE member has barrier'd; the
commit set is reduced in RANK order, so K trainers on disjoint stride
shards reproduce a single-process `grad_accum=K` run bit-for-bit (incl.
the LR schedule, weight decay and model averaging — all state lives
here).  A trainer that dies mid-window is dropped (conn EOF or heartbeat
expiry), its buffered in-flight contribution is DISCARDED, and the
barrier re-evaluates — the pass continues with the survivors.

Multi-shard sync: trainers join/barrier at SHARD 0 (the membership
coordinator); its barrier reply carries the window's commit set, which
trainers relay to the other shards inside `get_params` — every shard then
applies the identical rank-ordered reduction.  A trainer only barriers
after every shard acked its `send_grad`, so a commit-set member's
contribution is guaranteed buffered everywhere.

Async mode: no barrier — each contribution applies on arrival, guarded by
a per-trainer version check (`max_staleness` versions behind rejects the
gradient and tells the trainer to re-pull), with the applied staleness
distribution exported honestly as `pserver_async_staleness`.

Observability rides the existing machinery: pserver_* rows in
obs.metrics.CATALOG behind a strict registry (`metrics` frame), flight
events (trainer_join/trainer_leave/trainer_drain/ps_commit/ps_snapshot/
straggler/ps_wedge) on the process-global recorder, and a `dump` frame
freezing a postmortem bundle.  Training-fleet tracing (docs/
distributed_training.md "Observability"): `send_grad`/`barrier`/
`get_params` frames carry the trainer-minted wire trace context, this
shard records `recv_grad` (loop), `accumulate`/`apply`/`commit` (update
thread) and `snapshot` spans adopting it, and a `trace` RPC (loop
thread, stale-ok against a wedged update thread, live `enable` flip)
feeds `tools/trace_dump.py --pull` — so a K-trainer × N-shard run
stitches into ONE Perfetto trace.  The barrier reply carries the
window's `timing` (accum/apply ms + arrival skew) for the trainer's
per-window attribution; the shard-0 coordinator observes per-window
barrier-arrival skew (`pserver_window_skew_ms`, `straggler` events
naming the late rank), and a loop-side watchdog over the update
thread's job lag freezes one postmortem bundle per wedge episode.
Design doc: docs/distributed_training.md.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import queue
import sys
import threading
import time
from collections import deque
from types import SimpleNamespace
from typing import Optional

import numpy as np

from paddle_tpu.obs import MetricsRegistry, tracer_collector
from paddle_tpu.obs.flight import flight_collector, get_flight_recorder
from paddle_tpu.obs.slo import SloEvaluator, default_pserver_slos
from paddle_tpu.obs.timeseries import (HistorySampler, MetricHistory,
                                       history_collector, history_reply)
from paddle_tpu.obs.trace import get_tracer, trace_reply
from paddle_tpu.pserver import membership as mem
from paddle_tpu.pserver.blocks import (BlockMap, decode_array,
                                       decode_blocks_bin, encode_array,
                                       encode_blocks_bin)
from paddle_tpu.pserver.membership import Membership
from paddle_tpu.serving import wire
from paddle_tpu.serving.wire import FrameConn

#: staleness histogram buckets: versions behind at apply (async mode)
_STALENESS_BUCKETS = (0, 1, 2, 4, 8, 16, 32)


class UpdateEngine:
    """The jax half: block store + optimizer state + exact update math.

    Owned by the server's update thread (construction aside); `lock`
    guards only the params/state POINTER swap so the snapshot thread can
    capture a consistent reference set mid-training.  Usable standalone —
    the churn soak's replay oracle drives one directly.
    """

    def __init__(self, block_map: BlockMap, shard_index: int,
                 opt_config, param_cfgs: dict,
                 init_blocks: dict[str, np.ndarray]):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.optim.updater import ParameterUpdater

        self._jax = jax
        self._jnp = jnp
        # spans (accumulate/apply/commit on the "update" lane) land on the
        # owning server's ring; standalone engines (the replay oracle)
        # default to the process-global tracer, off unless a test flips it
        self.tracer = get_tracer()
        self.block_map = block_map
        self.shard_index = int(shard_index)
        self.refs = block_map.shard_blocks(self.shard_index)
        for name, cfg in param_cfgs.items():
            if cfg.update_hooks:
                raise NotImplementedError(
                    f"parameter {name!r} declares updater hooks (pruning "
                    f"masks) — masks are built from FULL-parameter "
                    f"magnitudes, which a block-sharded server cannot "
                    f"reproduce; train this config with the local "
                    f"ParameterUpdater")
        # block-level parameter configs: each block inherits its parent's
        # update knobs (per-param LR/momentum/decay/clipping are all
        # elementwise, so block granularity changes nothing)
        block_cfgs = []
        for r in self.refs:
            cfg = param_cfgs[r.name]
            block_cfgs.append(dataclasses.replace(
                cfg, name=r.bid, size=r.size, dims=[r.size],
                partition_spec=None))
        # windows are the SERVER'S construct here (their size is the live
        # trainer count, decided per commit) — the block updater itself
        # must never open a second accumulation window
        opt = dataclasses.replace(opt_config,
                                  num_batches_per_send_parameter=1)
        self.updater = ParameterUpdater(
            SimpleNamespace(parameters=block_cfgs), opt)
        self.params = {r.bid: jnp.asarray(init_blocks[r.bid])
                       for r in self.refs}
        self.state = self.updater.init_state(self.params)
        self.lock = threading.Lock()
        self.version = 0              # commits applied
        self._updatable = [r.bid for r in self.refs
                           if not param_cfgs[r.name].is_static]

        def _acc_zeros(p):
            dt = jnp.promote_types(p.dtype, jnp.float32) if \
                jnp.issubdtype(p.dtype, jnp.floating) else p.dtype
            return jnp.zeros(p.shape, dt)

        self._acc_zeros = _acc_zeros
        # EXACTNESS: these two mirror optim/updater.py step()'s
        # accumulate branch and apply_branch line for line — the sample-
        # weighted fp32 accumulation (static bsz, like the local step's
        # Python-int batch_size) and the traced-denominator mean + _apply
        from functools import partial

        @partial(jax.jit, static_argnums=(2,))
        def _acc_add(acc, g, bsz):
            return acc + bsz * g.astype(acc.dtype)

        def _apply_window(params, acc, core, n_samples):
            denom = n_samples.astype(jnp.float32)
            mean = {n: (a / denom).astype(a.dtype) for n, a in acc.items()}
            return self.updater._apply(params, mean, core, n_samples)

        self._acc_add = _acc_add
        self._apply_window = jax.jit(_apply_window)

    # -- properties ---------------------------------------------------------
    @property
    def pass_id(self) -> int:
        return int(self.state["pass_id"])

    @property
    def use_average(self) -> bool:
        return self.updater.use_average

    def block_bytes(self) -> int:
        return sum(int(np.dtype(v.dtype).itemsize) * int(np.size(v))
                   for v in self.params.values())

    # -- the commit (update thread) -----------------------------------------
    def commit(self, entries: list[tuple], window=None,
               trace=None) -> dict:
        """Apply one window: `entries` = [(rank, tid, samples,
        {bid: flat grad}, pre_accum)] ALREADY in rank order.  Accumulates
        sample-weighted in fp32 then applies the optimizer once on the
        mean — identical math to the local updater's grad_accum window.
        A `pre_accum` entry's blocks are ALREADY a trainer-side sample-
        weighted fp32 sum over `samples` batches' worth of gradients
        (the client ran the same `_acc_add` ladder locally), so they
        join the accumulator with weight 1 — the mean's denominator
        still counts every underlying sample.

        `window`/`trace` (the committed window id and its contributors'
        trace_ids) only label the accumulate/apply spans and the timing
        breakdown the barrier reply carries — the math never sees them.
        The apply is device-synced before the pointer swap so `apply_ms`
        is honest wall time (the trainers' pull would have paid the sync
        anyway) and a snapshot capture sees concrete arrays."""
        jnp = self._jnp
        assert entries, "commit with no contributions"
        t0 = time.perf_counter()
        acc = {bid: self._acc_zeros(self.params[bid])
               for bid in self._updatable}
        total = 0
        for _rank, _tid, samples, blocks, pre in entries:
            bsz = int(samples)
            total += bsz
            for bid, g in blocks.items():
                if bid in acc:
                    acc[bid] = self._acc_add(acc[bid], jnp.asarray(g),
                                             1 if pre else bsz)
        self._jax.block_until_ready(acc)
        t1 = time.perf_counter()
        new_params, new_state = self._apply_window(
            self.params, acc, self.state,
            jnp.asarray(total, jnp.int32))
        self._jax.block_until_ready(new_params)
        with self.lock:
            self.params = dict(new_params)
            self.state = new_state
            self.version += 1
        t2 = time.perf_counter()
        if self.tracer.enabled:
            attrs = {"version": self.version, "n": len(entries)}
            if window is not None:
                attrs["window"] = window
            if trace:
                attrs["trace_ids"] = trace
            self.tracer.add("accumulate", t0, t1 - t0, track="update",
                            attrs=attrs)
            self.tracer.add("apply", t1, t2 - t1, track="update",
                            attrs=attrs)
            self.tracer.add("commit", t0, t2 - t0, track="update",
                            attrs=attrs)
        return {"version": self.version, "samples": total,
                "n": len(entries),
                "timing": {"accum_ms": round((t1 - t0) * 1e3, 3),
                           "apply_ms": round((t2 - t1) * 1e3, 3),
                           "total_ms": round((t2 - t0) * 1e3, 3)}}

    def async_apply(self, tid: str, samples: int,
                    blocks: dict[str, np.ndarray],
                    trace=None) -> dict:
        """One async contribution = its own window of one."""
        return self.commit([(0, tid, int(samples), blocks, False)],
                           trace=trace)

    def finish_pass(self, trace_ids=None) -> int:
        """`trace_ids` = the pass-boundary frames' contributor contexts
        (attribution only, like commit's `trace`)."""
        t0 = time.perf_counter()
        with self.lock:
            self.state = self.updater.finish_pass(self.state)
        if self.tracer.enabled:
            attrs = {"kind": "pass", "pass": self.pass_id}
            if trace_ids:
                attrs["trace_ids"] = trace_ids
            self.tracer.add("commit", t0, time.perf_counter() - t0,
                            track="update", attrs=attrs)
        return self.pass_id

    # -- reads --------------------------------------------------------------
    def wire_blocks(self, want: str = "params") -> dict[str, dict]:
        """This shard's blocks, wire-encoded.  want='average' serves the
        model-averaging slots (ref: AverageOptimizer — what eval uses)."""
        if want == "average":
            if not self.use_average:
                raise ValueError("this configuration trains without model "
                                 "averaging (settings average_window=0) — "
                                 "pull want='params'")
            src = self.state["average"]
        else:
            src = self.params
        with self.lock:
            src = dict(src)
        return {bid: encode_array(np.asarray(v)) for bid, v in src.items()}

    def wire_blocks_bin(self, want: str = "params") -> tuple[dict, bytes]:
        """wire_blocks, binary flavor: (meta, raw payload) for a binary
        frame — the hot-path encoding peers negotiate via the
        "bin_blocks" hello capability (no base64 on every pull)."""
        if want == "average":
            if not self.use_average:
                raise ValueError("this configuration trains without model "
                                 "averaging (settings average_window=0) — "
                                 "pull want='params'")
            src = self.state["average"]
        else:
            src = self.params
        with self.lock:
            src = dict(src)
        return encode_blocks_bin({bid: np.asarray(v)
                                  for bid, v in src.items()})

    def capture(self) -> dict:
        """Consistent snapshot by reference (copy-on-write: commits swap
        whole arrays, never mutate) — the streaming checkpointer's read."""
        with self.lock:
            return {"params": dict(self.params), "state": dict(self.state),
                    "version": self.version}

    def assemble_full(self, snap: Optional[dict] = None
                      ) -> tuple[dict, dict]:
        """(params, opt_state) at PARAMETER granularity — only meaningful
        when this shard holds every block (n_shards == 1); the sharded
        layout goes through `assemble_sharded_checkpoint` instead."""
        snap = snap or self.capture()
        bm = self.block_map
        np_blocks = {bid: np.asarray(v) for bid, v in snap["params"].items()}
        params = bm.assemble_all(np_blocks)
        state = snap["state"]
        opt: dict = {k: np.asarray(v) for k, v in state.items()
                     if k not in ("slots", "average")}
        slots: dict = {}
        for name in bm.names():
            refs = bm.blocks[name]
            if refs[0].bid not in state["slots"]:
                continue                       # static: no slots
            keys = state["slots"][refs[0].bid].keys()
            slots[name] = {
                k: bm.assemble(name, {r.bid: np.asarray(
                    state["slots"][r.bid][k]) for r in refs})
                for k in keys}
        opt["slots"] = slots
        if "average" in state:
            opt["average"] = {
                name: bm.assemble(name, {
                    r.bid: np.asarray(state["average"][r.bid])
                    for r in bm.blocks[name]})
                for name in bm.names()}
        return params, opt


def _config_hash(bm_config: dict, opt_dict: dict, param_dicts: dict) -> str:
    blob = json.dumps({"map": bm_config, "opt": opt_dict,
                       "params": param_dicts}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def assemble_sharded_checkpoint(save_dir: str, pass_label: str
                                ) -> tuple[dict, dict]:
    """Merge the per-shard pass dirs a multi-shard pserver fleet wrote
    (`<save_dir>/shard-NN/<pass_label>/`) back into full (params,
    opt_state) trees.  The shard-0 dir carries `blockmap.json`."""
    from paddle_tpu.trainer import checkpoint as ckpt

    with open(os.path.join(save_dir, "shard-00", "blockmap.json")) as f:
        bm = BlockMap.from_config(json.load(f))
    blocks: dict = {}
    slot_blocks: dict = {}
    avg_blocks: dict = {}
    scalars: dict = {}
    for s in range(bm.n_shards):
        d = os.path.join(save_dir, f"shard-{s:02d}", pass_label)
        data = ckpt.load_checkpoint(d)
        blocks.update(data["params"])
        opt = data.get("opt") or {}
        for bid, tree in (opt.get("slots") or {}).items():
            slot_blocks[bid] = tree
        for bid, arr in (opt.get("average") or {}).items():
            avg_blocks[bid] = arr
        for k, v in opt.items():
            if k not in ("slots", "average"):
                scalars[k] = v
    params = bm.assemble_all(blocks)
    opt_state: dict = dict(scalars)
    slots: dict = {}
    for name in bm.names():
        refs = bm.blocks[name]
        if refs[0].bid not in slot_blocks:
            continue
        keys = slot_blocks[refs[0].bid].keys()
        slots[name] = {k: bm.assemble(
            name, {r.bid: slot_blocks[r.bid][k] for r in refs})
            for k in keys}
    opt_state["slots"] = slots
    if avg_blocks:
        opt_state["average"] = {
            name: bm.assemble(name, {r.bid: avg_blocks[r.bid]
                                     for r in bm.blocks[name]})
            for name in bm.names()}
    return params, opt_state


class ParameterServer:
    """One parameter-server shard speaking the serving wire protocol."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 shard_index: int = 0, n_shards: int = 1,
                 mode: str = "sync", max_staleness: int = 4,
                 beat_timeout_s: float = 10.0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: int = 0, keep_last: int = 2,
                 commit_log_cap: int = 4096, block_size: int = 0,
                 tracer=None, wedge_threshold_s: float = 30.0,
                 straggler_ms: float = 250.0,
                 history_resolution_s: float = 5.0,
                 history_retention_s: float = 1800.0,
                 slo_specs=None):
        from paddle_tpu.pserver.blocks import DEFAULT_BLOCK_SIZE
        assert mode in ("sync", "async"), mode
        if mode == "async" and int(n_shards) > 1:
            # per-shard arrival order makes staleness decisions diverge
            # across shards — a contribution accepted at shard 0 and
            # rejected at shard 1 would be a SILENT half-applied update;
            # refuse loudly until cross-shard async admission lands
            # (ROADMAP "Distributed training, next increments")
            raise ValueError(
                "async mode is single-shard for now: with n_shards > 1 "
                "the per-shard staleness guards could accept a gradient "
                "on some shards and reject it on others (a silent "
                "half-applied update) — run one shard, or use sync mode")
        self.host, self.port = host, int(port)
        self.shard_index, self.n_shards = int(shard_index), int(n_shards)
        assert 0 <= self.shard_index < self.n_shards
        self.block_size = int(block_size) or DEFAULT_BLOCK_SIZE
        self.mode = mode
        self.max_staleness = int(max_staleness)
        self.beat_timeout_s = float(beat_timeout_s)
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self.keep_last = int(keep_last)
        self.is_coordinator = self.shard_index == 0

        self.engine: Optional[UpdateEngine] = None
        self._config_hash: Optional[str] = None
        self._config_json: Optional[str] = None
        self.membership = Membership()
        self._conn_tid: dict[int, str] = {}      # ctl conn seq -> tid
        # coordinator window state
        self._next_window = 0
        self._contrib: dict[str, dict] = {}      # tid -> contribution
        self._barriers: dict[str, tuple] = {}    # tid -> (conn, t_arrived)
        self._pass_waiters: dict[str, tuple] = {}
        self._pass_traces: dict[str, str] = {}   # tid -> boundary trace_id
        self._committing = False
        self._after_commit: list = []            # deferred loop callbacks
        # non-coordinator apply state
        self._shard_contrib: dict[int, dict] = {}    # window -> tid -> entry
        self._apply_waiters: dict[int, list] = {}    # window -> [(conn, msg)]
        self._minv_waiters: list = []    # [(min_version, conn, msg)] —
        #                                  joiner pulls parked until this
        #                                  shard caught up to shard 0
        self._pass_relaying = False
        self._pass_relay_waiters: list = []
        self._pass_relay_traces: list = []       # boundary trace_ids
        self._applying = False
        self.commit_log: deque = deque(maxlen=int(commit_log_cap))
        self._async_version: dict[str, int] = {}     # tid -> base at pull

        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._bg_thread = None
        self._closed: Optional[asyncio.Event] = None
        self._expire_task = None
        self._draining = False
        self._started_t = time.monotonic()

        # update thread + its wedge watchdog: `_job_started` is stamped
        # by the update thread around each job, so the loop-side watchdog
        # (and the pserver_update_lag_s gauge) can see a single apply
        # wedging without touching the jax state — the serving pump-beat
        # pattern, job-shaped
        self._jobs: "queue.Queue" = queue.Queue()
        self._update_thread: Optional[threading.Thread] = None
        self._update_error: Optional[str] = None
        self._updates_done = 0
        self._job_started: Optional[float] = None
        self.wedge_threshold_s = float(wedge_threshold_s)
        self._wedge_dumped = False    # one bundle per wedge episode
        self._watch_task = None
        self.straggler_ms = float(straggler_ms)
        self.last_skew_ms = 0.0

        # snapshot thread
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_event = threading.Event()
        self._snap_write_lock = threading.Lock()   # drain's final write
        #                          vs an in-flight streaming one: the two
        #                          would race save_checkpoint's re-save
        #                          rename dance on the same pass dir
        self._snap_stop = False
        self.snapshot_in_progress = False
        self.snapshots_written = 0
        self.last_snapshot_path: Optional[str] = None
        self.last_snapshot_seconds = 0.0
        self._snap_hook = None          # test seam: runs between capture
        #                                 and write, on the snapshot thread

        # per-server tracer (default: the process-global ring) — in-process
        # multi-shard tests hand each shard its own Tracer, the per-process
        # shape the `trace` RPC snapshots in a real deployment
        self.tracer = tracer if tracer is not None else get_tracer()
        self.flight = get_flight_recorder()
        self._init_metrics()
        # the health plane (obs/timeseries.py + obs/slo.py): pserver_*
        # series history behind the `history` RPC, with the window-skew
        # SLO burning over the skew histogram's per-window mean.  The
        # sampler thread reads only lock-guarded registry state — it
        # never touches the update thread's jax state.
        self.history = MetricHistory(self.metrics,
                                     resolution_s=history_resolution_s,
                                     retention_s=history_retention_s)
        self.metrics.register_collector(history_collector(self.history))
        self.slo = SloEvaluator(
            self.history,
            default_pserver_slos() if slo_specs is None else slo_specs,
            flight=self.flight, registry=self.metrics,
            dump_fn=self._slo_dump)
        self.history_sampler = HistorySampler(self.history,
                                              on_sample=self.slo.evaluate)

    # -- metrics -------------------------------------------------------------
    def _init_metrics(self) -> None:
        self.metrics = MetricsRegistry(strict=True)
        self._m_updates = self.metrics.counter("pserver_updates_total")
        self._m_grads = self.metrics.counter("pserver_grads_received_total")
        self._m_discarded = self.metrics.counter(
            "pserver_grads_discarded_total")
        self._m_async_rej = self.metrics.counter(
            "pserver_async_rejected_total")
        self._m_snapshots = self.metrics.counter("pserver_snapshots_total")
        self._m_staleness = self.metrics.histogram(
            "pserver_async_staleness", buckets=_STALENESS_BUCKETS)
        self._m_barrier_wait = self.metrics.histogram(
            "pserver_barrier_wait_seconds")
        self._m_snap_s = self.metrics.histogram("pserver_snapshot_seconds")
        self._m_skew = self.metrics.histogram(
            "pserver_window_skew_ms",
            buckets=(1.0, 5.0, 25.0, 100.0, 250.0, 1000.0, 5000.0))
        self._m_apply_s = self.metrics.histogram("pserver_apply_seconds")
        g = self.metrics.gauge
        g("pserver_update_lag_s").set_fn(self.update_lag)
        g("pserver_update_alive").set_fn(
            lambda: 1.0 if self.update_alive() else 0.0)
        g("pserver_version").set_fn(
            lambda: float(self.engine.version) if self.engine else 0.0)
        g("pserver_pass_id").set_fn(
            lambda: float(self.engine.pass_id) if self.engine else 0.0)
        g("pserver_trainers_active").set_fn(
            lambda: float(self.membership.counts()[mem.ACTIVE]))
        g("pserver_trainers_draining").set_fn(
            lambda: float(self.membership.counts()[mem.DRAINING]))
        g("pserver_blocks").set_fn(
            lambda: float(len(self.engine.refs)) if self.engine else 0.0)
        g("pserver_block_bytes").set_fn(
            lambda: float(self.engine.block_bytes()) if self.engine else 0.0)
        self.metrics.register_collector(flight_collector(self.flight))
        self.metrics.register_collector(tracer_collector(self.tracer))

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._closed = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._update_thread = threading.Thread(
            target=self._update_loop, name="pserver-update", daemon=True)
        self._update_thread.start()
        if self.snapshot_dir:
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, name="pserver-snapshot",
                daemon=True)
            self._snap_thread.start()
        self._expire_task = self._loop.create_task(self._expire_loop())
        # the wedge watchdog rides the LOOP thread (it must keep running
        # exactly when the update thread cannot) — crossing the threshold
        # records a ps_wedge event and freezes one postmortem bundle
        self._watch_task = self._loop.create_task(self._wedge_watchdog())
        self.history_sampler.start()
        return self.host, self.port

    async def drain(self, final_snapshot: bool = True) -> None:
        """SIGTERM path: refuse new work, fail open barriers honestly,
        write one final checkpoint, close."""
        self._draining = True
        for tid, (conn, _t) in list(self._barriers.items()):
            conn.send({"type": "error", "op": "barrier",
                       "error": "parameter server draining"})
        self._barriers.clear()
        for tid, (conn, _t) in list(self._pass_waiters.items()):
            conn.send({"type": "error", "op": "barrier",
                       "error": "parameter server draining"})
        self._pass_waiters.clear()
        if final_snapshot and self.snapshot_dir and self.engine is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._write_snapshot, "drain")
        await self._shutdown()

    async def stop(self) -> None:
        await self.drain(final_snapshot=False)

    async def _shutdown(self) -> None:
        self.history_sampler.stop()
        if self._expire_task is not None:
            self._expire_task.cancel()
            self._expire_task = None
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        self._jobs.put(("stop",))
        self._snap_stop = True
        self._snap_event.set()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()

    def start_background(self) -> tuple[str, int]:
        started = threading.Event()
        addr: list = []

        async def _amain():
            addr.extend(await self.start())
            started.set()
            await self.wait_closed()

        self._bg_thread = threading.Thread(
            target=lambda: asyncio.run(_amain()),
            name="pserver-loop", daemon=True)
        self._bg_thread.start()
        if not started.wait(timeout=60):
            raise RuntimeError("parameter server failed to bind within 60s")
        return addr[0], addr[1]

    def stop_background(self, drain: bool = True, timeout: float = 120):
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.drain() if drain else self.stop(), self._loop)
        fut.result(timeout=timeout)
        if self._bg_thread is not None:
            self._bg_thread.join(timeout=timeout)

    # -- update thread -------------------------------------------------------
    def _update_loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job[0] == "stop":
                return
            self._job_started = time.monotonic()
            try:
                if job[0] == "commit":
                    _, entries, cb, info = job
                    out = self.engine.commit(
                        entries, window=info.get("window"),
                        trace=info.get("trace"))
                    self._m_apply_s.observe(
                        out["timing"]["total_ms"] / 1e3)
                    self._m_updates.inc()
                    self._updates_done += 1
                    if self.snapshot_every and self.snapshot_dir and \
                            self._updates_done % self.snapshot_every == 0:
                        self._snap_event.set()
                elif job[0] == "async":
                    _, tid, samples, blocks, cb, trace = job
                    out = self.engine.async_apply(tid, samples, blocks,
                                                  trace=trace)
                    self._m_apply_s.observe(
                        out["timing"]["total_ms"] / 1e3)
                    self._m_updates.inc()
                    self._updates_done += 1
                    if self.snapshot_every and self.snapshot_dir and \
                            self._updates_done % self.snapshot_every == 0:
                        self._snap_event.set()
                elif job[0] == "pass":
                    # snapshot: the relay path hands the LIVE list so
                    # late-arriving relays still attribute to this pass
                    _, cb, traces = job
                    out = {"pass_id": self.engine.finish_pass(
                        trace_ids=list(traces) or None)}
                else:                  # pragma: no cover — unknown job
                    self._job_started = None
                    continue
            except Exception as e:     # noqa: BLE001 — surfaced to clients
                self._update_error = f"{type(e).__name__}: {e}"
                out = {"error": self._update_error}
            self._job_started = None
            self._loop.call_soon_threadsafe(cb, out)

    def update_alive(self) -> bool:
        return self._update_error is None and \
            self._update_thread is not None and self._update_thread.is_alive()

    def update_lag(self) -> float:
        """Seconds the update thread has been inside its CURRENT job
        (0.0 when idle) — the wedge signal.  A healthy apply is
        milliseconds; a lag crossing `wedge_threshold_s` means a hung
        compiled step / stuck host callback, exactly the state the
        stale-ok stats/metrics/trace frames must stay readable through."""
        t = self._job_started
        return 0.0 if t is None else max(0.0, time.monotonic() - t)

    async def _wedge_watchdog(self) -> None:
        """Loop-side wedge detector (the serving pump watchdog, ported to
        the update thread): when one job's lag crosses the threshold,
        record a ps_wedge event and freeze exactly ONE postmortem bundle
        for the episode; re-arm when the job completes, so a flapping
        apply produces one bundle per episode, not one per poll."""
        period = max(0.05, min(1.0, self.wedge_threshold_s / 4.0))
        while True:
            await asyncio.sleep(period)
            lag = self.update_lag()
            if lag > self.wedge_threshold_s and self.update_alive():
                if not self._wedge_dumped:
                    self._wedge_dumped = True
                    self.flight.record("ps_wedge", lag_s=round(lag, 3),
                                       window=self._next_window)
                    if self.snapshot_dir:
                        try:
                            self.flight.dump(
                                self.snapshot_dir, reason="update_wedge",
                                spans=self.tracer.snapshot(),
                                engine=self._stats_msg(),
                                metrics=self.metrics.snapshot(),
                                config=self._config_snapshot(),
                                history=self.history.snapshot(),
                                error=f"update thread wedged: current "
                                      f"job running {lag:.1f}s "
                                      f"(threshold "
                                      f"{self.wedge_threshold_s:g}s)")
                        except OSError as e:
                            print(f"pserver: wedge dump failed: {e}",
                                  file=sys.stderr, flush=True)
            elif lag <= self.wedge_threshold_s:
                self._wedge_dumped = False

    # -- snapshot thread -----------------------------------------------------
    def _snapshot_loop(self) -> None:
        while True:
            self._snap_event.wait()
            self._snap_event.clear()
            if self._snap_stop:
                return
            if self.engine is None:
                continue
            try:
                self._write_snapshot("stream")
            except Exception as e:     # noqa: BLE001 — a failed snapshot
                # must not kill the tier; the next trigger retries
                print(f"pserver: snapshot failed: {type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)

    def _write_snapshot(self, why: str) -> str:
        """Capture by reference (brief lock), then serialize WITHOUT
        pausing the update thread — `send_grad` keeps committing while
        the npz writes (the no-stall regression pins this)."""
        with self._snap_write_lock:
            return self._write_snapshot_locked(why)

    def _write_snapshot_locked(self, why: str) -> str:
        from paddle_tpu.trainer import checkpoint as ckpt

        t0 = time.perf_counter()
        snap = self.engine.capture()
        self.snapshot_in_progress = True
        try:
            if self._snap_hook is not None:
                self._snap_hook(snap)
            pass_id = self.engine.pass_id
            if self.n_shards == 1:
                params, opt = self.engine.assemble_full(snap)
                out_dir = self.snapshot_dir
            else:
                # block-granular shard dir + the map to reassemble with
                out_dir = os.path.join(self.snapshot_dir,
                                       f"shard-{self.shard_index:02d}")
                os.makedirs(out_dir, exist_ok=True)
                with open(os.path.join(out_dir, "blockmap.json"), "w") as f:
                    json.dump(self.engine.block_map.config(), f)
                params = {bid: np.asarray(v)
                          for bid, v in snap["params"].items()}
                state = snap["state"]
                opt = {k: np.asarray(v) for k, v in state.items()
                       if k not in ("slots", "average")}
                opt["slots"] = {bid: {k: np.asarray(v)
                                      for k, v in tree.items()}
                                for bid, tree in state["slots"].items()}
                if "average" in state:
                    opt["average"] = {bid: np.asarray(v) for bid, v
                                      in state["average"].items()}
            path = ckpt.save_checkpoint(
                out_dir, pass_id - 1, params, opt_state=opt,
                config_json=self._config_json, keep_last=self.keep_last)
            dt = time.perf_counter() - t0
            self.snapshots_written += 1
            self.last_snapshot_path = path
            self.last_snapshot_seconds = dt
            self._m_snapshots.inc()
            self._m_snap_s.observe(dt)
            if self.tracer.enabled:
                self.tracer.add("snapshot", t0, dt, track="snapshot",
                                attrs={"why": why,
                                       "version": snap["version"]})
            self.flight.record("ps_snapshot", path=path, why=why,
                               version=snap["version"],
                               seconds=round(dt, 4))
            return path
        finally:
            self.snapshot_in_progress = False

    # -- membership plumbing (loop thread) -----------------------------------
    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.beat_timeout_s / 3.0, 0.05))
            for m in self.membership.expire(self.beat_timeout_s):
                self._trainer_gone(m.tid, "heartbeat expired")

    def _trainer_gone(self, tid: str, why: str) -> None:
        """Dead trainer: discard in-flight work, re-size the barrier."""
        m = self.membership.drop_dead(tid) or \
            SimpleNamespace(tid=tid, rank=-1)
        if self._contrib.pop(tid, None) is not None:
            self._m_discarded.inc()
        self._barriers.pop(tid, None)
        self._pass_waiters.pop(tid, None)
        self._pass_traces.pop(tid, None)
        self._async_version.pop(tid, None)
        self.flight.record("trainer_leave", tid=tid, rank=m.rank, why=why)
        self._maybe_commit()

    # -- sync window commit (coordinator, loop thread) -----------------------
    def _maybe_commit(self) -> None:
        if self._committing or self._draining or not self.is_coordinator:
            return
        arrived = set(self._barriers) | set(self._pass_waiters)
        if self._barriers and not self.membership.required(arrived):
            self._commit_window()
        elif self._pass_waiters and not self._barriers and \
                not self.membership.required(set(self._pass_waiters)):
            self._commit_pass()

    def _window_skew(self, waiters: dict) -> float:
        """Per-rank barrier-arrival skew for the closing window: last
        arriver minus first, in ms, observed into the histogram; past
        `straggler_ms` a `straggler` flight event NAMES the late rank —
        the 1605.08695 lesson that PS-architecture stragglers are the
        scaling killer you must measure before you tune."""
        arrivals = [(t_arr, tid) for tid, (_c, t_arr) in waiters.items()]
        if not arrivals:
            return 0.0
        t_first = min(t for t, _ in arrivals)
        t_last, tid_last = max(arrivals)
        skew_ms = (t_last - t_first) * 1e3
        self._m_skew.observe(skew_ms)
        self.last_skew_ms = skew_ms
        if len(arrivals) >= 2 and skew_ms > self.straggler_ms:
            m = self.membership.get(tid_last)
            rank = m.rank if m is not None else -1
            self.flight.record("straggler", tid=tid_last, rank=rank,
                               window=self._next_window,
                               skew_ms=round(skew_ms, 3))
            if self.tracer.enabled:
                self.tracer.instant("straggler", track="pserver",
                                    rank=rank, window=self._next_window,
                                    skew_ms=round(skew_ms, 3))
        return skew_ms

    def _commit_window(self) -> None:
        w = self._next_window
        order = self.membership.in_rank_order(list(self._barriers))
        entries = []
        members = []
        traces = []
        for tid in order:
            c = self._contrib.get(tid)
            if c is None:
                continue               # barrier'd without grads: no-op rank
            m = self.membership.get(tid)
            entries.append((m.rank, tid, c["samples"], c["blocks"],
                            c.get("pre", False)))
            members.append([tid, m.rank, c["samples"], c.get("tag")])
            if c.get("trace"):
                traces.append(c["trace"]["trace_id"])
            m.windows_joined += 1
        waiters = dict(self._barriers)
        skew_ms = self._window_skew(waiters)
        self._barriers.clear()
        self._contrib.clear()
        self._committing = True

        def done(out: dict) -> None:
            self._committing = False
            if "error" in out:
                for tid, (conn, _t) in waiters.items():
                    conn.send({"type": "error", "op": "barrier",
                               "error": f"update failed: {out['error']}"})
                # joins/reads parked against this commit must not hang
                # until their socket timeout — replay them against the
                # (unchanged — commit applies atomically) state
                pend, self._after_commit = self._after_commit, []
                for cb in pend:
                    cb()
                return
            version = out.get("version",
                              self.engine.version if self.engine else 0)
            self._next_window = w + 1
            self.commit_log.append({"window": w, "version": version,
                                    "members": members})
            self.flight.record("ps_commit", window=w, version=version,
                               n=len(members))
            now = time.monotonic()
            # the window's server-side timing breakdown rides the barrier
            # reply: the trainer folds apply_ms into its per-window
            # attribution (nested inside its own barrier_wait_ms)
            timing = dict(out.get("timing") or {})
            timing["skew_ms"] = round(skew_ms, 3)
            reply = {"type": "barrier", "window": w, "version": version,
                     "members": members, "timing": timing}
            for tid, (conn, t_arr) in waiters.items():
                self._m_barrier_wait.observe(now - t_arr)
                conn.send(dict(reply, tid=tid))
            pend, self._after_commit = self._after_commit, []
            for cb in pend:
                cb()
            self._maybe_commit()

        if entries:
            self._jobs.put(("commit", entries, done,
                            {"window": w, "trace": traces or None}))
        else:
            # every barrierer arrived grad-less (possible but degenerate):
            # advance the window without an optimizer apply
            done({"version": self.engine.version if self.engine else 0})

    def _commit_pass(self) -> None:
        if self._contrib:
            # contributions without barriers at pass end mirror the local
            # updater's drop-last convention: discarded, loudly counted
            self._m_discarded.inc(len(self._contrib))
            self._contrib.clear()
        waiters = dict(self._pass_waiters)
        self._pass_waiters.clear()
        traces = [self._pass_traces.pop(tid) for tid in waiters
                  if tid in self._pass_traces]
        self._committing = True

        def done(out: dict) -> None:
            self._committing = False
            if "error" in out:
                for tid, (conn, _t) in waiters.items():
                    conn.send({"type": "error", "op": "barrier",
                               "error": f"finish_pass failed: "
                                        f"{out['error']}"})
                pend, self._after_commit = self._after_commit, []
                for cb in pend:
                    cb()
                return
            # the commit log records pass boundaries too: the churn
            # soak's replay oracle must re-run finish_pass at the same
            # point in the update sequence (LR pass schedules)
            self.commit_log.append({"pass": out["pass_id"],
                                    "window": self._next_window})
            for tid, (conn, t_arr) in waiters.items():
                self._m_barrier_wait.observe(time.monotonic() - t_arr)
                conn.send({"type": "barrier", "kind": "pass", "tid": tid,
                           "pass_id": out["pass_id"],
                           "window": self._next_window})
            pend, self._after_commit = self._after_commit, []
            for cb in pend:
                cb()
            self._maybe_commit()

        self._jobs.put(("pass", done, traces))

    # -- non-coordinator apply (loop thread) ---------------------------------
    def _maybe_apply_shard(self, w: int) -> None:
        if self._applying or w != self._next_window:
            return
        waiting = self._apply_waiters.get(w) or []
        if not waiting:
            return
        members = waiting[0][1]["apply"]["members"]
        have = self._shard_contrib.get(w, {})
        if any(tid not in have for tid, *_rest in members):
            return                     # a member's send_grad is in flight
        entries = [(rank, tid, have[tid]["samples"], have[tid]["blocks"],
                    have[tid].get("pre", False))
                   for tid, rank, _samples, *_tag in members]
        traces = [have[tid]["trace"]["trace_id"]
                  for tid, *_rest in members if have[tid].get("trace")]
        # a dead trainer's buffered contribution (it never made the
        # commit set) dies with the window bucket
        extra = len(have) - len(entries)
        if extra > 0:
            self._m_discarded.inc(extra)
        self._shard_contrib.pop(w, None)
        self._applying = True

        def done(out: dict) -> None:
            self._applying = False
            # pop at COMPLETION, not at queue time: a second trainer's
            # relay arriving while the apply is in flight joins this
            # list and must be answered here, not orphaned
            waiters = self._apply_waiters.pop(w, [])
            if "error" in out:
                for conn, msg in waiters:
                    conn.send({"type": "error", "id": msg.get("id"),
                               "op": "get_params",
                               "error": f"update failed: {out['error']}"})
                # a version-gated joiner pull can never be satisfied by
                # a shard whose update thread just failed — error it
                # out instead of letting it ride to the socket timeout
                parked, self._minv_waiters = self._minv_waiters, []
                for _v, conn, msg in parked:
                    conn.send({"type": "error", "id": msg.get("id"),
                               "op": "get_params",
                               "error": f"update failed: {out['error']}"})
                return
            self._next_window = w + 1
            self.commit_log.append({"window": w,
                                    "version": self.engine.version,
                                    "members": members})
            self.flight.record("ps_commit", window=w,
                               version=self.engine.version, n=len(members))
            timing = out.get("timing")
            for conn, msg in waiters:
                self._reply_params(conn, msg, timing=timing)
            # joiner pulls parked on a minimum version: answer the ones
            # this apply satisfied
            still, ready = [], []
            for v, conn, msg in self._minv_waiters:
                (ready if self.engine.version >= v else still).append(
                    (v, conn, msg))
            self._minv_waiters = still
            for _v, conn, msg in ready:
                self._reply_params(conn, msg)
            self._maybe_apply_shard(self._next_window)

        if entries:
            self._jobs.put(("commit", entries, done,
                            {"window": w, "trace": traces or None}))
        else:
            done({})

    # -- connection handling -------------------------------------------------
    async def _handle(self, reader, writer) -> None:
        conn = FrameConn(writer)
        first = True
        try:
            while True:
                try:
                    msg = await wire.read_frame(reader)
                except wire.FrameError as e:
                    err = str(e)
                    if first:
                        # a peer speaking the wrong protocol deserves to
                        # be told what this socket is
                        err += (f"; this is a parameter server — expected "
                                f"the {wire.PROTO_DESC}")
                    conn.send({"type": "error", "error": err})
                    break
                if msg is None:
                    break
                first = False
                try:
                    self._dispatch(conn, msg)
                except Exception as e:  # noqa: BLE001 — conn must survive
                    conn.send({"type": "error", "id": msg.get("id"),
                               "error": f"{type(e).__name__}: {e}"})
        finally:
            tid = self._conn_tid.pop(conn.seq, None)
            if tid is not None and self.membership.get(tid) is not None:
                self._trainer_gone(tid, "connection lost")
            try:
                writer.close()
            except (ConnectionError, RuntimeError):
                pass

    # -- frame dispatch (loop thread) ----------------------------------------
    def _dispatch(self, conn: FrameConn, msg: dict) -> None:
        t = msg.get("type")
        if t == "ping":
            conn.send({"type": "pong"})
        elif t == "hello":
            conn.send(wire.hello_msg(
                "pserver", shard=self.shard_index, n_shards=self.n_shards,
                mode=self.mode, block_size=self.block_size,
                initialized=self.engine is not None,
                version=self.engine.version if self.engine else 0,
                capabilities=sorted([
                    "hello", "ping", "ps_init", "ps_join", "ps_beat",
                    "ps_drain", "ps_leave", "send_grad", "barrier",
                    "get_params", "stats", "metrics", "dump", "ps_log",
                    "trace", "bin_blocks", "pre_accum", "history"])))
        elif t == "ps_init":
            self._handle_init(conn, msg)
        elif t == "ps_join":
            self._handle_join(conn, msg)
        elif t == "ps_beat":
            self.membership.beat(str(msg.get("tid")))
        elif t == "ps_drain":
            tid = str(msg.get("tid"))
            ok = self.membership.drain(tid)
            if ok:
                m = self.membership.get(tid)
                self.flight.record("trainer_drain", tid=tid, rank=m.rank)
            conn.send({"type": "ps_drain", "tid": tid, "ok": ok})
            self._maybe_commit()
        elif t == "ps_leave":
            tid = str(msg.get("tid"))
            m = self.membership.leave(tid)
            if m is not None:
                self._contrib.pop(tid, None)
                self._barriers.pop(tid, None)
                self._pass_waiters.pop(tid, None)
                self._pass_traces.pop(tid, None)
                self.flight.record("trainer_leave", tid=tid, rank=m.rank,
                                   why="left")
            conn.send({"type": "ps_leave", "tid": tid,
                       "ok": m is not None})
            self._maybe_commit()
        elif t == "send_grad":
            self._handle_send_grad(conn, msg)
        elif t == "barrier":
            self._handle_barrier(conn, msg)
        elif t == "get_params":
            self._handle_get_params(conn, msg)
        elif t == "stats":
            conn.send(self._stats_msg())
        elif t == "metrics":
            conn.send({"type": "metrics", "text": self.metrics.render()})
        elif t == "ps_log":
            n = int(msg.get("last", 0)) or len(self.commit_log)
            conn.send({"type": "ps_log",
                       "commits": list(self.commit_log)[-n:],
                       "next_window": self._next_window})
        elif t == "dump":
            self._handle_dump(conn, msg)
        elif t == "trace":
            # trace collection over the wire — loop thread, stale-ok like
            # `metrics`/`stats`: snapshot() is safe concurrent with the
            # update thread, so trace_dump --pull works against a wedged
            # or dead optimizer apply (exactly when an operator pulls).
            # `enable` flips tracing LIVE (no restart) — the train_dist
            # overhead probe's same-fleet A/B switch; the flip applies
            # before the snapshot, so enable:false returns the spans it
            # just froze.
            conn.send(trace_reply(self.tracer, msg, "pserver",
                                  self.host, self.port,
                                  shard=self.shard_index))
        elif t == "history":
            # the health plane's ring — loop thread, stale-ok like
            # `trace`: reads only lock-guarded ring state, so it answers
            # against a wedged update thread (obs/timeseries.py)
            conn.send(history_reply(self.history, msg, "pserver",
                                    self.host, self.port,
                                    shard=self.shard_index))
        elif t in ("generate", "cancel", "fleet"):
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": f"{t!r} belongs to a serving replica/"
                                f"router — this is a parameter server "
                                f"(hello role 'pserver', tools/pserver.py)"
                                f"; point serving clients at tools/"
                                f"serve.py"})
        else:
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": f"unknown message type {t!r}"})

    def _handle_init(self, conn: FrameConn, msg: dict) -> None:
        from paddle_tpu.config.schema import (OptimizationConfig,
                                              ParameterConfig)

        cfg = msg["config"]
        h = _config_hash(cfg["map"], cfg["opt"], cfg["params"])
        if self.engine is not None:
            if h != self._config_hash:
                conn.send({"type": "error", "op": "ps_init",
                           "error": f"configuration mismatch: this server "
                                    f"was initialized with config hash "
                                    f"{self._config_hash}, the joining "
                                    f"trainer sent {h} — all trainers of "
                                    f"one job must share the exact model/"
                                    f"optimizer configuration"})
                return
            conn.send({"type": "ps_init", "initialized": False,
                       "version": self.engine.version})
            return
        bm = BlockMap.from_config(cfg["map"])
        if bm.block_size != self.block_size:
            conn.send({"type": "error", "op": "ps_init",
                       "error": f"trainer block map uses block_size "
                                f"{bm.block_size}, this server announced "
                                f"{self.block_size} — derive the map from "
                                f"the hello frame"})
            return
        if bm.n_shards != self.n_shards:
            conn.send({"type": "error", "op": "ps_init",
                       "error": f"trainer derived a {bm.n_shards}-shard "
                                f"block map but this server runs "
                                f"{self.n_shards} shard(s) — the "
                                f"--pserver list and the fleet size "
                                f"disagree"})
            return
        opt = OptimizationConfig.from_dict(cfg["opt"])
        pcfgs = {n: ParameterConfig.from_dict(d)
                 for n, d in cfg["params"].items()}
        blocks = {bid: decode_array(d)
                  for bid, d in (msg.get("blocks") or {}).items()}
        self.engine = UpdateEngine(bm, self.shard_index, opt, pcfgs, blocks)
        self.engine.tracer = self.tracer
        self._config_hash = h
        self._config_json = msg.get("config_json")
        conn.send({"type": "ps_init", "initialized": True, "version": 0})

    def _handle_join(self, conn: FrameConn, msg: dict) -> None:
        if not self.is_coordinator:
            conn.send({"type": "error", "op": "ps_join",
                       "error": f"shard {self.shard_index} is not the "
                                f"membership coordinator — join at shard "
                                f"0 and only push/pull blocks here"})
            return
        if self._draining:
            conn.send({"type": "error", "op": "ps_join",
                       "error": "parameter server draining"})
            return
        if self._committing:
            # a joiner must observe post-commit state: park the join
            # until the in-flight window lands
            self._after_commit.append(
                lambda c=conn, m=msg: self._handle_join(c, m))
            return
        rank = msg.get("rank")
        try:
            m = self.membership.join(rank=rank)
        except ValueError as e:
            conn.send({"type": "error", "op": "ps_join", "error": str(e)})
            return
        self._conn_tid[conn.seq] = m.tid
        self.flight.record("trainer_join", tid=m.tid, rank=m.rank)
        conn.send({"type": "ps_join", "tid": m.tid, "rank": m.rank,
                   "window": self._next_window,
                   "version": self.engine.version if self.engine else 0,
                   "pass_id": self.engine.pass_id if self.engine else 0,
                   "n_trainers": len(self.membership)})

    def _handle_send_grad(self, conn: FrameConn, msg: dict) -> None:
        if self.engine is None:
            conn.send({"type": "error", "op": "send_grad",
                       "error": "server not initialized — ps_init first"})
            return
        t0 = time.perf_counter()
        tid = str(msg.get("tid"))
        w = int(msg.get("window", -1))
        samples = int(msg.get("samples", 0))
        if wire.PAYLOAD_KEY in msg:
            # binary frame (bin_blocks capability): block meta in the
            # header, raw bytes behind it — no per-block base64 decode
            blocks = decode_blocks_bin(msg["blocks"],
                                       msg[wire.PAYLOAD_KEY])
        else:
            blocks = {bid: decode_array(d)
                      for bid, d in msg["blocks"].items()}
        # wire-level trace context: the trainer minted one trace_id for
        # this window and stamped it on the frame; adopting it as span
        # attrs is what joins this shard's recv/apply spans to the
        # trainer's window span in a stitched trace
        trace = wire.get_trace(msg)
        self._m_grads.inc()
        if self.tracer.enabled:
            self.tracer.add("recv_grad", t0, time.perf_counter() - t0,
                            track="pserver",
                            attrs={"tid": tid, "window": w,
                                   **(trace or {})})
        if self.mode == "async":
            self._handle_async_grad(conn, msg, tid, samples, blocks,
                                    trace)
            return
        if self.is_coordinator:
            m = self.membership.get(tid)
            if m is None:
                conn.send({"type": "error", "op": "send_grad", "tid": tid,
                           "error": f"trainer {tid!r} is not a member — "
                                    f"it was evicted (heartbeat expiry or "
                                    f"connection loss) or never joined; "
                                    f"rejoin with ps_join and pull fresh "
                                    f"parameters"})
                return
            if w != self._next_window:
                conn.send({"type": "error", "op": "send_grad", "tid": tid,
                           "error": f"window {w} is stale: the fleet is "
                                    f"at window {self._next_window} (this "
                                    f"trainer was evicted mid-window?) — "
                                    f"rejoin and pull fresh parameters"})
                return
            m.grads_sent += 1
            self._contrib[tid] = {"samples": samples, "blocks": blocks,
                                  "tag": msg.get("tag"), "trace": trace,
                                  "pre": bool(msg.get("pre_accum"))}
        else:
            self._shard_contrib.setdefault(w, {})[tid] = {
                "samples": samples, "blocks": blocks, "trace": trace,
                "pre": bool(msg.get("pre_accum"))}
            self._maybe_apply_shard(w)
        conn.send({"type": "grad_ack", "tid": tid, "window": w})

    def _handle_async_grad(self, conn, msg, tid, samples, blocks,
                           trace=None) -> None:
        base = int(msg.get("base_version", 0))
        staleness = self.engine.version - base
        if staleness > self.max_staleness:
            self._m_async_rej.inc()
            conn.send({"type": "grad_ack", "tid": tid, "rejected": "stale",
                       "staleness": staleness,
                       "version": self.engine.version,
                       "max_staleness": self.max_staleness})
            return
        self._m_staleness.observe(float(max(staleness, 0)))

        def done(out: dict) -> None:
            if "error" in out:
                conn.send({"type": "error", "op": "send_grad", "tid": tid,
                           "error": out["error"]})
            else:
                conn.send({"type": "grad_ack", "tid": tid,
                           "version": out["version"],
                           "staleness": staleness,
                           "timing": out.get("timing")})

        self._jobs.put(("async", tid, samples, blocks, done,
                        [trace["trace_id"]] if trace else None))

    def _handle_barrier(self, conn: FrameConn, msg: dict) -> None:
        if not self.is_coordinator:
            if msg.get("kind") == "pass":
                # the pass-boundary RELAY: trainers forward the
                # coordinator's finish_pass to every shard (like window
                # commit sets ride get_params) so pass-dependent LR
                # schedules and snapshot pass labels stay in lockstep
                # fleet-wide
                self._handle_pass_relay(conn, msg)
                return
            conn.send({"type": "error", "op": "barrier",
                       "error": f"shard {self.shard_index} is not the "
                                f"membership coordinator — barrier at "
                                f"shard 0"})
            return
        tid = str(msg.get("tid"))
        if self.membership.get(tid) is None:
            conn.send({"type": "error", "op": "barrier", "tid": tid,
                       "error": f"trainer {tid!r} is not a member — "
                                f"rejoin with ps_join"})
            return
        if msg.get("kind") == "pass":
            # both modes synchronize pass boundaries (the LR pass
            # schedule and finish_pass bookkeeping live server-side)
            self._pass_waiters[tid] = (conn, time.monotonic())
            tr = wire.get_trace(msg)
            if tr:
                self._pass_traces[tid] = tr["trace_id"]
        elif self.mode == "async":
            conn.send({"type": "error", "op": "barrier",
                       "error": "async mode has no batch barrier — "
                                "send_grad applies immediately"})
            return
        else:
            w = int(msg.get("window", -1))
            if w != self._next_window:
                conn.send({"type": "error", "op": "barrier", "tid": tid,
                           "error": f"window {w} is stale (fleet at "
                                    f"{self._next_window}) — rejoin and "
                                    f"pull fresh parameters"})
                return
            self._barriers[tid] = (conn, time.monotonic())
        self._maybe_commit()

    def _handle_pass_relay(self, conn: FrameConn, msg: dict) -> None:
        """Non-coordinator pass boundary (idempotent: a pass_id already
        reached replies immediately, concurrent relays share one job)."""
        if self.engine is None:
            conn.send({"type": "error", "op": "barrier",
                       "error": "server not initialized — ps_init first"})
            return
        target = int(msg.get("pass_id", 0))
        if self.engine.pass_id >= target:
            conn.send({"type": "barrier", "kind": "pass",
                       "pass_id": self.engine.pass_id,
                       "window": self._next_window})
            return
        if self.engine.pass_id != target - 1:
            conn.send({"type": "error", "op": "barrier",
                       "error": f"pass relay for {target} but this shard "
                                f"is at pass {self.engine.pass_id} — a "
                                f"boundary was skipped (restarted "
                                f"shard?)"})
            return
        self._pass_relay_waiters.append(conn)
        tr = wire.get_trace(msg)
        if tr:
            self._pass_relay_traces.append(tr["trace_id"])
        if self._pass_relaying:
            return
        self._pass_relaying = True

        def done(out: dict) -> None:
            self._pass_relaying = False
            # waiters AND traces swap together here (not at enqueue): a
            # relay arriving while the job is in flight is answered by
            # THIS done, so its boundary trace_id must ride this pass's
            # commit span, not the next one's
            waiters, self._pass_relay_waiters = \
                self._pass_relay_waiters, []
            self._pass_relay_traces = []
            for c in waiters:
                if "error" in out:
                    c.send({"type": "error", "op": "barrier",
                            "error": f"finish_pass failed: "
                                     f"{out['error']}"})
                else:
                    c.send({"type": "barrier", "kind": "pass",
                            "pass_id": out["pass_id"],
                            "window": self._next_window})

        self._jobs.put(("pass", done, self._pass_relay_traces))

    def _handle_get_params(self, conn: FrameConn, msg: dict) -> None:
        if self.engine is None:
            conn.send({"type": "error", "op": "get_params",
                       "error": "server not initialized — ps_init first"})
            return
        apply = msg.get("apply")
        if apply is not None and not self.is_coordinator:
            w = int(apply["window"])
            if w > self._next_window:
                conn.send({"type": "error", "op": "get_params",
                           "error": f"apply for future window {w} (shard "
                                    f"at {self._next_window}) — windows "
                                    f"commit in order"})
                return
            if w == self._next_window:
                self._apply_waiters.setdefault(w, []).append((conn, msg))
                self._maybe_apply_shard(w)
                return
            # w < next: already applied; fall through to a plain read
        minv = msg.get("min_version")
        if minv is not None and not self.is_coordinator and \
                self.engine.version < int(minv):
            # a joiner pulling between a coordinator commit and the
            # commit-set relay would read a parameter state that never
            # existed fleet-wide — park until this shard catches up
            self._minv_waiters.append((int(minv), conn, msg))
            return
        if self.is_coordinator and self._committing:
            # reads during a commit would hand a joiner pre-commit
            # parameters for a post-commit window
            self._after_commit.append(
                lambda c=conn, m=msg: self._handle_get_params(c, m))
            return
        self._reply_params(conn, msg)

    def _reply_params(self, conn: FrameConn, msg: dict,
                      timing: Optional[dict] = None) -> None:
        want = msg.get("want", "params")
        binary = bool(msg.get("bin"))
        reply = {"type": "params", "id": msg.get("id"), "want": want,
                 "version": self.engine.version,
                 "window": self._next_window,
                 "pass_id": self.engine.pass_id,
                 "bin": binary}
        if timing is not None:
            # the window reply a commit-set relay triggered carries this
            # shard's apply breakdown (accum/apply/total ms)
            reply["timing"] = timing
        if binary:
            # the client asked for the raw-bytes reply (it saw the
            # bin_blocks capability in our hello): block meta rides in
            # the header, the concatenated bytes behind it
            meta, payload = self.engine.wire_blocks_bin(want)
            reply["blocks"] = meta
            conn.send_bin(reply, payload)
        else:
            reply["blocks"] = self.engine.wire_blocks(want)
            conn.send(reply)

    # -- ops frames ----------------------------------------------------------
    def _stats_msg(self) -> dict:
        counts = self.membership.counts()
        return {
            "type": "stats", "role": "pserver",
            "shard": self.shard_index, "n_shards": self.n_shards,
            "mode": self.mode,
            "initialized": self.engine is not None,
            "version": self.engine.version if self.engine else 0,
            "window": self._next_window,
            "pass_id": self.engine.pass_id if self.engine else 0,
            "trainers_active": counts[mem.ACTIVE],
            "trainers_draining": counts[mem.DRAINING],
            "trainers": self.membership.summary(),
            "pending_grads": len(self._contrib) + sum(
                len(v) for v in self._shard_contrib.values()),
            "pending_barriers": len(self._barriers),
            "pending_pass_barriers": len(self._pass_waiters),
            "blocks": len(self.engine.refs) if self.engine else 0,
            "block_bytes": self.engine.block_bytes() if self.engine else 0,
            "update_alive": self.update_alive(),
            "update_error": self._update_error,
            "update_lag_s": round(self.update_lag(), 3),
            "wedge_threshold_s": self.wedge_threshold_s,
            "straggler_ms": self.straggler_ms,
            "last_skew_ms": round(self.last_skew_ms, 3),
            "draining": self._draining,
            "snapshot": {
                "dir": self.snapshot_dir,
                "every": self.snapshot_every,
                "in_progress": self.snapshot_in_progress,
                "written": self.snapshots_written,
                "last_path": self.last_snapshot_path,
                "last_seconds": round(self.last_snapshot_seconds, 4),
            },
            "uptime_s": round(time.monotonic() - self._started_t, 3),
        }

    def _slo_dump(self, fired: list) -> None:
        """One proactive bundle per SLO episode (obs/slo.py calls this
        on the sampler thread at the firing transition) — gated on the
        snapshot dir like every other pserver dump."""
        if not self.snapshot_dir:
            return
        names = ",".join(sorted({str(f.get("slo", "?")) for f in fired}))
        try:
            self.flight.dump(
                self.snapshot_dir, reason=f"slo:{names}",
                spans=self.tracer.snapshot(),
                engine=self._stats_msg(),
                metrics=self.metrics.snapshot(),
                config=self._config_snapshot(),
                history=self.history.snapshot(),
                error=f"slo firing: {names}")
        except OSError as e:
            print(f"pserver: slo dump failed: {e}",
                  file=sys.stderr, flush=True)

    def _handle_dump(self, conn: FrameConn, msg: dict) -> None:
        self.flight.record("dump_rpc", id=str(msg.get("id")))
        if not self.snapshot_dir:
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": "no snapshot/postmortem directory "
                                "configured — start the server with "
                                "snapshot_dir= (tools/pserver.py "
                                "--snapshot-dir)"})
            return
        try:
            path = self.flight.dump(
                self.snapshot_dir, reason="dump_rpc",
                spans=self.tracer.snapshot(),
                engine=self._stats_msg(),
                metrics=self.metrics.snapshot(),
                config=self._config_snapshot(),
                history=self.history.snapshot())
        except OSError as e:
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": f"dump failed: {e}"})
            return
        conn.send({"type": "dump", "id": msg.get("id"), "path": path,
                   "events": self.flight.recorded,
                   "spans": self.tracer.recorded})

    def _config_snapshot(self) -> dict:
        return {"shard": self.shard_index, "n_shards": self.n_shards,
                "mode": self.mode, "config_hash": self._config_hash,
                "wedge_threshold_s": self.wedge_threshold_s,
                "straggler_ms": self.straggler_ms}
