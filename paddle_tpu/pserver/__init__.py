"""Elastic parameter-server training tier.

The TPU-native rebuild of the reference's layers 5-6 (ref:
paddle/pserver/ParameterServer2.{h,cpp} addGradient/getParameter + block
maps :120-145, ParameterClient2, trainer/RemoteParameterUpdater.{h,cpp}),
re-expressed over the serving wire protocol (`serving/wire.py`) with the
PS-vs-graph placement lesson of the TensorFlow paper (arXiv:1605.08695):
the parameter + optimizer-state blocks live in a thin restartable server
tier, the heavy forward/backward math stays on the trainers' devices, and
the server's update math is the REPO'S OWN `optim/updater.py` applied at
block granularity — which is what makes the sync mode bit-exact against
a single-process `grad_accum=K` run.

    pserver/blocks.py      deterministic block map + array wire codec
    pserver/membership.py  elastic trainer membership state machine
    pserver/server.py      ParameterServer (asyncio) + UpdateEngine
    pserver/client.py      ParameterClient (blocking sockets, jax-free)

The trainer-side half is `optim/remote_updater.py`
(RemoteParameterUpdater — the third member of the reference's
local/thread/remote updater family) behind the same interface as the
local `ParameterUpdater`.  CLIs: `tools/pserver.py`, `tools/train_dist.py`.
Design doc: docs/distributed_training.md.
"""

from paddle_tpu.pserver.blocks import BlockMap, decode_array, encode_array
from paddle_tpu.pserver.membership import Membership, TrainerMember

__all__ = ["BlockMap", "Membership", "TrainerMember", "decode_array",
           "encode_array", "ParameterServer", "ParameterClient"]


def __getattr__(name):
    # ParameterServer pulls in jax (update math); ParameterClient is
    # deliberately jax-free — lazy both so `import paddle_tpu.pserver`
    # stays cheap for client-side tools
    if name == "ParameterServer":
        from paddle_tpu.pserver.server import ParameterServer
        return ParameterServer
    if name == "ParameterClient":
        from paddle_tpu.pserver.client import ParameterClient
        return ParameterClient
    raise AttributeError(name)
