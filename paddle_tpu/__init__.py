"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of 2016-era
PaddlePaddle (reference: /root/reference): a layer-graph model description
built by a Python DSL, compiled into a single XLA step function, trained by
SGD-family optimizers with data/model parallelism expressed as a
`jax.sharding.Mesh` + collectives instead of threads and parameter servers.

Layer map (bottom-up), mirroring the reference's layering (SURVEY.md §1):

  utils/      flags, logging, timers/stats          (ref: paddle/utils/)
  ops/        device op library on jnp + Pallas     (ref: paddle/cuda/ hl_*)
  parameter/  initializers, Argument batch struct   (ref: paddle/parameter/)
  graph/      layer registry + graph executor       (ref: paddle/gserver/)
  optim/      optimizer/LR-schedule/regularizer zoo (ref: paddle/parameter/*Optimizer*)
  parallel/   mesh, shardings, collectives          (ref: paddle/pserver/ + MultiGradientMachine)
  trainer/    train/test loops, checkpoint, eval    (ref: paddle/trainer/)
  config/     model/trainer config schema + parser  (ref: proto/, config_parser.py)
  dsl/        user-facing layer DSL                 (ref: trainer_config_helpers/)
  data/       data providers and feeders            (ref: gserver/dataproviders/)
  models/     model zoo                              (ref: demo/)
"""

__version__ = "0.1.0"

from paddle_tpu.config.schema import (  # noqa: F401
    LayerConfig,
    ModelConfig,
    OptimizationConfig,
    ParameterConfig,
    TrainerConfig,
)
