from paddle_tpu.utils.flags import FLAGS, define_flag, parse_flags  # noqa: F401
from paddle_tpu.utils.logger import get_logger  # noqa: F401
from paddle_tpu.utils.stat import StatSet, global_stat, timer  # noqa: F401
