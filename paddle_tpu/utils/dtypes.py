"""Mixed-precision dtype policy helpers (compute_dtype='bfloat16')."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LOW_PRECISION = (jnp.bfloat16, jnp.float16)


def promote_compute(x: jax.Array) -> jax.Array:
    """Promote low-precision compute dtypes to float32 for numerically
    sensitive ops (softmax/log/statistics/loss accumulation); float32 and
    float64 pass through unchanged."""
    if x.dtype in LOW_PRECISION:
        return x.astype(jnp.float32)
    return x
