"""Mixed-precision dtype policy helpers (compute_dtype='bfloat16')."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LOW_PRECISION = (jnp.bfloat16, jnp.float16)


def promote_compute(x: jax.Array) -> jax.Array:
    """Promote low-precision compute dtypes to float32 for numerically
    sensitive ops (softmax/log/statistics/loss accumulation); float32 and
    float64 pass through unchanged."""
    if x.dtype in LOW_PRECISION:
        return x.astype(jnp.float32)
    return x


def sublane_min(*arrays) -> int:
    """Minimum TPU sublane tile for the widest-constrained array dtype:
    2-byte floats (bf16/fp16) need (16, 128) tiles, 4-byte (8, 128).
    Pallas kernels round their second-minor block dims with this."""
    return 16 if any(a.dtype in LOW_PRECISION for a in arrays) else 8
