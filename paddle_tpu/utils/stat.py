"""Timers and stat accumulation.

TPU-native analog of the reference's REGISTER_TIMER / StatSet machinery
(ref: paddle/utils/Stat.h:130-256): named accumulating timers that the trainer
prints and resets every log_period.  On TPU the hot path is one compiled XLA
call, so timers wrap host-side phases (data feed, step dispatch, eval) and the
jax profiler covers device-side detail.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field


#: bounded per-stat sample window for percentile queries — old samples are
#: overwritten ring-buffer style, so a long-lived server's stats RPC reports
#: RECENT latency percentiles at O(1) memory per stat
SAMPLE_WINDOW = 4096


def _quantile(snap: list, q: float) -> float:
    """Linear-interpolated quantile of an already-SORTED list (numpy
    percentile semantics); 0.0 when empty."""
    if not snap:
        return 0.0
    pos = (len(snap) - 1) * min(max(q, 0.0), 100.0) / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(snap) - 1)
    return snap[lo] + (snap[hi] - snap[lo]) * (pos - lo)


@dataclass
class Stat:
    name: str
    total_s: float = 0.0
    count: int = 0
    max_s: float = 0.0
    samples: list = field(default_factory=list)   # last SAMPLE_WINDOW dts
    # add() runs on the owning hot thread (serving pump, trainer loop)
    # while percentiles()/snapshots run on others (the asyncio stats
    # thread, the metrics render) — the lock makes the multi-field update
    # and the window copy atomic, instead of relying on GIL interleaving
    # (a ring overwrite racing a sort could pair count with a half-updated
    # window).  Uncontended acquire is ~100ns; these record host phases
    # measured in microseconds to milliseconds.
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False, compare=False)

    def add(self, dt: float) -> None:
        with self.lock:
            if len(self.samples) < SAMPLE_WINDOW:
                self.samples.append(dt)
            else:
                self.samples[self.count % SAMPLE_WINDOW] = dt
            self.total_s += dt
            self.count += 1
            if dt > self.max_s:
                self.max_s = dt

    def reset(self) -> None:
        with self.lock:
            self.total_s = 0.0
            self.count = 0
            self.max_s = 0.0
            self.samples = []

    def window(self) -> list:
        """Consistent copy of the sample window."""
        with self.lock:
            return list(self.samples)

    def __str__(self) -> str:
        avg = self.total_s / max(self.count, 1)
        return (f"{self.name}: total={self.total_s * 1e3:.1f}ms "
                f"count={self.count} avg={avg * 1e3:.3f}ms max={self.max_s * 1e3:.3f}ms")


@dataclass
class StatSet:
    """Named stat registry (ref: StatSet globalStat, Stat.h:94-128)."""

    name: str = "global"
    stats: dict[str, Stat] = field(default_factory=dict)
    # guards stat CREATION only — two threads get()ing a new name must not
    # both insert (the loser's Stat, and any samples it took, would vanish)
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False, compare=False)

    def get(self, name: str) -> Stat:
        s = self.stats.get(name)
        if s is None:
            with self.lock:
                s = self.stats.setdefault(name, Stat(name))
        return s

    @contextlib.contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.get(name).add(time.perf_counter() - t0)

    def percentiles(self, name: str, qs=(50.0, 99.0)) -> dict[str, float]:
        """{"p50": ..., "p99": ...} in SECONDS for stat `name` (0.0s when
        the stat never recorded) — the serving stats RPC's building block.
        Copies the window under the stat's lock (add() runs on another
        thread — the serving pump), then sorts ONCE for all requested
        quantiles."""
        s = self.stats.get(name)
        snap = sorted(s.window()) if s else []
        return {f"p{q:g}": _quantile(snap, q) for q in qs}

    def print_all(self, log=None) -> str:
        lines = ["======= StatSet: [%s] =======" % self.name]
        for s in sorted(self.stats.values(), key=lambda s: -s.total_s):
            lines.append("  " + str(s))
        text = "\n".join(lines)
        if log is not None:
            log.info(text)
        return text

    def reset(self) -> None:
        for s in self.stats.values():
            s.reset()


global_stat = StatSet()


def timer(name: str):
    """``with timer("forwardBackward"): ...`` accumulates into global_stat."""
    return global_stat.time(name)
