"""Global flag system.

TPU-native analog of the reference's command-line flag tier
(ref: paddle/utils/Flags.{h,cpp}, CommandLineParser.{h,cpp}): a process-global
registry of typed flags with defaults, overridable from argv or
programmatically.  Unlike the reference there is no gflags dependency — a thin
argparse-free implementation keeps startup cheap and embeddable.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Any


@dataclass
class _FlagSpec:
    name: str
    default: Any
    type: type
    help: str


class _Flags:
    """Attribute-style access to registered flags: ``FLAGS.use_tpu``."""

    def __init__(self) -> None:
        object.__setattr__(self, "_specs", {})
        object.__setattr__(self, "_values", {})

    def define(self, name: str, default: Any, help: str = "") -> None:
        specs = object.__getattribute__(self, "_specs")
        if name in specs:  # re-definition keeps first registration (idempotent imports)
            return
        specs[name] = _FlagSpec(name, default, type(default) if default is not None else str, help)
        object.__getattribute__(self, "_values")[name] = default

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"undefined flag: {name}")

    def __setattr__(self, name: str, value: Any) -> None:
        values = object.__getattribute__(self, "_values")
        if name not in values:
            raise AttributeError(f"undefined flag: {name}; use define_flag first")
        values[name] = value

    def as_dict(self) -> dict[str, Any]:
        return dict(object.__getattribute__(self, "_values"))

    def parse(self, argv: list[str] | None = None) -> list[str]:
        """Consume ``--name=value`` / ``--name value`` pairs; returns leftovers."""
        specs = object.__getattribute__(self, "_specs")
        values = object.__getattribute__(self, "_values")
        if argv is None:
            argv = sys.argv[1:]
        rest: list[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("--"):
                rest.append(arg)
                i += 1
                continue
            body = arg[2:]
            if "=" in body:
                name, raw = body.split("=", 1)
            else:
                name = body
                nxt = argv[i + 1] if i + 1 < len(argv) else None
                # a bare flag never consumes a following flag token — values
                # that genuinely start with '--' need the --name=value form
                if nxt is not None and name in specs and \
                        not nxt.startswith("--"):
                    raw = nxt
                    i += 1
                else:
                    raw = "true"
            if name not in specs:
                rest.append(arg)
                i += 1
                continue
            spec = specs[name]
            if spec.type is bool:
                values[name] = raw.lower() in ("1", "true", "yes", "on")
            elif spec.default is None:
                values[name] = raw
            else:
                values[name] = spec.type(raw)
            i += 1
        return rest


FLAGS = _Flags()


def define_flag(name: str, default: Any, help: str = "") -> None:
    FLAGS.define(name, default, help)


def parse_flags(argv: list[str] | None = None) -> list[str]:
    return FLAGS.parse(argv)


# Core global flags (ref: paddle/utils/Flags.cpp:19-68 — use_gpu, trainer_count,
# log_period, saving_period, ... re-expressed for the TPU runtime).
define_flag("use_tpu", True, "run compute on TPU devices when available")
define_flag("seed", 1, "global RNG seed (0 = nondeterministic)")
define_flag("log_period", 100, "log training stats every N batches")
define_flag("dot_period", 1, "progress dot every N batches")
define_flag("saving_period", 1, "checkpoint every N passes")
define_flag("test_period", 0, "test every N batches (0 = every pass)")
define_flag("num_passes", 1, "number of training passes")
define_flag("start_pass", 0, "resume from pass N")
define_flag("save_dir", "./output", "checkpoint directory")
define_flag("init_model_path", "", "path to initial model checkpoint")
define_flag("config", "", "trainer config python file")
define_flag("config_args", "", "comma-separated key=value passed to the config")
define_flag("job", "train", "train | test | checkgrad | time")
define_flag("checkgrad_bar", 0.02, "max relative error --job=checkgrad "
            "accepts before failing (exit 1)")
define_flag("show_parameter_stats_period", 0, "dump parameter stats every N batches")
define_flag("beam_size", 1, "beam width for sequence generation")
define_flag("mesh_shape", "", "device mesh, e.g. 'data:8' or 'data:4,model:2'")
define_flag("profile_dir", "", "if set, write jax profiler traces here")
define_flag("compute_dtype", "", "override compute dtype ('bfloat16' = "
            "mixed precision: fp32 params, bf16 matmuls on the MXU)")
define_flag("detect_nan", False, "trap FP anomalies (jax_debug_nans; "
            "ref: feenableexcept at TrainerMain.cpp:97)")
define_flag("nonfinite_check_period", 100, "without --detect_nan, losses "
            "buffer on device and are bulk-checked every N batches (keeps "
            "dispatch pipelined — no per-batch host sync)")
define_flag("steps_per_dispatch", 1, "fuse k consecutive same-shape train "
            "steps into ONE compiled lax.scan dispatch (k>1 amortizes "
            "per-step Python dispatch overhead k-fold and overlaps the "
            "next group's host->device staging with the current scan; "
            "batches group by their padded-shape signature and a group "
            "flushes early when the shape changes, so the update order — "
            "and the training trajectory — is identical to k=1)")
define_flag("prev_batch_state", False, "truncated-BPTT continuation: "
            "forward recurrent layers start from the previous batch's final "
            "hidden state instead of zeros (ref: RecurrentLayer.cpp "
            "prevOutput_; feed consecutive chunks of long streams in order)")
define_flag("check_sparse_distribution", False,
            "check vocab-sharded table ids for balanced per-shard traffic "
            "(ref: --check_sparse_distribution_in_pserver)")
define_flag("show_check_sparse_distribution_log", False,
            "log per-shard row-touch counts for every probed batch")
define_flag("check_sparse_distribution_batches", 100,
            "run the sparse distribution check for N batches, then stop")
define_flag("check_sparse_distribution_ratio", 0.6,
            "crash if more than this fraction of checked batches is unbalanced")
define_flag("check_sparse_distribution_unbalance_degree", 2.0,
            "max/mean row-touch ratio beyond which a batch counts unbalanced")
# multi-host bootstrap (ref: --trainer_id/--pservers of the pserver fleet)
define_flag("coordinator_address", "", "jax.distributed coordinator host:port")
define_flag("num_processes", 0, "number of cluster processes")
define_flag("process_id", 0, "this process's id in the cluster")


def env_flag(name: str, default: str = "") -> str:
    return os.environ.get(name, default)
