"""Logging facade (ref: paddle/utils/Logging.{h,cpp} — glog-or-builtin clone).

One process-wide logger with a glog-style format.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(levelname).1s %(asctime)s.%(msecs)03d %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt=_DATEFMT))
    root = logging.getLogger("paddle_tpu")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    _configure()
    if name != "paddle_tpu" and not name.startswith("paddle_tpu."):
        name = f"paddle_tpu.{name}"
    return logging.getLogger(name)
