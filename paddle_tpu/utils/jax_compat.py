"""Portability shims for the jax API surface this repo targets.

The codebase is written against current jax — `jax.shard_map` with its
`check_vma` flag, `jax.enable_x64` — while older installs (0.4.x) expose
the same functionality under `jax.experimental` with earlier names
(`shard_map`'s replication check is `check_rep`; `enable_x64` lives in
`jax.experimental`).  Importing from here instead of `jax` directly keeps
every mesh/precision path runnable on both, so the tier-1 suite exercises
the same code the TPU build runs.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """`jax.shard_map`, falling back to `jax.experimental.shard_map` with
    `check_vma` renamed to its pre-rename spelling `check_rep` (same
    semantics: False opts out of the replication/varying-axes check for
    bodies — pallas calls, hand-rolled ppermute rings — the checker cannot
    type)."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def enable_x64():
    """`jax.enable_x64()` context manager (f64 checkgrad/test paths),
    falling back to `jax.experimental.enable_x64`."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64()
    from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64()


def axis_size(axis_name) -> int:
    """`lax.axis_size(name)` inside a shard_map/pmap body; older jax spells
    it `psum(1, name)` (constant-folded to a static int)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pallas_tpu_compiler_params(**kw):
    """`pltpu.CompilerParams` (renamed from `TPUCompilerParams`)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)
