"""Checkpoint save/load.

Matches the reference's checkpoint layout and behavior (ref:
paddle/trainer/ParamUtil.{h,cpp}: per-pass dirs `pass-%05d`,
saveParametersOnePass, deleteOldest; parameter/Parameter.cpp save/load header)
re-expressed for a param pytree: each pass directory holds one `model.npz`
with the flattened parameter/optimizer/net-state trees plus the serialized
TrainerConfig, so a checkpoint is a self-contained deployable bundle (also
subsuming paddle_merge_model — ref: trainer/MergeModel.cpp).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import struct
import zipfile
from typing import Any, Optional

import jax
import numpy as np

SEP = "|"  # path separator inside npz keys

# -- reference v0.9.0 binary Parameter format --------------------------------
# Header {int32 version; uint32 valueSize; uint64 size} followed by
# size*valueSize raw little-endian reals (ref: parameter/Parameter.h:300-306
# kFormatVersion=0, Parameter.cpp:309-381 save/load); a pass-%05d dir holds
# one such file per parameter, named by the parameter.
_REF_HEADER = struct.Struct("<iIQ")


def read_reference_parameter(path: str) -> np.ndarray:
    """Read one reference-format parameter file -> flat float array."""
    with open(path, "rb") as f:
        raw = f.read(_REF_HEADER.size)
        if len(raw) < _REF_HEADER.size:
            raise ValueError(f"{path}: too short for a parameter header")
        version, value_size, size = _REF_HEADER.unpack(raw)
        if version != 0:
            raise ValueError(f"{path}: unsupported format version {version}")
        if value_size not in (4, 8):
            raise ValueError(f"{path}: unsupported valueSize {value_size}")
        dtype = np.float32 if value_size == 4 else np.float64
        data = np.frombuffer(f.read(size * value_size), dtype=dtype)
        if data.size != size:
            raise ValueError(
                f"{path}: header promises {size} values, file has {data.size}")
    return data


def write_reference_parameter(path: str, arr: np.ndarray) -> None:
    """Write a flat array in the reference binary format (export /
    test-synthesis counterpart of read_reference_parameter)."""
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    with open(path, "wb") as f:
        f.write(_REF_HEADER.pack(0, 4, flat.size))
        f.write(flat.tobytes())


def _is_reference_parameter_file(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            raw = f.read(_REF_HEADER.size)
        if len(raw) < _REF_HEADER.size:
            return False
        version, value_size, size = _REF_HEADER.unpack(raw)
    except OSError:
        return False
    return (version == 0 and value_size in (4, 8)
            and os.path.getsize(path) == _REF_HEADER.size + size * value_size)


def load_reference_pass_dir(d: str) -> dict[str, np.ndarray]:
    """Import a reference pass-%05d directory: every well-formed parameter
    file, keyed by file name (= parameter name).  Arrays come back FLAT —
    the caller reshapes against its model's parameter dims."""
    out: dict[str, np.ndarray] = {}
    for name in sorted(os.listdir(d)):
        p = os.path.join(d, name)
        if os.path.isfile(p) and _is_reference_parameter_file(p):
            out[name] = read_reference_parameter(p)
    if not out:
        raise ValueError(
            f"{d}: no reference-format parameter files found")
    return out


def _flatten(tree: Any, prefix: str) -> dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = prefix + SEP + SEP.join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_dicts(flat: dict[str, np.ndarray]) -> dict:
    """Rebuild nested dicts from SEP-joined keys (trees here are nested
    dicts).  Keys are inserted in SORTED order regardless of the writer's
    npz ordering, so two checkpoints of the same state load into
    identically-ordered trees no matter who wrote them — the trainer's
    save(), or the pserver's streaming snapshotter assembling blocks —
    and a loaded optimizer tree's slot iteration order is deterministic
    (jax pytrees sort dict keys, but plain-dict consumers like
    _merge_state and test assertions must not depend on writer
    insertion order either)."""
    root: dict = {}
    for key in sorted(flat):
        parts = key.split(SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = flat[key]
    return root


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync (makes renames/creates durable on
    filesystems with delayed allocation); a filesystem that cannot fsync
    a directory fd is not a reason to fail the save."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def pass_dir(save_dir: str, pass_id: int) -> str:
    """pass_id < 0 = a snapshot taken BEFORE the first pass completed: it
    gets its own label so it can never collide with (or shadow) the real
    end-of-pass-0 `pass-00000` snapshot, and resuming from it does not skip
    training pass 0."""
    if pass_id < 0:
        return os.path.join(save_dir, "pass-init")
    return os.path.join(save_dir, f"pass-{pass_id:05d}")


def save_checkpoint(
    save_dir: str,
    pass_id: int,
    params: dict,
    opt_state: Optional[dict] = None,
    net_state: Optional[dict] = None,
    config_json: Optional[str] = None,
    keep_last: int = 0,
    rng=None,
) -> str:
    """Write pass-%05d/{model.npz, trainer_config.json}
    (ref: ParamUtil::saveParametersOnePass).  `rng` is the trainer's
    PRNG key: persisting it makes resume EXACT for stochastic models
    too (dropout streams continue where the uninterrupted run would).

    ATOMIC: the whole pass dir is staged under `<dir>.tmp` and renamed
    into place as the last step, and model.npz itself is os.replace'd
    from a temp name inside the staging dir — a crash at ANY point leaves
    either a committed checkpoint or `.tmp` stragglers that every reader
    (load_checkpoint, latest_pass, latest_checkpoint, keep_last pruning)
    ignores; never a loadable-looking truncated npz.  Re-saving an
    EXISTING pass moves the committed dir aside (`.old.tmp`) rather than
    deleting it pre-commit, so even that path never destroys data it has
    not yet replaced (worst case after a crash between the two renames:
    the pass is absent but both its old and new contents sit complete
    under `.tmp` names).  keep_last pruning runs only after the rename
    commits."""
    d = pass_dir(save_dir, pass_id)
    tmp_d = d + ".tmp"
    if os.path.isdir(tmp_d):
        shutil.rmtree(tmp_d)                 # stale straggler from a crash
    os.makedirs(tmp_d)
    flat = _flatten(params, "params")
    if opt_state is not None:
        flat.update(_flatten(opt_state, "opt"))
    if net_state is not None:
        flat.update(_flatten(net_state, "net"))
    if rng is not None:
        flat["rng"] = np.asarray(rng)
    tmp_npz = os.path.join(tmp_d, "model.npz.part")
    with open(tmp_npz, "wb") as f:           # file handle: np.savez would
        np.savez(f, **flat)                  # append .npz to a str path
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_npz, os.path.join(tmp_d, "model.npz"))
    if config_json is not None:
        with open(os.path.join(tmp_d, "trainer_config.json"), "w") as f:
            f.write(config_json)
            f.flush()
            os.fsync(f.fileno())             # same durability as model.npz:
            # the commit rename below must never reach disk ahead of this
            # file's data (delayed allocation would leave a COMMITTED dir
            # with a torn config)
    old_d = d + ".old.tmp"
    if os.path.isdir(old_d):
        shutil.rmtree(old_d)                 # straggler from an old crash
    if os.path.isdir(d):
        # re-saving the same pass: POSIX cannot atomically swap two dirs,
        # so move the committed one ASIDE (not rmtree — deleting it before
        # the commit rename would open a crash window where the pass is
        # simply gone) and drop it only after the new dir is in place
        os.replace(d, old_d)
    _fsync_dir(tmp_d)                        # staged entries durable first
    os.replace(tmp_d, d)                     # THE commit point
    _fsync_dir(save_dir)                     # ...then the rename itself
    shutil.rmtree(old_d, ignore_errors=True)
    if keep_last > 0:
        _delete_old(save_dir, keep_last)
    return d


def _delete_old(save_dir: str, keep_last: int) -> None:
    """(ref: ParamUtil::deleteParameters keeps save_only_one / latest).
    The pre-training pass-init snapshot counts as the oldest."""
    for x in os.listdir(save_dir):
        # crashed-save stragglers from OTHER passes (a pass that is never
        # re-saved never triggers the same-pass cleanup) would otherwise
        # hold a full checkpoint's disk forever while committed ones are
        # being pruned to save space.  Runs post-commit: the current
        # save's staging dirs are already renamed/removed.
        if re.match(r"pass-(\d{5}|init)(\.old)?\.tmp$", x):
            shutil.rmtree(os.path.join(save_dir, x), ignore_errors=True)
    dirs = sorted(
        (m.group(0) for m in (re.match(r"pass-\d{5}$", x) for x in os.listdir(save_dir)) if m))
    if os.path.isdir(os.path.join(save_dir, "pass-init")):
        dirs.insert(0, "pass-init")
    for old in dirs[:-keep_last]:
        shutil.rmtree(os.path.join(save_dir, old), ignore_errors=True)


def load_checkpoint(path: str) -> dict[str, Any]:
    """Load a checkpoint dir (or its model.npz); returns
    {'params': ..., 'opt': ..., 'net': ..., 'config_json': ...}."""
    if path.endswith(".npz"):
        npz = path
    else:
        npz = os.path.join(path, "model.npz")
        if not os.path.exists(npz):
            lp = latest_pass(path)
            if lp >= 0:
                # given the save_dir root, resume from its newest pass
                # (ref: ParamUtil --start_pass resume semantics)
                cand = os.path.join(path, f"pass-{lp:05d}")
                npz = os.path.join(cand, "model.npz")
                if not os.path.exists(npz):
                    # a reference-produced save_dir: its pass dirs hold raw
                    # binary parameter files instead of model.npz
                    return {"params": load_reference_pass_dir(cand),
                            "reference_format": True, "pass_id": lp}
            elif os.path.exists(os.path.join(path, "pass-init", "model.npz")):
                # only a pre-training snapshot exists: resume from it
                npz = os.path.join(path, "pass-init", "model.npz")
            elif os.path.isdir(path) and any(
                    _is_reference_parameter_file(os.path.join(path, x))
                    for x in os.listdir(path)):
                # a reference v0.9.0 pass directory given directly
                out: dict[str, Any] = {"params": load_reference_pass_dir(path),
                                       "reference_format": True}
                m = re.match(r"pass-(\d{5})$", os.path.basename(path))
                if m:
                    out["pass_id"] = int(m.group(1))
                return out
    try:
        data = np.load(npz, allow_pickle=False)
        flat = {k: data[k] for k in data.files}
    except (zipfile.BadZipFile, EOFError, ValueError) as e:
        # a truncated npz (crash mid-save before this module staged writes
        # atomically, or a torn copy) surfaces as a raw BadZipFile with no
        # hint WHICH file — name the path and the way out
        raise ValueError(
            f"checkpoint {npz} is corrupt or truncated ({e}); it cannot "
            f"be loaded — delete its pass directory and resume from the "
            f"newest committed one (trainer.checkpoint.latest_checkpoint)"
        ) from e
    trees: dict[str, dict] = {"params": {}, "opt": {}, "net": {}}
    for prefix in trees:
        sub = {k[len(prefix) + 1:]: v for k, v in flat.items()
               if k.startswith(prefix + SEP)}
        trees[prefix] = _unflatten_dicts(sub)
    out: dict[str, Any] = dict(trees)
    if "rng" in flat:
        out["rng"] = flat["rng"]
    base = os.path.basename(os.path.dirname(npz))
    m = re.match(r"pass-(\d{5})$", base)
    if m:
        # which pass this snapshot belongs to, so a resumed Trainer can
        # continue the numbering instead of re-saving from pass-00000
        out["pass_id"] = int(m.group(1))
    elif base == "pass-init":
        # pre-training snapshot: the resumed run starts at pass 0
        out["pass_id"] = -1
    cfg_path = os.path.join(os.path.dirname(npz), "trainer_config.json")
    if os.path.exists(cfg_path):
        out["config_json"] = open(cfg_path).read()
    return out


def latest_pass(save_dir: str) -> int:
    """Highest pass id present, or -1."""
    if not os.path.isdir(save_dir):
        return -1
    best = -1
    for x in os.listdir(save_dir):
        m = re.match(r"pass-(\d{5})$", x)
        if m:
            best = max(best, int(m.group(1)))
    return best


def latest_checkpoint(save_dir: str) -> Optional[str]:
    """Path of the newest COMMITTED pass dir under `save_dir` (falling
    back to `pass-init`), or None.  `.tmp` stragglers from a crashed
    save_checkpoint never match — only dirs whose final rename committed
    are candidates, so this is the safe resume/serve entry point
    (tools/serve.py --checkpoint uses it)."""
    lp = latest_pass(save_dir)
    if lp >= 0:
        return pass_dir(save_dir, lp)
    init = os.path.join(save_dir, "pass-init")
    if os.path.isdir(init):
        return init
    return None
