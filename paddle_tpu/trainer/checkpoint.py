"""Checkpoint save/load.

Matches the reference's checkpoint layout and behavior (ref:
paddle/trainer/ParamUtil.{h,cpp}: per-pass dirs `pass-%05d`,
saveParametersOnePass, deleteOldest; parameter/Parameter.cpp save/load header)
re-expressed for a param pytree: each pass directory holds one `model.npz`
with the flattened parameter/optimizer/net-state trees plus the serialized
TrainerConfig, so a checkpoint is a self-contained deployable bundle (also
subsuming paddle_merge_model — ref: trainer/MergeModel.cpp).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Optional

import jax
import numpy as np

SEP = "|"  # path separator inside npz keys


def _flatten(tree: Any, prefix: str) -> dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = prefix + SEP + SEP.join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_dicts(flat: dict[str, np.ndarray]) -> dict:
    """Rebuild nested dicts from SEP-joined keys (trees here are nested dicts)."""
    root: dict = {}
    for key, arr in flat.items():
        parts = key.split(SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def pass_dir(save_dir: str, pass_id: int) -> str:
    """pass_id < 0 = a snapshot taken BEFORE the first pass completed: it
    gets its own label so it can never collide with (or shadow) the real
    end-of-pass-0 `pass-00000` snapshot, and resuming from it does not skip
    training pass 0."""
    if pass_id < 0:
        return os.path.join(save_dir, "pass-init")
    return os.path.join(save_dir, f"pass-{pass_id:05d}")


def save_checkpoint(
    save_dir: str,
    pass_id: int,
    params: dict,
    opt_state: Optional[dict] = None,
    net_state: Optional[dict] = None,
    config_json: Optional[str] = None,
    keep_last: int = 0,
) -> str:
    """Write pass-%05d/{model.npz, trainer_config.json}
    (ref: ParamUtil::saveParametersOnePass)."""
    d = pass_dir(save_dir, pass_id)
    os.makedirs(d, exist_ok=True)
    flat = _flatten(params, "params")
    if opt_state is not None:
        flat.update(_flatten(opt_state, "opt"))
    if net_state is not None:
        flat.update(_flatten(net_state, "net"))
    np.savez(os.path.join(d, "model.npz"), **flat)
    if config_json is not None:
        with open(os.path.join(d, "trainer_config.json"), "w") as f:
            f.write(config_json)
    if keep_last > 0:
        _delete_old(save_dir, keep_last)
    return d


def _delete_old(save_dir: str, keep_last: int) -> None:
    """(ref: ParamUtil::deleteParameters keeps save_only_one / latest).
    The pre-training pass-init snapshot counts as the oldest."""
    dirs = sorted(
        (m.group(0) for m in (re.match(r"pass-\d{5}$", x) for x in os.listdir(save_dir)) if m))
    if os.path.isdir(os.path.join(save_dir, "pass-init")):
        dirs.insert(0, "pass-init")
    for old in dirs[:-keep_last]:
        shutil.rmtree(os.path.join(save_dir, old), ignore_errors=True)


def load_checkpoint(path: str) -> dict[str, Any]:
    """Load a checkpoint dir (or its model.npz); returns
    {'params': ..., 'opt': ..., 'net': ..., 'config_json': ...}."""
    if path.endswith(".npz"):
        npz = path
    else:
        npz = os.path.join(path, "model.npz")
        if not os.path.exists(npz):
            lp = latest_pass(path)
            if lp >= 0:
                # given the save_dir root, resume from its newest pass
                # (ref: ParamUtil --start_pass resume semantics)
                npz = os.path.join(path, f"pass-{lp:05d}", "model.npz")
            elif os.path.exists(os.path.join(path, "pass-init", "model.npz")):
                # only a pre-training snapshot exists: resume from it
                npz = os.path.join(path, "pass-init", "model.npz")
    data = np.load(npz, allow_pickle=False)
    flat = {k: data[k] for k in data.files}
    trees: dict[str, dict] = {"params": {}, "opt": {}, "net": {}}
    for prefix in trees:
        sub = {k[len(prefix) + 1:]: v for k, v in flat.items()
               if k.startswith(prefix + SEP)}
        trees[prefix] = _unflatten_dicts(sub)
    out: dict[str, Any] = dict(trees)
    base = os.path.basename(os.path.dirname(npz))
    m = re.match(r"pass-(\d{5})$", base)
    if m:
        # which pass this snapshot belongs to, so a resumed Trainer can
        # continue the numbering instead of re-saving from pass-00000
        out["pass_id"] = int(m.group(1))
    elif base == "pass-init":
        # pre-training snapshot: the resumed run starts at pass 0
        out["pass_id"] = -1
    cfg_path = os.path.join(os.path.dirname(npz), "trainer_config.json")
    if os.path.exists(cfg_path):
        out["config_json"] = open(cfg_path).read()
    return out


def latest_pass(save_dir: str) -> int:
    """Highest pass id present, or -1."""
    if not os.path.isdir(save_dir):
        return -1
    best = -1
    for x in os.listdir(save_dir):
        m = re.match(r"pass-(\d{5})$", x)
        if m:
            best = max(best, int(m.group(1)))
    return best
