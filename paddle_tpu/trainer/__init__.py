from paddle_tpu.trainer.trainer import Trainer  # noqa: F401
