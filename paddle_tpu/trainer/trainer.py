"""Trainer — the training driver.

TPU-native analog of the reference's trainer stack (ref: paddle/trainer/
Trainer.{h,cpp}: train/trainOnePass/trainOneDataBatch :264-520;
TrainerInternal.cpp trainOneBatch :65-173; Tester.{h,cpp}).

Re-design: the reference's per-batch choreography (startBatch → forward →
per-parameter update callbacks pipelined into backward → finishBatch) becomes
ONE jitted `train_step` = loss + grad + optimizer apply, compiled by XLA with
the same overlap the reference engineered by hand.  The pass loop, periodic
logging/eval/checkpointing and the --job=time benchmark mode mirror the
reference's driver behavior.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial
from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.schema import DataConfig, TrainerConfig
from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import TEST, TRAIN
from paddle_tpu.optim.updater import ParameterUpdater
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer.evaluators import EvaluatorSet
from paddle_tpu.utils import FLAGS, get_logger, global_stat

log = get_logger("trainer")


def load_provider(data_cfg: DataConfig, fresh: bool = False):
    """Instantiate a @provider from a DataConfig
    (ref: gserver/dataproviders/PyDataProvider2.cpp createPyDataProvider).

    fresh=True clones the module-level wrapper and its settings before
    initialize() — required when several sub-sources reference the same
    @provider object with different args (the init_hook mutates settings,
    which would otherwise be shared)."""
    import importlib

    mod = importlib.import_module(data_cfg.load_data_module)
    prov = getattr(mod, data_cfg.load_data_object)
    if fresh:
        import copy
        prov = copy.copy(prov)
        prov.settings = copy.deepcopy(prov.settings)
    files: list[str] = []
    if data_cfg.files:
        if os.path.exists(data_cfg.files):
            with open(data_cfg.files) as f:
                files = [ln.strip() for ln in f if ln.strip()]
        else:
            files = [data_cfg.files]
    kwargs = json.loads(data_cfg.load_data_args) if data_cfg.load_data_args else {}
    if not isinstance(kwargs, dict):
        kwargs = {"args": kwargs}
    prov.initialize(files, **kwargs)
    return prov, files


class Trainer:
    """Drives training/testing of one TrainerConfig
    (ref: Trainer.h:48; jobs train/test/time)."""

    def __init__(
        self,
        config: TrainerConfig,
        seed: int = 1,
        mesh: Optional[Any] = None,
        updater: Optional[Any] = None,
    ):
        """`updater` swaps the parameter-update strategy (ref: the
        local/thread/remote ParameterUpdater family): None builds the
        local fused-into-the-train-step ParameterUpdater; an
        optim.remote_updater.RemoteParameterUpdater (is_remote=True)
        makes the train step GRAD-ONLY and routes every batch through
        the parameter-server tier (paddle_tpu/pserver/)."""
        assert config.model_config is not None and config.opt_config is not None
        self.config = config
        self.model = config.model_config
        self.opt = config.opt_config
        cdt = FLAGS.compute_dtype or self.opt.compute_dtype
        from paddle_tpu.parallel.mesh import PIPE_AXIS, axis_size
        if mesh is not None and axis_size(mesh, PIPE_AXIS) > 1 \
                and any(l.device >= 0 for l in self.model.layers):
            # config-driven pipeline parallelism: device=N layer annotations
            # map onto pipe-axis stages (ref: ParallelNeuralNetwork.h:35-70)
            from paddle_tpu.parallel.pipeline_config import PipelineExecutor
            self.executor = PipelineExecutor(
                self.model, mesh,
                n_micro=self.opt.pipeline_micro_batches, compute_dtype=cdt,
                schedule=self.opt.pipeline_schedule or "gpipe",
                virtual_stages=self.opt.pipeline_virtual_stages or 1)
        else:
            self.executor = GraphExecutor(self.model, mesh=mesh,
                                          compute_dtype=cdt)
        self.updater = updater if updater is not None \
            else ParameterUpdater(self.model, self.opt)
        self._remote = bool(getattr(self.updater, "is_remote", False))
        self.evaluators = EvaluatorSet(self.model)
        # under pipeline parallelism stage-internal activations never
        # surface, so evaluators referencing them are skipped rather than
        # failing; the plain path keeps missing layers a loud error
        self.evaluators.allow_missing = not isinstance(self.executor,
                                                       GraphExecutor)
        self.seed = seed
        self.mesh = mesh
        self.rng = jax.random.PRNGKey(seed)

        self.params = self.executor.init_params(jax.random.PRNGKey(seed))
        # updater hooks (pruning masks) bind to the initial values
        # (ref: ParameterUpdaterHook.cpp StaticPruningHook::init)
        self.params = self.updater.apply_init_hooks(self.params)
        self.opt_state = self.updater.init_state(self.params)
        self.net_state: dict[str, Any] = {}
        self.pass_id = 0
        if self._remote:
            # join the pserver fleet and adopt the authoritative
            # parameters (the first trainer seeds them from this very
            # seed-deterministic init, so a cold fleet start is a no-op)
            synced = self.updater.connect_and_sync(
                {n: np.asarray(jax.device_get(v))
                 for n, v in self.params.items()},
                config_json=self.config.to_json())
            self.params = {n: jnp.asarray(np.asarray(v))
                           for n, v in synced.items()}

        if mesh is not None:
            from paddle_tpu.parallel.dp import (effective_zero_stage,
                                                shard_train_objects)
            self.zero_stage = effective_zero_stage(self.opt)
            self.params, self.opt_state = shard_train_objects(
                mesh, self.model, self.params, self.opt_state,
                shard_opt=self.opt.shard_optimizer_state,
                zero_stage=self.zero_stage)
        else:
            self.zero_stage = 0
        self._train_step_fn = self._build_train_step_fn()
        # every trainer jit reports to the compile watcher (obs/
        # compile_watch.py): a data pipeline that churns batch signatures
        # shows up as a recompile storm instead of a silent slowdown.  The
        # wrapper proxies .lower()/._cache_size() introspection unchanged.
        from paddle_tpu.obs.compile_watch import get_compile_watch
        _cw = get_compile_watch()
        self._train_step = _cw.wrap_jit(
            "trainer.train_step",
            jax.jit(self._train_step_fn, donate_argnums=(0, 1)))
        self._fused_step = _cw.wrap_jit("trainer.fused_step",
                                        self._build_fused_step())
        # benchmark twin: same scanned step, losses only (no [iters, ...]
        # evaluator/host buffers stacked on device)
        self._fused_step_losses = _cw.wrap_jit(
            "trainer.fused_step", self._build_fused_step(
                collect_outputs=False))
        # fused-dispatch oracles: tests assert exactly ceil(n/k) compiled
        # scan executions for n same-signature batches
        self._n_fused_dispatches = 0
        self._settled_sigs: set = set()
        self._test_step = _cw.wrap_jit("trainer.eval_step",
                                       self._build_test_step())
        # device-side losses buffered between host syncs (VERDICT: the
        # reference pays a per-batch cost check but not an XLA pipeline
        # stall; here finiteness is checked in bulk every
        # nonfinite_check_period batches, or per batch under --detect_nan)
        self._loss_buf: list[jax.Array] = []
        self._drained_cost = 0.0
        self._last_batch: Optional[dict[str, Argument]] = None
        # BarrierStat analog: per-step dispatch/sync timing + straggler skew,
        # logged every log_period on mesh runs (ref: utils/BarrierStat.h:
        # 198-389, REGISTER_BARRIER_TIMER_SERVER).  The windows also route
        # through the process-global span tracer (paddle_tpu/obs) when
        # tracing is enabled, so per-dispatch phases land in the same
        # Perfetto timeline as serving request lifecycles.
        from paddle_tpu.obs.trace import get_tracer
        from paddle_tpu.parallel.barrier_stat import BarrierTimer
        self._tracer = get_tracer()
        self.barrier_stat = BarrierTimer(tracer=self._tracer)
        # unified metrics registry (obs.metrics): training progress gauges
        # plus read-time collectors over the pre-existing stat systems
        # (global_stat host phases, the barrier windows, tracer ring
        # accounting).  Snapshots append to <save_dir>/metrics.jsonl next
        # to the checkpoints (append_metrics, called per pass by train()).
        from paddle_tpu.obs import (MetricsRegistry, barrier_collector,
                                    statset_collector, tracer_collector)
        self.metrics = MetricsRegistry(strict=True)
        self._m_pass = self.metrics.gauge("trainer_pass_id")
        self._m_cost = self.metrics.gauge("trainer_cost")
        self._m_sps = self.metrics.gauge("trainer_samples_per_sec")
        self._m_batches = self.metrics.counter("trainer_batches_total")
        self._m_samples = self.metrics.counter("trainer_samples_total")
        self.metrics.register_collector(statset_collector(
            global_stat, "trainer_host_phase_seconds",
            "trainer_host_phase_count", label="phase",
            total_metric="trainer_host_phase_seconds_total"))
        self.metrics.register_collector(barrier_collector(self.barrier_stat))
        self.metrics.register_collector(tracer_collector(self._tracer))
        # compile events + device-memory accounting ride the same registry
        # (and therefore metrics.jsonl): per-site jit compile counters from
        # the process-global watcher, HBM/param-byte gauges with the
        # CPU-safe fallbacks of obs/hbm.py
        from paddle_tpu.obs.compile_watch import compile_collector
        from paddle_tpu.obs.hbm import hbm_collector
        self.metrics.register_collector(compile_collector())
        self.metrics.register_collector(
            hbm_collector(params_fn=lambda: self.params))
        # immutable after construction; _validate_batch uses it per batch
        self._data_layers = {l.name: l for l in self.model.layers
                             if l.type == "data"}
        # shard-traffic balance check for vocab-sharded tables (ref:
        # pserver/SparseParameterDistribution; --check_sparse_distribution)
        self.sparse_stats = None
        if mesh is not None and FLAGS.check_sparse_distribution:
            from paddle_tpu.parallel.sparse import (SparseShardStats,
                                                    sharded_table_feeds)
            feeds = sharded_table_feeds(mesh, self.model)
            if feeds:
                self.sparse_stats = SparseShardStats(
                    feeds,
                    batches=int(FLAGS.check_sparse_distribution_batches),
                    unbalance_degree=float(
                        FLAGS.check_sparse_distribution_unbalance_degree),
                    ratio=float(FLAGS.check_sparse_distribution_ratio),
                    show_log=bool(FLAGS.show_check_sparse_distribution_log))

    # -- compiled steps ---------------------------------------------------
    @property
    def _probe_names(self) -> list[str]:
        """Layers whose OUTPUT GRADIENT a gradient_printer evaluator wants
        (ref: Evaluator.cpp GradientPrinter reads getOutputGrad()); only
        supported on the plain GraphExecutor path."""
        if not isinstance(self.executor, GraphExecutor):
            return []
        names: list[str] = []
        for cfg in self.model.evaluators:
            if cfg.type != "gradient_printer":
                continue
            for n in cfg.input_layer_names:
                # probes are injected by forward()'s root layer loop only —
                # a silent zero for group-internal layers would masquerade
                # as a real gradient, so reject loudly
                if n in self.executor._sub_of:
                    raise NotImplementedError(
                        f"gradient_printer on {n!r}: the layer runs inside "
                        f"recurrent group "
                        f"{self.executor._sub_of[n].name!r}, where output-"
                        f"grad probes are not injected — probe a layer "
                        f"outside the group (e.g. the group's consumer)")
                if n not in self.executor.layer_map or \
                        self.executor.layer_map[n].type == "data":
                    raise ValueError(
                        f"gradient_printer on {n!r}: not a computed layer")
                if n not in names:
                    names.append(n)
        return names

    def _build_train_step_fn(self):
        executor, updater, evaluators = self.executor, self.updater, self.evaluators
        remote = self._remote
        probe_names = self._probe_names
        grad_shardings = None
        if self.mesh is not None and self.zero_stage >= 2:
            # ZeRO-2: pin each eligible gradient to the data axis so XLA
            # emits a reduce-scatter instead of an all-reduce and the
            # optimizer update runs on 1/N shards (the pserver addGradient
            # contract — each server receives only its own blocks)
            from paddle_tpu.parallel.dp import zero_grad_shardings
            grad_shardings = zero_grad_shardings(self.mesh, self.model,
                                                 self.params)

        def constrain_grads(grads):
            if grad_shardings is None:
                return grads
            return {n: jax.lax.with_sharding_constraint(g, grad_shardings[n])
                    if grad_shardings.get(n) is not None else g
                    for n, g in grads.items()}

        def train_step(params, opt_state, net_state, batch, rng):
            if probe_names:
                # additive zeros at the probed layers: d(loss)/d(probe) is
                # exactly the layer's output gradient
                shapes = jax.eval_shape(
                    lambda p: executor.forward(p, batch, net_state, TRAIN,
                                               rng)[0], params)
                for n in probe_names:
                    assert shapes[n].value is not None, (
                        f"gradient_printer on {n!r}: the layer's output has "
                        f"no dense value to probe (ids-only output)")
                probes = {n: jnp.zeros(shapes[n].value.shape,
                                       shapes[n].value.dtype)
                          for n in probe_names}

                def loss_fn(p, pr):
                    loss, aux = executor.loss(p, batch, net_state, TRAIN, rng,
                                              probes=pr)
                    return loss, aux
                (loss, (outputs, costs, new_net)), (grads, probe_grads) = \
                    jax.value_and_grad(loss_fn, argnums=(0, 1),
                                       has_aux=True)(params, probes)
                grads = constrain_grads(grads)
                outputs = dict(outputs)
                for n, g in probe_grads.items():
                    outputs["__grad__" + n] = Argument(value=g)
            elif getattr(executor, "schedule", None) in ("1f1b",
                                                         "interleaved"):
                # hand-scheduled pipeline backward (1F1B, plain or over
                # interleaved virtual stages) — the executor returns grads
                # itself instead of sitting behind jax.value_and_grad;
                # net_state may carry loaded frozen-BN stats (embedded as
                # stage-body constants, never updated)
                loss, grads = executor.loss_and_grad(params, batch,
                                                     TRAIN, rng,
                                                     state=net_state)
                outputs, costs, new_net = {}, {}, net_state
                grads = constrain_grads(grads)
            else:
                def loss_fn(p):
                    loss, aux = executor.loss(p, batch, net_state, TRAIN, rng)
                    return loss, aux
                (loss, (outputs, costs, new_net)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                grads = constrain_grads(grads)
            if self.mesh is not None:
                # grads are averaged across data shards by XLA automatically
                # via sharding propagation; nothing to do here.
                pass
            bsz = _batch_size(batch)
            if remote:
                # parameter-server mode: the jitted step computes
                # gradients only — the optimizer applies SERVER-side
                # (ref: RemoteParameterUpdater — the update leaves the
                # gradient machine), so params/opt_state pass through
                # and the grads ride out for _dispatch_step to push
                new_params, new_opt = params, opt_state
            else:
                new_params, new_opt = updater.step(params, grads,
                                                   opt_state, bsz)
            partials = evaluators.batch_partials(outputs, batch)
            host_out = {n: outputs[n].flatten_image()
                        for n in evaluators.host_layer_names if n in outputs}
            if remote:
                return (new_params, new_opt, new_net, loss, partials,
                        host_out, grads)
            return new_params, new_opt, new_net, loss, partials, host_out

        return train_step

    def _build_fused_step(self, collect_outputs: bool = True):
        """Jitted k-step fused dispatch: `lax.scan` of the IDENTICAL
        per-batch train step over k batches stacked on a leading step axis,
        with pre-split per-step rng keys — one Python dispatch and one XLA
        program launch for k optimizer updates (the whole-loop-compilation
        execution model of arXiv:1810.09868).  Per-step losses, evaluator
        partials and host fetches come back stacked along the step axis so
        every host-side contract (the `_drain_losses` nonfinite check, the
        float64 evaluator accumulation, host evaluators) replays unchanged
        and the trajectory is bit-identical to the per-batch loop.  Used by
        train_one_pass(steps_per_dispatch=k) and benchmark(scan=True) —
        the benchmark's scan mode IS the production path.

        The scan length is the stacked leading dim: each distinct
        (k, batch-signature) pair compiles once, like the per-batch step
        compiles per length bucket.  grad_accum (num_batches_per_send_
        parameter > 1) needs no special casing: the accumulate-or-apply
        lax.cond lives inside the per-batch step and scans unchanged.

        collect_outputs=False drops the per-step partials/host fetches
        from the scan outputs — the benchmark scans HUNDREDS of steps in
        one dispatch and consumes only losses, so stacking [iters, ...]
        evaluator/host buffers (e.g. printer-evaluator layer outputs)
        would burn HBM for nothing.  The training path (small k) keeps
        them."""
        from jax import lax

        step_fn = self._train_step_fn

        @partial(jax.jit, donate_argnums=(0, 1))
        def fused_step(params, opt_state, net_state, stacked, keys):
            def body(carry, xs):
                p, o, n = carry
                batch, key = xs
                p, o, n, loss, partials, host_out = step_fn(p, o, n, batch,
                                                            key)
                if not collect_outputs:
                    partials, host_out = {}, {}
                return (p, o, n), (loss, partials, host_out)

            (p, o, n), (losses, partials, host_outs) = lax.scan(
                body, (params, opt_state, net_state), (stacked, keys))
            return p, o, n, losses, partials, host_outs

        return fused_step

    def _build_test_step(self):
        executor, evaluators = self.executor, self.evaluators

        @jax.jit
        def test_step(params, net_state, batch, rng):
            loss, (outputs, costs, _) = executor.loss(params, batch, net_state, TEST, rng)
            partials = evaluators.batch_partials(outputs, batch)
            host_out = {n: outputs[n].flatten_image()
                        for n in evaluators.host_layer_names if n in outputs}
            return loss, partials, host_out

        return test_step

    # -- data -------------------------------------------------------------
    def _feeder(self, data_cfg: DataConfig, train: bool):
        if data_cfg.type == "ptsh":
            # binary shards via the native C++ loader (io/feeder.py)
            from paddle_tpu.io.feeder import ShardFeeder
            kwargs = (json.loads(data_cfg.load_data_args)
                      if data_cfg.load_data_args else {})
            return ShardFeeder(
                data_cfg.files, input_names=self.model.input_layer_names,
                batch_size=self.opt.batch_size, seed=self.seed,
                drop_last=train, shuffle=train,
                names=kwargs.get("names"))
        if data_cfg.type == "multi":
            # ratio-mixed sub-providers (ref: MultiDataProvider.{h,cpp})
            from paddle_tpu.data.provider import MultiProviderWrapper
            subs, sub_files = [], []
            for sub_cfg in data_cfg.sub_configs:
                p, f = load_provider(sub_cfg, fresh=True)
                subs.append(p)
                sub_files.append(f)
            prov = MultiProviderWrapper(subs, sub_files,
                                        ratios=data_cfg.data_ratios or None,
                                        is_test=not train)
            files: list[str] = []
        else:
            prov, files = load_provider(data_cfg)
        return DataFeeder(
            prov, files, input_names=self.model.input_layer_names,
            batch_size=self.opt.batch_size, seed=self.seed,
            drop_last=train, shuffle=None if train else False,
            constant_slots=data_cfg.constant_slots)

    def train_batches(self) -> Iterator[dict[str, Argument]]:
        assert self.config.data_config is not None, "config has no data source"
        feeder = self._feeder(self.config.data_config, True)
        if not self.config.data_config.async_load_data:
            # ref: --async_load_data=false / DataConfig.async_load_data —
            # assemble batches synchronously on the training thread
            return feeder.batches()
        return feeder.prefetched_batches()

    # -- loops ------------------------------------------------------------
    def _batch_signature(self, batch: dict[str, Argument]) -> tuple:
        """Shape/dtype signature of a batch plus the net_state structure —
        the retrace key of the compiled step.  The per-batch path uses it
        to keep compile time out of the barrier windows; the fused path
        (steps_per_dispatch > 1) groups consecutive same-signature batches
        by it (a length-bucketed feeder emits few distinct signatures)."""
        return (str(jax.tree.map(
                    lambda a: (jnp.shape(a), str(jnp.result_type(a))), batch)),
                str(jax.tree_util.tree_structure(self.net_state)))

    def _seen_sigs(self) -> set:
        seen = getattr(self, "_dispatch_sigs", None)
        if seen is None:
            seen = self._dispatch_sigs = set()
        return seen

    def _dispatch_step(self, batch: dict[str, Argument], key=None):
        """Dispatch one compiled train step (async — no host sync); returns
        (loss, partials, host_out) device values.  `key` overrides the
        internal rng split with a pre-split per-step key (the fused path's
        settling dispatch must consume the key already drawn for batch 0)."""
        if self.mesh is not None:
            from paddle_tpu.parallel.dp import shard_batch
            batch = shard_batch(self.mesh, batch)
        if key is None:
            self.rng, key = jax.random.split(self.rng)
        self._last_rng = key
        # any UNSEEN (batch-shape, net_state-structure) signature likely
        # retraces+recompiles — seconds of XLA work, not queue backpressure;
        # keep those dispatches out of the barrier timing windows (this
        # covers the first batch, every new length bucket, and the
        # net_state pytree change after batch 1)
        sig = self._batch_signature(batch)
        seen = self._seen_sigs()
        if sig in seen:
            with self.barrier_stat.time_dispatch():
                out = self._train_step(self.params, self.opt_state,
                                       self.net_state, batch, key)
        else:
            seen.add(sig)
            out = self._train_step(self.params, self.opt_state,
                                   self.net_state, batch, key)
        (self.params, self.opt_state, new_net, loss, partials,
         host_out) = out[:6]
        if new_net:
            self.net_state = new_net
        if self._remote:
            # parameter-server round trip (ref: RemoteParameterUpdater::
            # finishBatch): fetch this batch's gradients to the host,
            # contribute them to every shard, and adopt the post-window
            # parameters (sync mode returns them every batch; async on
            # the num_batches_per_get_parameter cadence)
            grads = out[6]
            with global_stat.time("remoteUpdate"):
                t_c0 = time.perf_counter()
                grads_host = {n: np.asarray(jax.device_get(g))
                              for n, g in grads.items()}
                # the grad fetch blocks until the dispatched step's
                # gradients exist — its wall IS the window's compute
                # part; the updater folds it into the per-window
                # attribution and the window span
                fresh = self.updater.remote_step(
                    grads_host, _batch_size(batch),
                    compute=(t_c0, time.perf_counter() - t_c0))
            if fresh is not None:
                self.params = {n: jnp.asarray(np.asarray(v))
                               for n, v in fresh.items()}
        return loss, partials, host_out

    def _dispatch_fused(self, staged, keys, sig: tuple):
        """Dispatch ONE compiled k-step scan over a staged same-signature
        group (async); returns stacked (losses, partials, host_outs).  The
        first dispatch of a (k, signature) pair compiles — kept out of the
        `scan` barrier window like _dispatch_step's first-seen logic."""
        self._last_rng = keys[-1]
        fsig = ("fused", int(keys.shape[0]), sig)
        seen = self._seen_sigs()
        if fsig in seen:
            with self.barrier_stat.time_scan():
                out = self._fused_step(self.params, self.opt_state,
                                       self.net_state, staged, keys)
        else:
            seen.add(fsig)
            out = self._fused_step(self.params, self.opt_state,
                                   self.net_state, staged, keys)
        (self.params, self.opt_state, new_net, losses, partials, host_outs) = out
        if new_net:
            self.net_state = new_net
        self._n_fused_dispatches += 1
        return losses, partials, host_outs

    def _validate_batch(self, batch: dict[str, Argument]) -> None:
        """Clear errors for the common feed mistakes BEFORE tracing: a
        missing/misspelled key would otherwise silently skip downstream
        layers (the generation-path skip in builder.forward) and surface as
        'model has no cost layers'; out-of-range ids would gather garbage
        and train on NaNs.  Host-side numpy checks only — device arrays are
        not synced."""
        data_layers = self._data_layers
        missing = sorted(set(data_layers) - set(batch))
        if missing:
            raise KeyError(
                f"batch is missing feed(s) for data layer(s) {missing}; "
                f"fed keys: {sorted(batch)}")
        unknown = sorted(set(batch) - set(data_layers))
        if unknown:
            raise KeyError(
                f"batch feeds unknown key(s) {unknown} — not data layers "
                f"(expected: {sorted(data_layers)}); a feed shadowing a "
                f"computed layer would silently override it")
        sizes = {}
        for name, arg in batch.items():
            if arg.value is None and arg.ids is None:
                raise ValueError(f"feed {name!r} carries neither dense "
                                 f"values nor ids")
            sizes[name] = arg.batch_size
            cfg = data_layers[name]
            ids = arg.ids
            if (isinstance(ids, np.ndarray) and arg.sparse_dim == 0
                    and cfg.size > 0 and ids.size):
                hi, lo = int(ids.max()), int(ids.min())
                if hi >= cfg.size or lo < 0:
                    raise ValueError(
                        f"feed {name!r}: id {hi if hi >= cfg.size else lo} "
                        f"out of range for data layer size {cfg.size} — "
                        f"this would gather garbage and train on NaNs")
        if len(set(sizes.values())) > 1:
            raise ValueError(f"feeds disagree on batch size: {sizes}")

    def train_one_batch(self, batch: dict[str, Argument]):
        """(ref: TrainerInternal::trainOneBatch).

        Returns the step's loss as a DEVICE scalar — no host sync.  Under
        --detect_nan (the reference's feenableexcept analog,
        TrainerMain.cpp:97) the loss is fetched and checked every batch with
        layer-level localisation; otherwise losses buffer on device and are
        bulk-checked every nonfinite_check_period batches, so dispatch
        pipelines with device compute."""
        self._validate_batch(batch)
        if self.sparse_stats is not None:
            self.sparse_stats.probe_batch(batch)
        loss, partials, host_out = self._dispatch_step(batch)
        self._acc = self.evaluators.accumulate(getattr(self, "_acc", {}), partials)
        if self.evaluators.host_configs:
            if not hasattr(self, "_host_acc") or self._host_acc is None:
                self._host_acc = self.evaluators.new_host_state()
            self.evaluators.host_update(self._host_acc, host_out)
        return self._account_loss(loss, batch)

    def _account_loss(self, loss, batch: dict[str, Argument]):
        """Per-step loss bookkeeping shared by the per-batch and fused
        loops: under --detect_nan fetch+check immediately; otherwise buffer
        the device scalar and bulk-drain every nonfinite_check_period."""
        if FLAGS.detect_nan:
            loss_f = float(loss)
            if not np.isfinite(loss_f):
                # layer-level localisation, the gLayerStackTrace-on-crash
                # analog (ref: utils/CustomStackTrace.h;
                # NeuralNetwork.cpp:280-286)
                raise FloatingPointError(
                    f"non-finite loss {loss_f}; {self.diagnose_nonfinite(batch)}")
            self._drained_cost += loss_f
            return loss_f
        self._last_batch = batch
        self._loss_buf.append(loss)
        if len(self._loss_buf) >= max(int(FLAGS.nonfinite_check_period), 1):
            self._drained_cost += self._drain_losses()
        return loss

    def _drain_losses(self) -> float:
        """One host sync for all buffered device losses: bulk finiteness
        check + their sum (for cost accounting)."""
        if not self._loss_buf:
            return 0.0
        with self.barrier_stat.time_sync():
            losses = np.asarray(jax.device_get(jnp.stack(self._loss_buf)))
        n = len(self._loss_buf)
        self._loss_buf.clear()
        if not np.isfinite(losses).all():
            bad = int(np.flatnonzero(~np.isfinite(losses))[0])
            diag = (self.diagnose_nonfinite(self._last_batch)
                    if self._last_batch is not None else "")
            raise FloatingPointError(
                f"non-finite loss {losses[bad]} ({n - bad - 1} batches before "
                f"the last dispatched; run with --detect_nan for exact "
                f"per-batch localisation); {diag}")
        return float(losses.sum())

    def train_one_pass(self, batches: Optional[Iterator] = None,
                       log_period: int = 0,
                       steps_per_dispatch: Optional[int] = None
                       ) -> dict[str, float]:
        """(ref: Trainer::trainOnePass).

        steps_per_dispatch=k > 1 (default: --steps_per_dispatch) runs the
        pass through the fused dispatch path: consecutive same-signature
        batches stack into k-groups, each executed as ONE compiled k-step
        lax.scan while a background thread device-stages the NEXT group
        (see _train_one_pass_fused).  Trajectory, evaluator results and
        the nonfinite-check contract are identical to the k=1 loop."""
        t0 = time.time()
        self._acc = self.evaluators.new_accumulator()
        self._host_acc = self.evaluators.new_host_state() if \
            self.evaluators.host_configs else None
        self._drained_cost = 0.0
        self._loss_buf.clear()
        if batches is None:
            batches = self.train_batches()
        k = int(FLAGS.steps_per_dispatch if steps_per_dispatch is None
                else steps_per_dispatch)
        if k > 1 and self._remote:
            # the fused scan hosts the optimizer INSIDE the compiled
            # dispatch; remote mode applies it server-side per batch —
            # the two cannot compose, and the sync barrier is per batch
            # anyway, so the scan would buy nothing
            log.warning("remote updater forces steps_per_dispatch=1 "
                        "(the pserver barrier is per batch)")
            k = 1
        if k > 1 and FLAGS.detect_nan:
            # --detect_nan promises PER-BATCH halting + localisation with
            # the failing step's rng/params; a fused group would apply the
            # remaining k-1 updates before the check and replay diagnosis
            # with the group's last key.  Debug mode wins over dispatch
            # overhead: fall back to the per-batch loop.
            log.warning("--detect_nan forces steps_per_dispatch=1 "
                        "(per-batch nonfinite localisation)")
            k = 1
        if k > 1:
            return self._train_one_pass_fused(batches, log_period, k, t0)
        n_batches, n_samples = 0, 0
        stats_period = FLAGS.show_parameter_stats_period
        for batch in batches:
            with global_stat.time("trainOneBatch"):
                self.train_one_batch(batch)
            n_batches += 1
            n_samples += _batch_size(batch)
            if log_period and n_batches % log_period == 0:
                self._log_progress(n_batches)
            if stats_period and n_batches % stats_period == 0:
                self.log_param_stats()
        return self._finish_pass_stats(t0, n_batches, n_samples)

    def _log_progress(self, n_batches: int) -> None:
        self._drained_cost += self._drain_losses()
        log.info("pass %d batch %d: cost=%.5f %s", self.pass_id, n_batches,
                 self._drained_cost / n_batches,
                 _fmt(self.evaluators.finalize(self._acc)))
        if self.mesh is not None:
            log.info("barrier: %s", self.barrier_stat.render())

    def _finish_pass_stats(self, t0: float, n_batches: int,
                           n_samples: int) -> dict[str, float]:
        self._drained_cost += self._drain_losses()
        total_cost = self._drained_cost
        self.opt_state = self.updater.finish_pass(self.opt_state)
        stats = self.evaluators.finalize(self._acc)
        if self._host_acc is not None:
            stats.update(self.evaluators.finalize_host(self._host_acc))
        dt = time.time() - t0
        stats.update(cost=total_cost / max(n_batches, 1), batches=n_batches,
                     samples=n_samples, seconds=dt,
                     samples_per_sec=n_samples / dt if dt > 0 else 0.0)
        if self._remote and hasattr(self.updater, "pass_timing"):
            # remote-updater attribution riding the pass row: where this
            # pass's wall went (push/barrier_wait/pull/apply ms + async
            # staleness rejects) — metrics.jsonl and TRAIN_JSON inherit
            # these next to the throughput gauges, so a distributed run's
            # single-file pass history answers "where did my
            # scaling_efficiency go" without a trace viewer
            stats.update(self.updater.pass_timing())
        log.info("pass %d done: %s", self.pass_id, _fmt(stats))
        if self._tracer.enabled:
            self._tracer.add("train_pass", time.perf_counter() - dt, dt,
                             track="trainer",
                             attrs={"pass": self.pass_id,
                                    "batches": n_batches})
        self.pass_id += 1
        self._m_pass.set(self.pass_id)             # = passes completed
        self._m_cost.set(stats["cost"])
        self._m_sps.set(stats["samples_per_sec"])
        if n_batches:
            self._m_batches.inc(n_batches)
            self._m_samples.inc(n_samples)
        return stats

    # -- fused k-step dispatch (--steps_per_dispatch) ---------------------
    def _net_state_settled(self, batch: dict[str, Argument], key) -> bool:
        """True if dispatching `batch` cannot change the net_state pytree
        STRUCTURE.  A stateful model (training-mode batch norm) grows its
        state on the first-ever dispatch; a lax.scan carry must be
        structure-stable, so the fused path routes that one batch through
        the per-batch step first — exactly what the k=1 loop's batch 0
        does.  Shape-level tracing only (jax.eval_shape); cached per batch
        signature."""
        sig = self._batch_signature(batch)
        if sig in self._settled_sigs:
            return True
        try:
            out = jax.eval_shape(self._train_step_fn, self.params,
                                 self.opt_state, self.net_state, batch, key)
        except Exception:
            return False     # conservatively settle via a per-batch dispatch
        new_net = out[2]
        settled = (not new_net) or (
            jax.tree_util.tree_structure(new_net)
            == jax.tree_util.tree_structure(self.net_state))
        if settled:
            self._settled_sigs.add(sig)
        return settled

    def _stage_group(self, group):
        """DeviceDoubleBuffer place_fn: stack a same-signature k-group on a
        leading step axis and move it to device (batch dim sharded over
        `data` under a mesh) — runs on the prefetch thread, so the H2D
        transfer of group i+1 overlaps the scan of group i."""
        host_batches, keys, sig = group
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *host_batches)
        if self.mesh is not None:
            from paddle_tpu.parallel.dp import stage_stacked_batch
            stacked = stage_stacked_batch(self.mesh, stacked)
        else:
            stacked = jax.device_put(stacked)
        return stacked, jnp.stack(keys), host_batches, sig

    def _train_one_pass_fused(self, batches: Iterator, log_period: int,
                              k: int, t0: float) -> dict[str, float]:
        """Fused pass body: k train steps per compiled dispatch + device
        double-buffered input staging.

        Parity with the k=1 loop is exact, not approximate:
          - batches group by the _batch_signature length-bucket key but
            only CONSECUTIVE same-signature batches fuse (a group flushes
            early on signature change), so optimizer updates apply in
            arrival order;
          - per-step rng keys are pre-split from self.rng in arrival
            order — step i consumes the very key the k=1 loop would;
          - grad_accum (optim/updater.py) rides inside the scanned step;
          - per-step losses come back stacked and feed the same
            _loss_buf/_drain_losses cadence, and evaluator partials
            accumulate per step in the same float64 order.
        Dispatch count for n same-signature batches is exactly ceil(n/k)
        (+1 per-batch settling dispatch for stateful models, mirroring the
        k=1 loop's structure-changing first batch)."""
        from paddle_tpu.data.feeder import DeviceDoubleBuffer
        stats_period = FLAGS.show_parameter_stats_period
        n_batches, n_samples = 0, 0

        def host_groups():
            pending: list = []
            keys: list = []
            sig = None
            for batch in batches:
                self._validate_batch(batch)
                if self.sparse_stats is not None:
                    self.sparse_stats.probe_batch(batch)
                s = self._batch_signature(batch)
                if pending and (s != sig or len(pending) == k):
                    yield pending, keys, sig
                    pending, keys = [], []
                sig = s
                self.rng, sub = jax.random.split(self.rng)
                pending.append(batch)
                keys.append(sub)
            if pending:
                yield pending, keys, sig

        groups = host_groups()
        first = next(groups, None)
        if first is None:
            return self._finish_pass_stats(t0, 0, 0)
        if not self._net_state_settled(first[0][0], first[1][0]):
            b0, key0 = first[0][0], first[1][0]
            with global_stat.time("trainOneBatch"):
                loss, partials, host_out = self._dispatch_step(b0, key=key0)
                self._acc = self.evaluators.accumulate(self._acc, partials)
                if self._host_acc is not None:
                    self.evaluators.host_update(self._host_acc, host_out)
                self._account_loss(loss, b0)
            n_batches += 1
            n_samples += _batch_size(b0)
            first = (first[0][1:], first[1][1:], first[2])

        def chain():
            if first[0]:
                yield first
            yield from groups

        staged = DeviceDoubleBuffer(chain(), self._stage_group,
                                    timer=self.barrier_stat.time_h2d)
        try:
            for stacked, keys, host_batches, sig in staged:
                j = len(host_batches)
                with global_stat.time("trainKSteps"):
                    losses, partials, host_outs = self._dispatch_fused(
                        stacked, keys, sig)
                self._acc = self.evaluators.accumulate_stacked(
                    self._acc, partials, j)
                if self._host_acc is not None and host_outs:
                    host_np = jax.tree.map(np.asarray,
                                           jax.device_get(host_outs))
                    for i in range(j):
                        self.evaluators.host_update(
                            self._host_acc,
                            jax.tree.map(lambda a: a[i], host_np))
                for i in range(j):
                    self._account_loss(losses[i], host_batches[i])
                n_batches += j
                n_samples += sum(_batch_size(b) for b in host_batches)
                if log_period and (n_batches // log_period) != \
                        ((n_batches - j) // log_period):
                    self._log_progress(n_batches)
                if stats_period and (n_batches // stats_period) != \
                        ((n_batches - j) // stats_period):
                    self.log_param_stats()
        finally:
            # a mid-pass exception (nonfinite drain, feed validation) must
            # not leave the producer thread blocked holding staged groups
            staged.close()
        return self._finish_pass_stats(t0, n_batches, n_samples)

    def train(self, num_passes: int = 1, log_period: int = 100,
              save_dir: Optional[str] = None, keep_last: int = 0) -> list[dict]:
        """Full training job (ref: Trainer::train)."""
        history = []
        for _ in range(num_passes):
            stats = self.train_one_pass(log_period=log_period)
            if self.config.test_data_config is not None:
                test_stats = self.test()
                log.info("pass %d test: %s", self.pass_id - 1, _fmt(test_stats))
                stats["test"] = test_stats
            if save_dir:
                self.save(save_dir, keep_last=keep_last)
                # the metrics sink rides next to the checkpoints: one
                # registry snapshot per pass, JSON-lines, append-only
                self.append_metrics(save_dir, extra=stats)
            history.append(stats)
        return history

    def append_metrics(self, save_dir: str, extra: Optional[dict] = None
                       ) -> str:
        """Append one metrics record to `<save_dir>/metrics.jsonl` — the
        trainer-side counterpart of the serving server's `metrics` frame:
        {ts, pass_id, extra scalar pass stats, metrics: registry snapshot
        (progress gauges + host-phase/barrier quantiles)}.  Process 0
        only, like checkpoint writes."""
        if jax.process_index() != 0:
            return ""
        import datetime

        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, "metrics.jsonl")
        rec = {"ts": datetime.datetime.now(datetime.timezone.utc)
                       .isoformat(timespec="seconds"),
               "pass_id": self.pass_id}
        for k, v in (extra or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rec[k] = v
        rec["metrics"] = self.metrics.snapshot()
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return path

    def test(self, batches: Optional[Iterator] = None) -> dict[str, float]:
        """(ref: Tester::testOnePeriod)."""
        if batches is None:
            if self.config.test_data_config is None:
                raise ValueError(
                    "test needs a test data source, but this config "
                    "declares none — add define_py_data_sources2("
                    "test_list=...) to the config, or pass batches= "
                    "explicitly (ref: --job=test requires a test source, "
                    "TrainerMain.cpp)")
            batches = self._feeder(self.config.test_data_config, False).batches()
        params = self.updater.averaged_params(self.params, self.opt_state)
        acc = self.evaluators.new_accumulator()
        host_acc = self.evaluators.new_host_state() if \
            self.evaluators.host_configs else None
        total, n = 0.0, 0
        self.rng, sub = jax.random.split(self.rng)
        with self._tracer.span("eval", track="trainer"):
            for batch in batches:
                loss, partials, host_out = self._test_step(
                    params, self.net_state, batch, sub)
                bsz = _batch_size(batch)
                total += float(loss) * bsz
                n += bsz
                acc = self.evaluators.accumulate(acc, partials)
                if host_acc is not None:
                    self.evaluators.host_update(host_acc, host_out)
        stats = self.evaluators.finalize(acc)
        if host_acc is not None:
            stats.update(self.evaluators.finalize_host(host_acc))
        stats["cost"] = total / max(n, 1)
        return stats

    # -- diagnostics ------------------------------------------------------
    def param_stats(self) -> dict[str, dict[str, float]]:
        """Per-parameter health dump (ref: TrainerInternal.cpp:187-217
        showParameterStats: avg/max abs value logged every
        show_parameter_stats_period batches)."""
        out = {}
        for name, v in self.params.items():
            a = np.abs(np.asarray(jax.device_get(v)))
            out[name] = {"shape": tuple(v.shape), "mean_abs": float(a.mean()),
                         "max_abs": float(a.max())}
        return out

    def log_param_stats(self) -> None:
        for name, s in self.param_stats().items():
            log.info("param %s shape=%s mean_abs=%.3e max_abs=%.3e",
                     name, s["shape"], s["mean_abs"], s["max_abs"])

    def diagnose_nonfinite(self, batch: dict[str, Argument],
                           rng: Optional[jax.Array] = None) -> str:
        """Layer-level NaN/Inf localisation — the analog of the reference's
        gLayerStackTrace dump on crash (ref: utils/CustomStackTrace.h;
        NeuralNetwork.cpp:241,280-286): re-run forward uncompiled and report
        the first layer whose output is non-finite.

        The jitted train step donates its param buffers, so this runs on the
        POST-update parameters — the report says which case applies."""
        if rng is None:
            rng = getattr(self, "_last_rng", None)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        outputs, costs, _ = self.executor.forward(
            self.params, batch, self.net_state, TRAIN, rng)
        for l in self.model.layers:
            arg = outputs.get(l.name)
            if arg is None or arg.value is None:
                continue
            a = np.asarray(jax.device_get(arg.value))
            if not np.isfinite(a).all():
                return (f"first non-finite output at layer {l.name!r} "
                        f"(type={l.type}): nan={np.isnan(a).sum()} "
                        f"inf={np.isinf(a).sum()} of {a.size} "
                        f"(forward re-run with post-update parameters)")
        for cname, c in costs.items():
            if not np.isfinite(np.asarray(jax.device_get(c))).all():
                return (f"non-finite cost {cname!r} with finite layer outputs "
                        f"(forward re-run with post-update parameters)")
        return ("forward with post-update parameters is finite — the "
                "non-finite value arose in the gradient/optimizer update of "
                "the failing step")

    def check_gradient(self, batch: dict[str, Argument],
                       epsilon: float = 1e-3,
                       max_entries: int = 4,
                       refine_threshold: float = 0.02) -> dict[str, float]:
        """Finite-difference gradient check on a real batch — the --job=
        checkgrad mode (ref: Trainer::checkGradient, Trainer.cpp:303+):
        perturb sampled entries of every parameter, compare numeric
        d(loss)/d(w) against the analytic gradient.  Returns per-parameter
        max relative error.

        Two-stage precision: a fast fp32 screen over every parameter, then
        (CPU backends only) a float64 re-adjudication of just the
        parameters the screen flagged above `refine_threshold`.  fp32
        central differences carry multi-ulp rounding noise through a deep
        net — on the VGG configs the noise floor sits around |grad| ~1e-2,
        spuriously flagging every smaller-gradient parameter — while f64
        (the test_layer_grad.py pattern) is noise-free but ~100x slower,
        so it only re-checks the screen's failures.  TPU has no f64; there
        the fp32 noise-aware denominator is the whole story."""
        errors = self._checkgrad_pass(batch, epsilon, max_entries,
                                      x64=False)
        if jax.default_backend() == "cpu":
            flagged = [n for n, e in errors.items() if e > refine_threshold]
            if flagged:
                log.info("checkgrad: re-adjudicating %d flagged parameters "
                         "in float64: %s", len(flagged), flagged)
                errors.update(self._checkgrad_pass(
                    batch, epsilon, max_entries, x64=True, names=flagged,
                    detect_kinks=True))
        return errors

    def _checkgrad_pass(self, batch, epsilon, max_entries, x64: bool,
                        names=None, detect_kinks: bool = False
                        ) -> dict[str, float]:
        import contextlib

        rng = jax.random.PRNGKey(7)
        # full precision: a central difference of 1e-3 is below bf16
        # resolution, so the check must bypass any mixed-precision cast
        saved_dtype = self.executor.compute_dtype
        self.executor.compute_dtype = ""
        try:
            from paddle_tpu.utils.jax_compat import enable_x64
            with (enable_x64() if x64 else contextlib.nullcontext()):
                if x64:
                    def to_f64(x):
                        x = jnp.asarray(np.asarray(jax.device_get(x)))
                        if jnp.issubdtype(x.dtype, jnp.floating):
                            return x.astype(jnp.float64)
                        return x
                    params = {k: to_f64(v) for k, v in self.params.items()}
                    cbatch = jax.tree.map(to_f64, batch)
                    state = jax.tree.map(to_f64, self.net_state)
                else:
                    # no dtype change: keep the arrays (and any sharding)
                    # exactly as training holds them
                    params, cbatch, state = self.params, batch, self.net_state
                # jit once: every perturbed evaluation reuses the executable
                loss_fn = jax.jit(lambda p: self.executor.loss(
                    p, cbatch, state, TEST, rng)[0])
                if getattr(self.executor, "schedule", None) in (
                        "1f1b", "interleaved"):
                    # audit the grads TRAINING actually uses: the hand-
                    # scheduled loss_and_grad backward, not the autodiff of
                    # loss() that only the gpipe schedule trains with
                    _, grads = jax.jit(lambda p: self.executor.loss_and_grad(
                        p, cbatch, TEST, rng))(params)
                else:
                    grads = jax.jit(jax.grad(lambda p: self.executor.loss(
                        p, cbatch, state, TEST, rng)[0]))(params)
                return self._check_gradient_inner(loss_fn, grads, epsilon,
                                                  max_entries, params, names,
                                                  detect_kinks)
        finally:
            self.executor.compute_dtype = saved_dtype

    def _check_gradient_inner(self, loss_fn, grads, epsilon,
                              max_entries, params=None,
                              names=None,
                              detect_kinks=False) -> dict[str, float]:
        errors: dict[str, float] = {}
        params = self.params if params is None else params
        nrng = np.random.default_rng(0)
        L0 = float(loss_fn(params)) if detect_kinks else 0.0
        for name, w in params.items():
            if name in self.executor.static_param_names:
                continue
            if names is not None and name not in names:
                # keep drawing from nrng so the SAME entries are sampled
                # whether or not the parameter is in this pass's subset
                # (the f64 re-adjudication must probe what fp32 flagged);
                # .size reads shape metadata — no device transfer
                size = int(np.size(w))
                nrng.choice(max(size, 1), size=min(max_entries, size),
                            replace=False)
                continue
            flat = np.asarray(jax.device_get(w)).reshape(-1)
            gflat = np.asarray(jax.device_get(grads[name])).reshape(-1)
            idxs = nrng.choice(flat.size, size=min(max_entries, flat.size),
                               replace=False)
            worst = 0.0
            n_validated = n_kink = 0
            for i in idxs:
                eps_i = epsilon

                def fd_sides(h):
                    out = []
                    for sign in (+1, -1):
                        pert = flat.copy()
                        pert[i] += sign * h
                        p2 = dict(params)
                        p2[name] = jnp.asarray(pert.reshape(w.shape))
                        out.append(float(loss_fn(p2)))
                    return out

                sides = fd_sides(eps_i)
                if detect_kinks:
                    # a ReLU-style kink inside [w-h, w+h] makes the central
                    # difference measure the subgradient average, not the
                    # analytic one-sided derivative — mismatched forward/
                    # backward one-sided differences expose it (only
                    # meaningful in the f64 pass, where FD noise ~1e-12).
                    # First response: shrink h 100x — the kink usually
                    # falls outside the tighter interval and the entry
                    # stays validated; only a point RIGHT AT the kink is
                    # skipped.
                    def kinked(s, h):
                        fwd = (s[0] - L0) / h
                        bwd = (L0 - s[1]) / h
                        return abs(fwd - bwd) > 0.1 * max(
                            abs(fwd), abs(bwd), 1e-12), fwd, bwd
                    bad, fwd, bwd = kinked(sides, eps_i)
                    if bad:
                        eps_i = epsilon / 100.0
                        sides = fd_sides(eps_i)
                        bad, fwd, bwd = kinked(sides, eps_i)
                    if bad:
                        n_kink += 1
                        log.info(
                            "checkgrad %s[%d]: straddles a non-smooth point "
                            "even at h=%.1e (one-sided fwd %.3e vs bwd "
                            "%.3e) — entry skipped", name, i, eps_i, fwd,
                            bwd)
                        continue
                numeric = (sides[0] - sides[1]) / (2 * eps_i)
                n_validated += 1
                # central differences cancel catastrophically once the true
                # gradient drops below the loss's own rounding noise —
                # measured on the 13-layer VGG configs at ~up to 100 ulp of
                # |L| per evaluation (each perturbation re-rounds the whole
                # forward, not just the final sum), i.e. an absolute FD
                # resolution of ~100*|L|*dtype_eps/(2h).  fp32 screens
                # clamp the denominator there: gradients under the floor
                # carry no finite-difference signal either way (rel_err ~1
                # spuriously, the pre-r5 behavior), while a genuinely wrong
                # gradient of visible magnitude still flags — and anything
                # that DOES flag is re-adjudicated in f64, where the floor
                # is ~1e-11 and the check is strict.
                noise = (abs(sides[0]) + abs(sides[1])) * \
                    float(np.finfo(flat.dtype).eps) / (2 * eps_i)
                denom = max(abs(numeric), abs(gflat[i]), 100.0 * noise, 1e-8)
                worst = max(worst, abs(numeric - gflat[i]) / denom)
            if detect_kinks and n_validated == 0 and n_kink > 0:
                # every sampled entry sat exactly on a non-smooth point —
                # the refine pass ADJUDICATED NOTHING.  Omit the key so
                # check_gradient's errors.update() keeps the fp32 screen's
                # flagged value (a flagged-but-unadjudicated parameter must
                # still fail the --job=checkgrad exit-code contract, not
                # exit 0 on a silent 0.0; ADVICE r5)
                log.warning(
                    "checkgrad %s: 0 of %d sampled entries validated (all "
                    "straddle non-smooth points) — inconclusive; the fp32 "
                    "screen's flagged error stands for this parameter",
                    name, n_kink)
                continue
            errors[name] = worst
            log.info("checkgrad %s: max_rel_err=%.3e", name, worst)
        return errors

    def benchmark(self, batches: Iterator, warmup: int = 3, iters: int = 30,
                  scan: bool = False) -> dict:
        """--job=time analog (ref: TrainerBenchmark.cpp).

        Default mode dispatches the jitted step per batch asynchronously —
        no per-step host sync — and blocks once at the end; this includes
        host dispatch + any host->device input transfer in the measured
        time, like the reference's end-to-end --job=time loop.

        scan=True stages all batches in device memory and runs the SAME
        per-batch training step inside one `lax.scan` — a single dispatch
        for the whole run, via the PRODUCTION fused-dispatch path
        (_build_fused_step, what train_one_pass(steps_per_dispatch=k)
        executes).  This is the TPU-native shape of a production input
        pipeline (data prefetched to HBM ahead of compute) and measures
        pure device throughput.

        Every step's loss is checked finite after the final sync (a mid-run
        divergence fails the benchmark rather than being silently timed).
        """
        batch_list = []
        it = iter(batches)
        for _ in range(warmup + iters):
            try:
                batch_list.append(next(it))
            except StopIteration:
                break
        n_samples = sum(_batch_size(b) for b in batch_list[warmup:])
        if scan:
            if self._remote:
                raise ValueError(
                    "benchmark(scan=True) hosts the optimizer inside one "
                    "compiled dispatch — incompatible with the remote "
                    "(parameter-server) updater; benchmark with "
                    "scan=False or tools/train_dist.py / bench.py "
                    "train_dist")
            return self._benchmark_scan(batch_list, warmup, n_samples)
        for b in batch_list[:warmup]:
            self._dispatch_step(b)
        jax.block_until_ready(self.params)

        t0 = time.time()
        losses = []
        for b in batch_list[warmup:]:
            loss, _, _ = self._dispatch_step(b)
            losses.append(loss)
        # a real device->host fetch is the sync point (block_until_ready on
        # the experimental axon plugin can return before compute finishes)
        lo = np.asarray(jax.device_get(jnp.stack(losses))) if losses else None
        dt = time.time() - t0
        if lo is not None:
            assert np.isfinite(lo).all(), \
                f"non-finite loss at bench step {int(np.flatnonzero(~np.isfinite(lo))[0])}"
        return {"seconds": dt, "samples": n_samples,
                "samples_per_sec": n_samples / dt if dt else 0.0,
                "batches": len(batch_list) - warmup}

    def _benchmark_scan(self, batch_list: list, warmup: int, n_samples: int) -> dict:
        """Scan-of-steps benchmark body: one XLA dispatch for all iters —
        DELEGATES to the production fused-dispatch program (_build_fused_
        step), so the benchmark measures exactly what train_one_pass(
        steps_per_dispatch=k) executes: same scanned step, same pre-split
        per-step key contract, same staging layout."""
        iters = len(batch_list) - warmup
        assert iters > 0, "need at least one timed iteration"
        # stage on device, stacked along a leading step axis; on a mesh the
        # per-batch axis (dim 1) is sharded over `data`, matching what
        # _dispatch_step's shard_batch does per step
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batch_list[warmup:])
        if self.mesh is not None:
            from paddle_tpu.parallel.dp import stage_stacked_batch
            stacked = stage_stacked_batch(self.mesh, stacked)
        else:
            stacked = jax.device_put(stacked)
        jax.block_until_ready([a.value if a.value is not None else a.ids
                               for a in stacked.values()])

        for b in batch_list[:warmup]:
            self._dispatch_step(b)
        jax.block_until_ready(self.params)
        keys = []
        for _ in range(iters):
            self.rng, sub = jax.random.split(self.rng)
            keys.append(sub)
        keys = jnp.stack(keys)

        def run():
            (self.params, self.opt_state, new_net, losses, _, _) = \
                self._fused_step_losses(self.params, self.opt_state,
                                        self.net_state, stacked, keys)
            if new_net:
                self.net_state = new_net
            return losses

        # one untimed warmup EXECUTION (which also compiles): forces the
        # staged batches' host->device transfers to actually complete
        # (block_until_ready on the experimental axon plugin can return
        # early; only a device->host fetch is a true sync point) and
        # settles donation buffers
        np.asarray(jax.device_get(run()))

        t0 = time.time()
        losses = run()
        # the loss fetch is the honest end-of-run sync point
        lo = np.asarray(jax.device_get(losses))
        dt = time.time() - t0
        assert np.isfinite(lo).all(), \
            f"non-finite loss at bench step {int(np.flatnonzero(~np.isfinite(lo))[0])}"
        return {"seconds": dt, "samples": n_samples,
                "samples_per_sec": n_samples / dt if dt else 0.0,
                "batches": iters}


    # -- checkpointing ----------------------------------------------------
    def save(self, save_dir: str, keep_last: int = 0) -> str:
        """(ref: ParamUtil::saveParametersOnePass; only trainer 0 saves —
        here process 0 under multi-host jax.distributed)."""
        # every process participates in the gather of non-addressable
        # shards (ZeRO-1 slots span hosts); only process 0 writes
        params = _host_tree(self.params)
        opt_state = _host_tree(self.opt_state)
        net_state = _host_tree(self.net_state)
        if jax.process_index() != 0:
            return ""
        # pass_id 0 = nothing completed yet: label the snapshot pass-init
        # instead of clamping into the pass-00000 slot (which the real
        # end-of-pass-0 save owns; resuming from a clamped one would
        # silently skip training pass 0)
        return ckpt.save_checkpoint(
            save_dir, self.pass_id - 1, params, opt_state, net_state,
            config_json=self.config.to_json(), keep_last=keep_last,
            rng=np.asarray(self.rng))

    def load(self, path: str) -> None:
        """(ref: ParamUtil::loadParameters / --init_model_path)."""
        data = ckpt.load_checkpoint(path)
        loaded = data["params"]
        ref_fmt = data.get("reference_format", False)
        self.params = dict(self.params)
        for name in self.params:
            assert name in loaded, f"checkpoint missing parameter {name!r}"
            cur = self.params[name]
            arr = jnp.asarray(loaded[name])
            if ref_fmt:
                # reference files are flat fp32 (Parameter.cpp:309-313):
                # restore this model's shape/dtype
                assert arr.size == cur.size, (
                    f"parameter {name!r}: reference file has {arr.size} "
                    f"values, model expects {cur.size}")
                arr = arr.reshape(cur.shape).astype(cur.dtype)
            self.params[name] = arr
        # rebuild pruning masks from the loaded magnitudes (the reference
        # reloads its mask file on --init_model_path too)
        self.params = self.updater.apply_init_hooks(self.params)
        if data.get("opt"):
            # rebuild optimizer state with loaded leaves where shapes match
            tmpl = self.updater.init_state(self.params)
            self.opt_state = _merge_state(tmpl, data["opt"])
        if data.get("net"):
            self.net_state = jax.tree.map(jnp.asarray, data["net"])
        if data.get("rng") is not None:
            # continue the PRNG stream where the saving run left it —
            # resume is then exact for stochastic (dropout) models too
            self.rng = jnp.asarray(data["rng"])
        if self.mesh is not None:
            # restore mesh placement (incl. ZeRO-1 slot sharding) — the
            # loaded host arrays would otherwise train replicated, silently
            # undoing the sharded-optimizer memory saving
            from paddle_tpu.parallel.dp import shard_train_objects
            self.params, self.opt_state = shard_train_objects(
                self.mesh, self.model, self.params, self.opt_state,
                shard_opt=self.opt.shard_optimizer_state,
                zero_stage=self.zero_stage)
        if "pass_id" in data:
            # continue the pass numbering: the snapshot is named after its
            # last completed pass, so the resumed run trains (and next
            # saves) pass N+1 instead of colliding with pass-00000
            self.pass_id = data["pass_id"] + 1


def _host_tree(tree):
    """Device -> host copy that works for arrays spanning non-addressable
    devices (multi-host ZeRO-1 slot shards): gather those across processes;
    plain device_get for everything else."""
    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))
    return jax.tree.map(fetch, tree)


def _merge_state(template, loaded):
    if isinstance(template, dict):
        return {k: _merge_state(v, loaded.get(k)) if loaded and k in loaded else v
                for k, v in template.items()}
    if loaded is None:
        return template
    arr = jnp.asarray(loaded)
    return arr if arr.shape == jnp.shape(template) else template


def _batch_size(batch: dict[str, Argument]) -> int:
    for arg in batch.values():
        return int(arg.batch_size)
    return 0


def _fmt(stats: dict) -> str:
    parts = []
    for k, v in stats.items():
        if isinstance(v, float):
            parts.append(f"{k}={v:.5g}")
        elif isinstance(v, (int, np.integer)):
            parts.append(f"{k}={v}")
    return " ".join(parts)
