"""N-device vs 1-device training-equivalence oracle.

The reference asserts that multi-trainer / remote-updater training produces
IDENTICAL final parameters to local training (ref: paddle/trainer/tests/
test_CompareSparse.cpp:133-152, test_TrainerOnePass.cpp:123-291).  This is
the shared implementation behind tests/test_dp_parity.py and the driver's
dryrun_multichip phase 3b — one source of truth for the tolerances.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

LOSS_RTOL, LOSS_ATOL = 2e-4, 1e-6
PARAM_RTOL, PARAM_ATOL = 3e-4, 2e-5


def train_for_parity(config, batches, mesh, seed: int = 1):
    """Train one Trainer over `batches`; return (losses, host params)."""
    from paddle_tpu.trainer.trainer import Trainer

    tr = Trainer(config, seed=seed, mesh=mesh)
    losses = [float(tr.train_one_batch(b)) for b in batches]
    params = {k: np.asarray(jax.device_get(v)) for k, v in tr.params.items()}
    return np.asarray(losses), params


def assert_dp_parity(config, batches, mesh, seed: int = 1,
                     config2: Optional[object] = None) -> None:
    """Train the same config+seed+batches on `mesh` and on one device; the
    loss trajectories and final parameters must match.  `config2` supplies a
    distinct (identically-parsed) config object when the caller's configs
    are not safely reusable across Trainer instances."""
    l1, p1 = train_for_parity(config, batches, None, seed)
    ln, pn = train_for_parity(config2 if config2 is not None else config,
                              batches, mesh, seed)
    assert np.isfinite(l1).all() and np.isfinite(ln).all()
    np.testing.assert_allclose(
        ln, l1, rtol=LOSS_RTOL, atol=LOSS_ATOL,
        err_msg="dp loss trajectory diverged from dp=1")
    assert p1.keys() == pn.keys()
    for name in p1:
        np.testing.assert_allclose(
            pn[name], p1[name], rtol=PARAM_RTOL, atol=PARAM_ATOL,
            err_msg=f"final parameter {name!r} diverged under dp")
