"""Evaluator zoo — streaming metrics over batches.

TPU re-design of the reference's Evaluator framework (ref:
paddle/gserver/evaluators/Evaluator.{h,cpp}:41-1235 — classification_error,
sum, column_sum, auc, precision_recall, pnpair; ChunkEvaluator.cpp;
CTCErrorEvaluator.cpp).  Each evaluator contributes per-batch partial sums
computed *inside the jitted step* (cheap jnp reductions fused into the graph);
the host accumulates partials across batches and finalizes — the analog of the
reference's eval start/finish + merge protocol, without leaving the device
during the hot loop.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.schema import EvaluatorConfig, ModelConfig
from paddle_tpu.parameter.argument import Argument

Array = jax.Array

# type -> (batch_fn(cfg, outputs, feed) -> dict partials,
#          finalize_fn(cfg, accumulated) -> dict of floats)
evaluator_registry: dict[str, tuple[Callable, Callable]] = {}


def register_evaluator(*names):
    def deco(pair):
        for n in names:
            evaluator_registry[n] = pair
        return pair
    return deco


def _get(outputs: dict[str, Argument], name: str) -> Argument:
    return outputs[name].flatten_image()


# -- classification error ---------------------------------------------------

def _cls_err_batch(cfg: EvaluatorConfig, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    pred = out.value
    if pred.shape[-1] == 1:
        err = (pred[..., 0] > cfg.classification_threshold).astype(jnp.float32) \
            != lbl.ids.astype(jnp.float32)
        err = err.astype(jnp.float32)
    else:
        err = (jnp.argmax(pred, axis=-1) != lbl.ids).astype(jnp.float32)
    if out.is_sequence:
        mask = out.mask(jnp.float32)
        return {"err": jnp.sum(err * mask), "n": jnp.sum(mask)}
    return {"err": jnp.sum(err), "n": jnp.asarray(err.size, jnp.float32)}


def _cls_err_final(cfg, acc):
    return {"classification_error": acc["err"] / max(acc["n"], 1.0)}


register_evaluator("classification_error")((_cls_err_batch, _cls_err_final))


# -- sums -------------------------------------------------------------------

def _sum_batch(cfg, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    v = out.data.astype(jnp.float32)
    if out.is_sequence:
        mask = out.mask(jnp.float32)
        v = v * (mask[..., None] if v.ndim == 3 else mask)
    return {"sum": jnp.sum(v), "n": jnp.asarray(v.shape[0], jnp.float32)}


def _sum_final(cfg, acc):
    return {"sum": acc["sum"], "mean": acc["sum"] / max(acc["n"], 1.0)}


register_evaluator("sum")((_sum_batch, _sum_final))


def _colsum_batch(cfg, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    v = out.value
    if out.is_sequence:
        v = v * out.mask(jnp.float32)[..., None]
        v = jnp.sum(v, axis=1)
    return {"colsum": jnp.sum(v, axis=0), "n": jnp.asarray(v.shape[0], jnp.float32)}


def _colsum_final(cfg, acc):
    return {"column_sum_mean": acc["colsum"] / max(acc["n"], 1.0)}


register_evaluator("column_sum")((_colsum_batch, _colsum_final))


# -- AUC (histogram method, matching the reference's bucketed AUC) ----------

_AUC_BINS = 1024


def _auc_batch(cfg, outputs, feed):
    """(ref: Evaluator.cpp AucEvaluator — 2 x kBinNum histograms; created
    with colIdx=-1 for 'last-column-auc' (Evaluator.cpp:857-858), so the
    score is always the LAST output column; optional 3rd input = per-sample
    weight)."""
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    p = out.value
    pos_prob = p[..., -1]
    y = lbl.ids.astype(jnp.float32).reshape(pos_prob.shape)
    w = jnp.ones_like(pos_prob)
    if len(cfg.input_layer_names) > 2:
        wt = _get(outputs, cfg.input_layer_names[2])
        w = wt.value.reshape(pos_prob.shape).astype(jnp.float32)
    idx = jnp.clip((pos_prob * _AUC_BINS).astype(jnp.int32), 0, _AUC_BINS - 1)
    pos_hist = jnp.zeros((_AUC_BINS,), jnp.float32).at[idx].add(y * w)
    neg_hist = jnp.zeros((_AUC_BINS,), jnp.float32).at[idx].add((1.0 - y) * w)
    return {"pos": pos_hist, "neg": neg_hist}


def _auc_final(cfg, acc):
    pos, neg = np.asarray(acc["pos"]), np.asarray(acc["neg"])
    # integrate from the high-score end (ref: AucEvaluator::calcAuc)
    tp = np.cumsum(pos[::-1])
    fp = np.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    if tot_pos == 0 or tot_neg == 0:
        return {"auc": 0.0}
    tpr = np.concatenate([[0.0], tp / tot_pos])
    fpr = np.concatenate([[0.0], fp / tot_neg])
    auc = float(np.trapezoid(tpr, fpr))
    return {"auc": auc}


# "auc" is a convenience alias: the reference registers ONLY
# "last-column-auc" (= AucEvaluator(-1), Evaluator.cpp:857; the DSL's
# auc_evaluator emits that type too), so last-column scoring IS the
# reference behavior — for the common 2-column softmax output it is
# column 1, the positive class
register_evaluator("auc", "last-column-auc")((_auc_batch, _auc_final))


# -- precision / recall -----------------------------------------------------

def _pr_batch(cfg, outputs, feed):
    """(ref: PrecisionRecallEvaluator) — binary or per-class counts."""
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    p = out.value
    C = p.shape[-1]
    pred = jnp.argmax(p, axis=-1) if C > 1 else (
        p[..., 0] > cfg.classification_threshold).astype(jnp.int32)
    y = lbl.ids.reshape(pred.shape)
    nC = max(C, 2)
    onehot_p = jax.nn.one_hot(pred, nC)
    onehot_y = jax.nn.one_hot(y, nC)
    tp = jnp.sum(onehot_p * onehot_y, axis=tuple(range(onehot_p.ndim - 1)))
    fp = jnp.sum(onehot_p * (1 - onehot_y), axis=tuple(range(onehot_p.ndim - 1)))
    fn = jnp.sum((1 - onehot_p) * onehot_y, axis=tuple(range(onehot_p.ndim - 1)))
    return {"tp": tp, "fp": fp, "fn": fn}


def _pr_final(cfg, acc):
    tp, fp, fn = (np.asarray(acc[k]) for k in ("tp", "fp", "fn"))
    if cfg.positive_label >= 0:
        tp, fp, fn = tp[cfg.positive_label], fp[cfg.positive_label], fn[cfg.positive_label]
        prec = tp / max(tp + fp, 1.0)
        rec = tp / max(tp + fn, 1.0)
    else:
        prec = float(np.mean(tp / np.maximum(tp + fp, 1.0)))
        rec = float(np.mean(tp / np.maximum(tp + fn, 1.0)))
    f1 = 2 * prec * rec / max(prec + rec, 1e-8)
    return {"precision": float(prec), "recall": float(rec), "F1-score": float(f1)}


register_evaluator("precision_recall")((_pr_batch, _pr_final))


# ---------------------------------------------------------------------------
# Host evaluators — metrics with inherently sequential algorithms (segment
# matching, sorting, DP edit distance).  The reference runs these on CPU too
# (ref: ChunkEvaluator.cpp evalImp CHECK(!useGpu); Evaluator.cpp RankAuc
# "does not support GPU"); here they consume numpy copies of just the layers
# they need, fetched once per batch outside the jitted step.
#
# registry: type -> (new_state_fn() -> state,
#                    batch_fn(cfg, args: list[Argument(np)], state) -> None,
#                    finalize_fn(cfg, state) -> dict)
# ---------------------------------------------------------------------------

host_evaluator_registry: dict[str, tuple[Callable, Callable, Callable]] = {}


def register_host_evaluator(*names):
    def deco(triple):
        for n in names:
            host_evaluator_registry[n] = triple
        return triple
    return deco


def _np_arg(arg: Argument) -> Argument:
    """Device → host copy of one Argument."""
    return jax.tree.map(np.asarray, arg)


def _seq_rows(arg: Argument):
    """Yield (row ids/values, length) per sequence of a padded Argument.
    Non-sequence args are treated as length-1 sequences per sample."""
    lengths = np.asarray(arg.lengths) if arg.lengths is not None else None
    data = np.asarray(arg.data)
    B = data.shape[0]
    for b in range(B):
        if lengths is not None:
            L = int(lengths[b])
            yield data[b, :L], L
        elif data.ndim >= 2:
            yield data[b], data.shape[1]
        else:
            yield data[b:b + 1], 1


# -- chunk (NER F1) ---------------------------------------------------------

_CHUNK_SCHEMES = {
    # scheme -> (num_tag_types, begin, inside, end, single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_segments(labels: np.ndarray, scheme: str, num_chunk_types: int):
    """Extract (begin, end, type) segments
    (ref: ChunkEvaluator::getSegments/isChunkBegin/isChunkEnd)."""
    n_tag, t_begin, t_inside, t_end, t_single = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types
    segments = []
    in_chunk = False
    chunk_start = 0
    tag, typ = -1, other

    def is_end(ptag, ptyp, tag, typ):
        if ptyp == other:
            return False
        if typ == other or typ != ptyp:
            return True
        if ptag in (t_begin, t_inside):
            return tag in (t_begin, t_single)
        return ptag in (t_end, t_single)

    def is_begin(ptag, ptyp, tag, typ):
        if ptyp == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptyp:
            return True
        if tag == t_begin or tag == t_single:
            return True
        if tag in (t_inside, t_end):
            return ptag in (t_end, t_single)
        return False

    for i, lab in enumerate(labels):
        ptag, ptyp = tag, typ
        tag = int(lab) % n_tag
        typ = int(lab) // n_tag
        if in_chunk and is_end(ptag, ptyp, tag, typ):
            segments.append((chunk_start, i - 1, ptyp))
            in_chunk = False
        if is_begin(ptag, ptyp, tag, typ):
            chunk_start = i
            in_chunk = True
    if in_chunk:
        segments.append((chunk_start, len(labels) - 1, typ))
    return segments


def _chunk_state():
    return {"label_segs": 0, "out_segs": 0, "correct": 0}


def _chunk_batch(cfg, args, state):
    out, lbl = args[0], args[1]
    excluded = set(cfg.excluded_chunk_types or [])
    for (o, _), (l, _) in zip(_seq_rows(out), _seq_rows(lbl)):
        segs_o = _chunk_segments(o.reshape(-1), cfg.chunk_scheme, cfg.num_chunk_types)
        segs_l = _chunk_segments(l.reshape(-1), cfg.chunk_scheme, cfg.num_chunk_types)
        if excluded:
            segs_o = [s for s in segs_o if s[2] not in excluded]
            segs_l = [s for s in segs_l if s[2] not in excluded]
        state["correct"] += len(set(segs_o) & set(segs_l))
        state["out_segs"] += len(segs_o)
        state["label_segs"] += len(segs_l)


def _chunk_final(cfg, state):
    prec = state["correct"] / max(state["out_segs"], 1)
    rec = state["correct"] / max(state["label_segs"], 1)
    f1 = 0.0 if not state["correct"] else 2 * prec * rec / (prec + rec)
    return {"chunk_f1": f1, "true_chunks": state["label_segs"],
            "result_chunks": state["out_segs"], "correct_chunks": state["correct"]}


register_host_evaluator("chunk")((_chunk_state, _chunk_batch, _chunk_final))


# -- seq_classification_error ----------------------------------------------

def _seqcls_state():
    return {"err": 0, "n": 0}


def _seqcls_batch(cfg, args, state):
    """A sequence counts as one error if ANY frame is misclassified
    (ref: SequenceClassificationErrorEvaluator::evalImp)."""
    out, lbl = args[0], args[1]
    pred = np.asarray(out.value)
    if pred.shape[-1] == 1:
        frame_pred = (pred[..., 0] > cfg.classification_threshold).astype(np.int64)
    else:
        frame_pred = np.argmax(pred, axis=-1)
    labels = np.asarray(lbl.ids).reshape(frame_pred.shape)
    lengths = np.asarray(out.lengths) if out.lengths is not None else None
    for b in range(frame_pred.shape[0]):
        L = int(lengths[b]) if lengths is not None else frame_pred.shape[1]
        state["err"] += int(np.any(frame_pred[b, :L] != labels[b, :L]))
        state["n"] += 1


def _seqcls_final(cfg, state):
    return {"seq_classification_error": state["err"] / max(state["n"], 1)}


register_host_evaluator("seq_classification_error")(
    (_seqcls_state, _seqcls_batch, _seqcls_final))


# -- ctc_edit_distance ------------------------------------------------------

def _ctc_collapse(path, blank):
    """Collapse repeats then drop blanks (ref: CTCErrorEvaluator::path2String)."""
    out, prev = [], -1
    for lab in path:
        lab = int(lab)
        if lab != blank and (not out or lab != out[-1] or prev == blank):
            out.append(lab)
        prev = lab
    return out


def _edit_distance(a, b):
    n, m = len(a), len(b)
    if n == 0:
        return m
    if m == 0:
        return n
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        for j in range(1, m + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        prev = cur
    return prev[m]


def _ctc_state():
    return {"dist": 0.0, "len": 0, "seq_err": 0, "n": 0}


def _ctc_batch(cfg, args, state):
    """Best-path decode + edit distance vs label
    (ref: CTCErrorEvaluator::bestLabelSeq/stringAlignment)."""
    out, lbl = args[0], args[1]
    acts = np.asarray(out.value)          # [B, T, C]; blank = C-1
    blank = acts.shape[-1] - 1
    out_lens = np.asarray(out.lengths) if out.lengths is not None else None
    for b, (lab_row, _) in enumerate(_seq_rows(lbl)):
        T = int(out_lens[b]) if out_lens is not None else acts.shape[1]
        path = np.argmax(acts[b, :T], axis=-1)
        rec = _ctc_collapse(path, blank)
        gt = [int(x) for x in np.asarray(lab_row).reshape(-1)]
        d = _edit_distance(gt, rec)
        state["dist"] += d
        state["len"] += len(gt)
        state["seq_err"] += int(d != 0)
        state["n"] += 1


def _ctc_final(cfg, state):
    return {"ctc_edit_distance": state["dist"] / max(state["n"], 1),
            "character_error_rate": state["dist"] / max(state["len"], 1),
            "sequence_error_rate": state["seq_err"] / max(state["n"], 1)}


register_host_evaluator("ctc_edit_distance")((_ctc_state, _ctc_batch, _ctc_final))


# -- pnpair -----------------------------------------------------------------

def _pnpair_state():
    return {"records": []}


def _pnpair_batch(cfg, args, state):
    """Collect (score, label, queryid, weight) records
    (ref: PnpairEvaluator::evalImp — score is the LAST output column)."""
    out, lbl, info = args[0], args[1], args[2]
    weight = args[3] if len(args) > 3 else None
    scores = np.asarray(out.value).reshape(out.value.shape[0], -1)[:, -1]
    labels = np.asarray(lbl.ids).reshape(-1)
    infos = np.asarray(info.ids).reshape(-1)
    ws = (np.asarray(weight.data).reshape(-1) if weight is not None
          else np.ones_like(scores))
    state["records"].extend(zip(scores.tolist(), labels.tolist(),
                                infos.tolist(), ws.tolist()))


def _pnpair_final(cfg, state):
    """Count concordant/discordant pairs within each query group
    (ref: PnpairEvaluator::calc/stat)."""
    recs = sorted(state["records"], key=lambda r: r[2])
    pos = neg = spe = 0.0
    i = 0
    while i < len(recs):
        j = i
        while j < len(recs) and recs[j][2] == recs[i][2]:
            j += 1
        group = recs[i:j]
        for a in range(len(group)):
            for b in range(a + 1, len(group)):
                sa, la, _, wa = group[a]
                sb, lb, _, wb = group[b]
                if la == lb:
                    continue
                w = (wa + wb) / 2.0
                if (sa > sb and la > lb) or (sa < sb and la < lb):
                    pos += w
                elif (sa > sb and la < lb) or (sa < sb and la > lb):
                    neg += w
                else:
                    spe += w
        i = j
    return {"pos_pairs": pos, "neg_pairs": neg, "special_pairs": spe,
            "pnpair": pos / max(neg, 1e-8)}


register_host_evaluator("pnpair")((_pnpair_state, _pnpair_batch, _pnpair_final))


# -- rankauc ----------------------------------------------------------------

def _rankauc_state():
    return {"auc_sum": 0.0, "n": 0}


def _rank_auc_one(scores, clicks, pvs):
    """(ref: RankAucEvaluator::calcRankAuc) — tie-aware trapezoid."""
    order = np.argsort(-scores, kind="stable")
    auc = click_sum = old_click_sum = 0.0
    no_click = no_click_sum = 0.0
    last = scores[order[0]] + 1.0
    for idx in order:
        if scores[idx] != last:
            auc += (click_sum + old_click_sum) * no_click / 2.0
            old_click_sum = click_sum
            no_click = 0.0
            last = scores[idx]
        no_click += pvs[idx] - clicks[idx]
        no_click_sum += no_click
        click_sum += clicks[idx]
    auc += (click_sum + old_click_sum) * no_click / 2.0
    denom = click_sum * no_click_sum
    return 0.0 if denom == 0.0 else auc / denom


def _rankauc_batch(cfg, args, state):
    out, click = args[0], args[1]
    pv = args[2] if len(args) > 2 else None
    scores = np.asarray(out.value)
    clicks = np.asarray(click.data, np.float64).reshape(scores.shape[0], -1)
    pvs = (np.asarray(pv.data, np.float64).reshape(scores.shape[0], -1)
           if pv is not None else np.ones_like(clicks))
    lengths = np.asarray(out.lengths) if out.lengths is not None else None
    for b in range(scores.shape[0]):
        L = int(lengths[b]) if lengths is not None else scores.shape[1] if scores.ndim > 2 else clicks.shape[1]
        s = scores[b].reshape(-1)[:L]
        state["auc_sum"] += _rank_auc_one(s, clicks[b].reshape(-1)[:L],
                                          pvs[b].reshape(-1)[:L])
        state["n"] += 1


def _rankauc_final(cfg, state):
    return {"rankauc": state["auc_sum"] / max(state["n"], 1)}


register_host_evaluator("rankauc")((_rankauc_state, _rankauc_batch, _rankauc_final))


# -- printers ---------------------------------------------------------------
# (ref: Evaluator.cpp value_printer/max_id_printer/seq_text_printer/
#  classification_error_printer — side-effect evaluators that log samples)

def _printer_state():
    return {"printed": 0}


def _make_printer(fmt_fn, limit=5):
    def batch(cfg, args, state):
        if state["printed"] >= limit:
            return
        from paddle_tpu.utils import get_logger
        log = get_logger("evaluator")
        log.info("[%s] %s", cfg.name, fmt_fn(cfg, args))
        state["printed"] += 1

    def final(cfg, state):
        return {}
    return (_printer_state, batch, final)


register_host_evaluator("value_printer")(_make_printer(
    lambda cfg, args: " ".join(np.array2string(np.asarray(a.data), threshold=20)
                               for a in args)))
register_host_evaluator("max_id_printer")(_make_printer(
    lambda cfg, args: np.array2string(
        np.argmax(np.asarray(args[0].value), axis=-1), threshold=50)))
# seq_text_printer: decodes id sequences (via dict_file when given) and either
# appends them to result_file or logs them
# (ref: Evaluator.cpp SequenceTextPrinter — result_file/dict_file/delimited).

def _seqtext_state():
    return {"printed": 0, "dict": None, "file_reset": False}


def _seqtext_batch(cfg, args, state):
    rows = []
    if state["dict"] is None and cfg.dict_file:
        with open(cfg.dict_file) as f:
            state["dict"] = [ln.rstrip("\n") for ln in f]
    vocab = state["dict"]
    sep = " " if cfg.delimited else ""
    for row, _ in _seq_rows(args[0]):
        toks = [int(x) for x in np.asarray(row).reshape(-1)]
        rows.append(sep.join(vocab[t] if vocab and 0 <= t < len(vocab)
                             else str(t) for t in toks))
    if cfg.result_file:
        mode = "a" if state["file_reset"] else "w"
        state["file_reset"] = True
        with open(cfg.result_file, mode) as f:
            f.write("\n".join(rows) + "\n")
    elif state["printed"] < 5:
        from paddle_tpu.utils import get_logger
        get_logger("evaluator").info("[%s] %s", cfg.name, " | ".join(rows[:8]))
        state["printed"] += 1


register_host_evaluator("seq_text_printer")(
    (_seqtext_state, _seqtext_batch, lambda cfg, state: {}))
def _cls_err_print(cfg, args):
    pred = np.argmax(np.asarray(args[0].value), axis=-1)
    labels = np.asarray(args[1].ids).reshape(pred.shape)
    return np.array2string((pred != labels).astype(np.int32), threshold=50)


register_host_evaluator("classification_error_printer")(
    _make_printer(_cls_err_print))


def _max_frame_print(cfg, args):
    """Per sequence, print the value-maximizing frame and its index
    (ref: Evaluator.cpp MaxFramePrinter — selects each sequence's frame
    with the maximal output value)."""
    a = args[0]
    if a.value is None:
        raise ValueError(
            f"max_frame_printer on {cfg.input_layer_names!r}: probed layer has "
            f"no dense value (ids-only output) — point it at a layer that "
            f"emits values")
    v = np.asarray(a.value)
    if v.ndim == 2:
        v = v[:, None, :]               # [B, 1, D]: non-sequence = 1 frame
    lengths = np.asarray(a.lengths) if a.lengths is not None else None
    lines = []
    for b in range(v.shape[0]):
        L = int(lengths[b]) if lengths is not None else v.shape[1]
        frames = v[b, :max(L, 1)]
        t = int(np.argmax(frames.max(axis=-1)))
        lines.append(f"seq {b}: frame {t} "
                     f"{np.array2string(frames[t], threshold=10)}")
    return "; ".join(lines[:8])


register_host_evaluator("max_frame_printer")(_make_printer(_max_frame_print))

# gradient_printer: prints the probed layer's OUTPUT GRADIENT, delivered by
# the trainer as a __grad__<layer> Argument computed via an additive-zero
# probe (ref: Evaluator.cpp GradientPrinter reads Layer::getOutputGrad() —
# autodiff has no per-layer grad buffers, so the probe recreates them on
# demand for exactly the printed layers).
register_host_evaluator("gradient_printer")(_make_printer(
    lambda cfg, args: " ".join(np.array2string(np.asarray(a.data), threshold=20)
                               for a in args)))


# -- driver -----------------------------------------------------------------

class EvaluatorSet:
    """Accumulates all configured evaluators across batches
    (ref: Evaluator start/eval/finish + printStats protocol)."""

    # validation layer type -> evaluator it hosts (ref: ValidationLayer.cpp
    # AucValidation::init sets type 'last-column-auc', PnpairValidation::init
    # sets 'pnpair'; the layer is a pass-through registered in
    # graph/layers_cost.py)
    _VALIDATION_LAYERS = {"auc-validation": "last-column-auc",
                          "pnpair-validation": "pnpair"}

    def __init__(self, model: ModelConfig):
        evals = list(model.evaluators)
        for layer in model.layers:
            ev_type = self._VALIDATION_LAYERS.get(layer.type)
            if ev_type is not None:
                evals.append(EvaluatorConfig(
                    name=layer.name, type=ev_type,
                    input_layer_names=[i.input_layer_name
                                       for i in layer.inputs]))
        self.configs = [e for e in evals if e.type in evaluator_registry]
        self.host_configs = [e for e in evals
                             if e.type in host_evaluator_registry]
        # True = silently skip evaluators whose input layers are absent
        # from the step outputs (the Trainer sets this under pipeline
        # parallelism, where stage-internal activations never surface);
        # False (default) = a missing layer is a loud config error
        self.allow_missing = False

    @staticmethod
    def _host_keys(cfg: EvaluatorConfig) -> list[str]:
        """Output-dict keys one host evaluator consumes: layer names, or the
        trainer-provided __grad__<layer> probe results for gradient_printer."""
        if cfg.type == "gradient_printer":
            return ["__grad__" + n for n in cfg.input_layer_names]
        return list(cfg.input_layer_names)

    @property
    def host_layer_names(self) -> list[str]:
        """Keys host evaluators need fetched from the step outputs each batch."""
        names: list[str] = []
        for cfg in self.host_configs:
            for n in self._host_keys(cfg):
                if n not in names:
                    names.append(n)
        return names

    def new_host_state(self) -> dict:
        return {cfg.name: host_evaluator_registry[cfg.type][0]()
                for cfg in self.host_configs}

    def host_update(self, host_state: dict, outputs: dict[str, Argument]) -> None:
        """Feed one batch's (host-resident) outputs to every host evaluator."""
        cache = {n: _np_arg(outputs[n]) for n in self.host_layer_names
                 if n in outputs}
        for cfg in self.host_configs:
            keys = self._host_keys(cfg)
            missing = [n for n in keys if n not in cache]
            if missing:
                if self.allow_missing:
                    continue   # stage-internal under pipeline parallelism
                raise KeyError(
                    f"host evaluator {cfg.name!r} ({cfg.type}) references "
                    f"{missing} absent from the step outputs")
            args = [cache[n] for n in keys]
            host_evaluator_registry[cfg.type][1](cfg, args, host_state[cfg.name])

    def finalize_host(self, host_state: dict) -> dict[str, float]:
        out: dict[str, float] = {}
        many = len(self.host_configs) + len(self.configs) > 1
        for cfg in self.host_configs:
            res = host_evaluator_registry[cfg.type][2](cfg, host_state[cfg.name])
            for k, v in res.items():
                out[f"{cfg.name}.{k}" if many else k] = float(v)
        return out

    def batch_partials(self, outputs, feed) -> dict[str, dict]:
        """Called inside jit: returns {evaluator_name: partials}.

        When `allow_missing` is set (the Trainer sets it under pipeline
        parallelism, where intermediate activations never materialize
        outside their stage), an evaluator whose input layers are
        unavailable is skipped; on the plain path a missing layer is a
        config error and fails loudly."""
        res = {}
        for cfg in self.configs:
            missing = [n for n in cfg.input_layer_names
                       if n not in outputs and n not in feed]
            if missing:
                if self.allow_missing:
                    continue
                raise KeyError(
                    f"evaluator {cfg.name!r} ({cfg.type}) references "
                    f"layer(s) {missing} absent from the forward outputs")
            batch_fn, _ = evaluator_registry[cfg.type]
            res[cfg.name] = batch_fn(cfg, outputs, feed)
        return res

    def new_accumulator(self) -> dict:
        return {}

    def accumulate_stacked(self, acc: dict, stacked: dict, n: int) -> dict:
        """Fold a fused dispatch's per-step partials (each leaf stacked
        [n, ...] along a leading step axis by the k-step lax.scan) into the
        accumulator — ONE device fetch for the whole group, then the same
        per-step float64 additions, in the same order, as n separate
        `accumulate` calls: the fused path's evaluator results stay
        bit-identical to the per-batch loop's."""
        if not stacked:
            return acc
        host = jax.tree.map(np.asarray, jax.device_get(stacked))
        for i in range(n):
            acc = self.accumulate(
                acc, jax.tree.map(lambda a: a[i], host))
        return acc

    def accumulate(self, acc: dict, partials: dict) -> dict:
        for name, parts in partials.items():
            if name not in acc:
                acc[name] = {k: np.asarray(v, np.float64) for k, v in parts.items()}
            else:
                for k, v in parts.items():
                    acc[name][k] = acc[name][k] + np.asarray(v, np.float64)
        return acc

    def finalize(self, acc: dict) -> dict[str, float]:
        out: dict[str, float] = {}
        many = len(self.configs) + len(self.host_configs) > 1
        for cfg in self.configs:
            if cfg.name not in acc:
                continue
            _, fin = evaluator_registry[cfg.type]
            for k, v in fin(cfg, acc[cfg.name]).items():
                out[f"{cfg.name}.{k}" if many else k] = float(
                    np.asarray(v).reshape(-1)[0]) if np.ndim(v) == 0 or np.size(v) == 1 \
                    else v
        return out
