"""Evaluator zoo — streaming metrics over batches.

TPU re-design of the reference's Evaluator framework (ref:
paddle/gserver/evaluators/Evaluator.{h,cpp}:41-1235 — classification_error,
sum, column_sum, auc, precision_recall, pnpair; ChunkEvaluator.cpp;
CTCErrorEvaluator.cpp).  Each evaluator contributes per-batch partial sums
computed *inside the jitted step* (cheap jnp reductions fused into the graph);
the host accumulates partials across batches and finalizes — the analog of the
reference's eval start/finish + merge protocol, without leaving the device
during the hot loop.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.schema import EvaluatorConfig, ModelConfig
from paddle_tpu.parameter.argument import Argument

Array = jax.Array

# type -> (batch_fn(cfg, outputs, feed) -> dict partials,
#          finalize_fn(cfg, accumulated) -> dict of floats)
evaluator_registry: dict[str, tuple[Callable, Callable]] = {}


def register_evaluator(*names):
    def deco(pair):
        for n in names:
            evaluator_registry[n] = pair
        return pair
    return deco


def _get(outputs: dict[str, Argument], name: str) -> Argument:
    return outputs[name]


# -- classification error ---------------------------------------------------

def _cls_err_batch(cfg: EvaluatorConfig, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    pred = out.value
    if pred.shape[-1] == 1:
        err = (pred[..., 0] > cfg.classification_threshold).astype(jnp.float32) \
            != lbl.ids.astype(jnp.float32)
        err = err.astype(jnp.float32)
    else:
        err = (jnp.argmax(pred, axis=-1) != lbl.ids).astype(jnp.float32)
    if out.is_sequence:
        mask = out.mask(jnp.float32)
        return {"err": jnp.sum(err * mask), "n": jnp.sum(mask)}
    return {"err": jnp.sum(err), "n": jnp.asarray(err.size, jnp.float32)}


def _cls_err_final(cfg, acc):
    return {"classification_error": acc["err"] / max(acc["n"], 1.0)}


register_evaluator("classification_error")((_cls_err_batch, _cls_err_final))


# -- sums -------------------------------------------------------------------

def _sum_batch(cfg, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    v = out.data.astype(jnp.float32)
    if out.is_sequence:
        mask = out.mask(jnp.float32)
        v = v * (mask[..., None] if v.ndim == 3 else mask)
    return {"sum": jnp.sum(v), "n": jnp.asarray(v.shape[0], jnp.float32)}


def _sum_final(cfg, acc):
    return {"sum": acc["sum"], "mean": acc["sum"] / max(acc["n"], 1.0)}


register_evaluator("sum")((_sum_batch, _sum_final))


def _colsum_batch(cfg, outputs, feed):
    out = _get(outputs, cfg.input_layer_names[0])
    v = out.value
    if out.is_sequence:
        v = v * out.mask(jnp.float32)[..., None]
        v = jnp.sum(v, axis=1)
    return {"colsum": jnp.sum(v, axis=0), "n": jnp.asarray(v.shape[0], jnp.float32)}


def _colsum_final(cfg, acc):
    return {"column_sum_mean": acc["colsum"] / max(acc["n"], 1.0)}


register_evaluator("column_sum")((_colsum_batch, _colsum_final))


# -- AUC (histogram method, matching the reference's bucketed AUC) ----------

_AUC_BINS = 1024


def _auc_batch(cfg, outputs, feed):
    """(ref: Evaluator.cpp AucEvaluator — 2 x kBinNum histograms)."""
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    p = out.value
    pos_prob = p[..., 1] if p.shape[-1] == 2 else p[..., 0]
    y = lbl.ids.astype(jnp.float32).reshape(pos_prob.shape)
    idx = jnp.clip((pos_prob * _AUC_BINS).astype(jnp.int32), 0, _AUC_BINS - 1)
    pos_hist = jnp.zeros((_AUC_BINS,), jnp.float32).at[idx].add(y)
    neg_hist = jnp.zeros((_AUC_BINS,), jnp.float32).at[idx].add(1.0 - y)
    return {"pos": pos_hist, "neg": neg_hist}


def _auc_final(cfg, acc):
    pos, neg = np.asarray(acc["pos"]), np.asarray(acc["neg"])
    # integrate from the high-score end (ref: AucEvaluator::calcAuc)
    tp = np.cumsum(pos[::-1])
    fp = np.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    if tot_pos == 0 or tot_neg == 0:
        return {"auc": 0.0}
    tpr = np.concatenate([[0.0], tp / tot_pos])
    fpr = np.concatenate([[0.0], fp / tot_neg])
    auc = float(np.trapezoid(tpr, fpr))
    return {"auc": auc}


register_evaluator("auc", "last-column-auc")((_auc_batch, _auc_final))


# -- precision / recall -----------------------------------------------------

def _pr_batch(cfg, outputs, feed):
    """(ref: PrecisionRecallEvaluator) — binary or per-class counts."""
    out = _get(outputs, cfg.input_layer_names[0])
    lbl = _get(outputs, cfg.input_layer_names[1])
    p = out.value
    C = p.shape[-1]
    pred = jnp.argmax(p, axis=-1) if C > 1 else (
        p[..., 0] > cfg.classification_threshold).astype(jnp.int32)
    y = lbl.ids.reshape(pred.shape)
    nC = max(C, 2)
    onehot_p = jax.nn.one_hot(pred, nC)
    onehot_y = jax.nn.one_hot(y, nC)
    tp = jnp.sum(onehot_p * onehot_y, axis=tuple(range(onehot_p.ndim - 1)))
    fp = jnp.sum(onehot_p * (1 - onehot_y), axis=tuple(range(onehot_p.ndim - 1)))
    fn = jnp.sum((1 - onehot_p) * onehot_y, axis=tuple(range(onehot_p.ndim - 1)))
    return {"tp": tp, "fp": fp, "fn": fn}


def _pr_final(cfg, acc):
    tp, fp, fn = (np.asarray(acc[k]) for k in ("tp", "fp", "fn"))
    if cfg.positive_label >= 0:
        tp, fp, fn = tp[cfg.positive_label], fp[cfg.positive_label], fn[cfg.positive_label]
        prec = tp / max(tp + fp, 1.0)
        rec = tp / max(tp + fn, 1.0)
    else:
        prec = float(np.mean(tp / np.maximum(tp + fp, 1.0)))
        rec = float(np.mean(tp / np.maximum(tp + fn, 1.0)))
    f1 = 2 * prec * rec / max(prec + rec, 1e-8)
    return {"precision": float(prec), "recall": float(rec), "F1-score": float(f1)}


register_evaluator("precision_recall")((_pr_batch, _pr_final))


# -- driver -----------------------------------------------------------------

class EvaluatorSet:
    """Accumulates all configured evaluators across batches
    (ref: Evaluator start/eval/finish + printStats protocol)."""

    def __init__(self, model: ModelConfig):
        self.configs = [e for e in model.evaluators if e.type in evaluator_registry]

    def batch_partials(self, outputs, feed) -> dict[str, dict]:
        """Called inside jit: returns {evaluator_name: partials}."""
        res = {}
        for cfg in self.configs:
            batch_fn, _ = evaluator_registry[cfg.type]
            res[cfg.name] = batch_fn(cfg, outputs, feed)
        return res

    def new_accumulator(self) -> dict:
        return {}

    def accumulate(self, acc: dict, partials: dict) -> dict:
        for name, parts in partials.items():
            if name not in acc:
                acc[name] = {k: np.asarray(v, np.float64) for k, v in parts.items()}
            else:
                for k, v in parts.items():
                    acc[name][k] = acc[name][k] + np.asarray(v, np.float64)
        return acc

    def finalize(self, acc: dict) -> dict[str, float]:
        out: dict[str, float] = {}
        for cfg in self.configs:
            if cfg.name not in acc:
                continue
            _, fin = evaluator_registry[cfg.type]
            for k, v in fin(cfg, acc[cfg.name]).items():
                out[f"{cfg.name}.{k}" if len(self.configs) > 1 else k] = float(
                    np.asarray(v).reshape(-1)[0]) if np.ndim(v) == 0 or np.size(v) == 1 \
                    else v
        return out
