"""Device mesh construction.

The TPU replacement for the reference's entire parallel topology
configuration: `--trainer_count` worker threads + `--pservers` host lists
(ref: paddle/trainer/TrainerMain.cpp:47-92, paddle/pserver/LightNetwork.cpp)
collapse into one `jax.sharding.Mesh` whose axes name the parallelism kinds:

  data   — batch sharding (ref: MultiGradientMachine thread DP + pserver DP)
  model  — tensor/parameter sharding (ref: ParallelNeuralNetwork device=N)
  seq    — sequence/context parallelism (ring attention; parallel/context.py)
             — NEW capability, the reference handles long sequences on one
             device only (SURVEY.md §5 long-context)
  pipe   — pipeline parallelism over layer stages (parallel/pipeline.py)
             — the scaled-out analog of ParallelNeuralNetwork's per-layer
             device= placement

All four axes are always present (size 1 when unused) so partition specs
naming any of them stay valid on any mesh.  Collectives ride ICI within a
slice and DCN across slices; multi-host setup is jax.distributed instead of
a pserver fleet.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"

AXIS_ORDER = (DATA_AXIS, SEQ_AXIS, PIPE_AXIS, MODEL_AXIS)


def make_mesh(data: int = 0, model: int = 1, seq: int = 1, pipe: int = 1,
              devices=None) -> Mesh:
    """Build a mesh over (data, seq, pipe, model); data=0 = 'all remaining'.

    Axis order puts `model` innermost (fastest-varying devices = closest ICI
    neighbors — tensor-parallel collectives are the most latency-sensitive)
    and `data` outermost, matching standard TPU practice."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    rest = model * seq * pipe
    if data <= 0:
        assert n % rest == 0, f"{n} devices not divisible by {rest}"
        data = n // rest
    sizes = {DATA_AXIS: data, SEQ_AXIS: seq, PIPE_AXIS: pipe, MODEL_AXIS: model}
    total = data * rest
    assert total == n, f"mesh {sizes} = {total} devices != {n} available"
    # every axis is always present — size-1 axes cost nothing and keep
    # partition specs naming any canonical axis valid on any mesh
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    return Mesh(devs.reshape(shape), AXIS_ORDER)


def axis_size(mesh: Optional[Mesh], axis: str) -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape))[axis]


def model_mesh(n: int, devices=None) -> Optional[Mesh]:
    """A SERVING tensor-parallel mesh: exactly the first `n` devices on the
    `model` axis, every other axis 1 (the `--mesh model=N` flag of
    tools/serve.py / bench_serving).  Unlike make_mesh's data=0 remainder
    rule this never swallows spare devices into a data axis — replicating
    the KV pools over an unused data axis would defeat the per-chip HBM
    win sharding exists for.  n <= 1 returns None (no mesh: the engine
    keeps its single-device path)."""
    n = int(n)
    if n <= 1:
        return None
    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < n:
        raise ValueError(
            f"--mesh model={n} needs {n} devices, have {len(devs)} — on a "
            f"CPU host use XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={n} (set BEFORE jax initializes)")
    return make_mesh(data=1, model=n, seq=1, pipe=1, devices=devs[:n])


def mesh_from_flag(spec: str, devices=None) -> Optional[Mesh]:
    """Parse 'data:8' / 'data:4,model:2' / 'data:2,seq:2,model:2'
    (the --mesh_shape flag)."""
    if not spec:
        return None
    sizes = {"data": 0, "model": 1, "seq": 1, "pipe": 1}
    for part in spec.split(","):
        name, _, num = part.partition(":")
        name = name.strip()
        assert name in sizes, \
            f"unknown mesh axis {name!r}; valid: {sorted(sizes)}"
        sizes[name] = int(num)
    return make_mesh(sizes["data"], sizes["model"], sizes["seq"],
                     sizes["pipe"], devices)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (ref: the pserver fleet + --trainer_id/--pservers
    startup protocol → jax.distributed coordinator)."""
    kwargs = {}
    if coordinator_address:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
