"""Device mesh construction.

The TPU replacement for the reference's entire parallel topology
configuration: `--trainer_count` worker threads + `--pservers` host lists
(ref: paddle/trainer/TrainerMain.cpp:47-92, paddle/pserver/LightNetwork.cpp)
collapse into one `jax.sharding.Mesh` whose axes name the parallelism kinds:

  data   — batch sharding (ref: MultiGradientMachine thread DP + pserver DP)
  model  — tensor/parameter sharding (ref: ParallelNeuralNetwork device=N)

Collectives ride ICI within a slice and DCN across slices; multi-host setup
is jax.distributed instead of a pserver fleet.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: int = 0, model: int = 1, devices=None) -> Mesh:
    """Build a (data, model) mesh; data=0 means 'all remaining devices'."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    if data <= 0:
        assert n % model == 0, f"{n} devices not divisible by model={model}"
        data = n // model
    assert data * model == n, f"mesh {data}x{model} != {n} devices"
    return Mesh(devs.reshape(data, model), (DATA_AXIS, MODEL_AXIS))


def mesh_from_flag(spec: str, devices=None) -> Optional[Mesh]:
    """Parse 'data:8' / 'data:4,model:2' (the --mesh_shape flag)."""
    if not spec:
        return None
    sizes = {"data": 0, "model": 1}
    for part in spec.split(","):
        name, _, num = part.partition(":")
        sizes[name.strip()] = int(num)
    return make_mesh(sizes["data"], sizes["model"], devices)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (ref: the pserver fleet + --trainer_id/--pservers
    startup protocol → jax.distributed coordinator)."""
    kwargs = {}
    if coordinator_address:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
