"""Mixture-of-experts with expert parallelism.

NEW capability beyond the reference (2016-era PaddlePaddle predates MoE; its
closest relative is per-layer device placement, ref: paddle/gserver/
gradientmachines/ParallelNeuralNetwork.h:35-70).  Completes the framework's
parallelism portfolio (dp/tp/sp/pp + ep).

Design: Switch/GShard-style capacity-based routing expressed as dense
einsums — the idiomatic XLA formulation.  Tokens are routed top-k to E
experts with a per-expert capacity C; routing builds a dispatch one-hot
[B, E, C] and a probability-weighted combine tensor.  Expert FFN weights are
stacked [E, ...] and sharded over the `model` mesh axis (expert parallelism);
with tokens sharded over `data`, XLA lowers the dispatch/combine einsums to
the all-to-all exchanges a hand-written MoE would issue — riding ICI, fused
and overlapped by the compiler.

Tokens over capacity are dropped (their combine weight is zero — the
standard Switch trade; raise capacity_factor to avoid drops).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def moe_routing(
    gate_logits: Array,        # [B, E]
    top_k: int,
    capacity: int,
    valid: Optional[Array] = None,   # [B] bool; padding tokens never routed
) -> tuple[Array, Array, Array]:
    """Build (dispatch [B,E,C] one-hot, combine [B,E,C] prob-weighted,
    aux_loss scalar) from router logits.

    aux_loss is the load-balancing loss of Shazeer et al.: E * sum_e
    (fraction of tokens routed to e) * (mean router prob of e), computed
    over valid tokens only.
    """
    B, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    vmask = jnp.ones((B,), jnp.float32) if valid is None \
        else valid.astype(jnp.float32)

    dispatch = jnp.zeros((B, E, capacity), jnp.float32)
    combine = jnp.zeros((B, E, capacity), jnp.float32)
    remaining = probs
    # occupancy carried across the k rounds so capacity is shared
    fill = jnp.zeros((E,), jnp.int32)
    total_gate = jnp.zeros((B,), jnp.float32)
    picks = []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                  # [B]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # [B, E]
        onehot = onehot * vmask[:, None]      # pads take no expert slot
        # a round with no probability mass left (softmax underflow, or
        # top_k > num_experts) must not dispatch: argmax would re-pick
        # expert 0 with zero gate weight and burn one of its capacity slots
        onehot = onehot * (jnp.sum(remaining, -1, keepdims=True) > 0)
        picks.append(onehot)
        gate = jnp.sum(probs * onehot, axis=-1)               # [B]
        # position of each token within its expert's buffer this round
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) + fill[None, :].astype(jnp.float32)
        pos_tok = jnp.sum(pos * onehot, axis=-1)              # [B]
        keep = pos_tok < capacity
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                              dtype=jnp.float32)              # [B, C]
        d = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        total_gate = total_gate + gate * keep
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)                # mask the pick

    if top_k > 1:
        # normalize combine weights over the k selected experts
        combine = combine / jnp.maximum(total_gate, 1e-9)[:, None, None]
    # top_k == 1 keeps the raw gate prob as the output scale (Switch
    # Transformer): normalizing would cancel gate/gate and leave the router
    # with zero gradient from the main loss

    # load-balancing aux loss uses the FIRST-choice assignment, valid only
    n_valid = jnp.maximum(jnp.sum(vmask), 1.0)
    frac_tokens = jnp.sum(picks[0], axis=0) / n_valid         # [E]
    mean_prob = jnp.sum(probs * vmask[:, None], axis=0) / n_valid
    aux_loss = E * jnp.sum(frac_tokens * mean_prob)
    return dispatch, combine, aux_loss


def moe_ffn(
    x: Array,                  # [B, D] tokens
    w_router: Array,           # [D, E]
    w1: Array,                 # [E, D, H]  (shard on the model axis: ['model'])
    b1: Array,                 # [E, H]
    w2: Array,                 # [E, H, D_out]
    b2: Array,                 # [E, D_out]
    top_k: int = 2,
    capacity_factor: float = 1.25,
    activation=jax.nn.relu,
    valid: Optional[Array] = None,   # [B] bool; padding tokens never routed
) -> tuple[Array, Array]:
    """Expert-parallel MoE FFN block; returns (y [B, D_out], aux_loss).

    The einsum chain is the GShard formulation: dispatch gathers each
    expert's token buffer, experts run batched (vmapped by the leading E
    dim), combine scatters weighted outputs back to token order.
    """
    B, D = x.shape
    E = w1.shape[0]
    capacity = max(1, int(top_k * B * capacity_factor / E))
    logits = x.astype(jnp.float32) @ w_router.astype(jnp.float32)
    dispatch, combine, aux = moe_routing(logits, top_k, capacity, valid=valid)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    expert_in = jnp.einsum("bd,bec->ecd", x, dispatch)        # [E, C, D]
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :])
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    y = jnp.einsum("ecd,bec->bd", expert_out, combine)        # [B, D_out]
    return y, aux


def expert_partition_specs(n_leading_dims: int = 3) -> list:
    """Partition spec stubs for stacked expert params: expert dim over the
    `model` axis (['model', None, ...])."""
    return ["model"] + [None] * (n_leading_dims - 1)
