"""Config-driven pipeline parallelism: `device=N` layer annotations.

The reference's model parallelism runs on ANY config via a per-layer device
attribute — layers annotated `device=N` execute on device N's compute
thread, with explicit inter-device output copies (ref: paddle/gserver/
gradientmachines/ParallelNeuralNetwork.{h,cpp}:35-70, Layer.h:112
copyOutputToOtherDevice).  This module is the TPU-native analog: the same
`device=N` annotation (DSL: `layer_attr=ExtraLayerAttribute(device=N)`,
parsed into LayerConfig.device) partitions the layer graph into pipeline
stages laid out over the `pipe` mesh axis, and the batch flows through them
GPipe-style as microbatches on a ring of `lax.ppermute` hops.

Re-design notes (vs parallel/pipeline.py's uniform-stage library path):
- stages are HETEROGENEOUS: inside the shard_map each device selects its
  own stage's computation with `lax.switch` on its pipe-axis index, so one
  SPMD program hosts S different stage bodies (conv stack on device 0, fc
  head on device S-1, ...).
- stage interfaces are derived from the config, not assumed uniform: all
  activations crossing a stage boundary (including skip connections, which
  are carried through intermediate stages) are flattened and packed into
  one [mb, W_b] carrier per boundary; W_b is static per config, and the
  ring carrier is padded to max_b W_b — pad/unpad is exact, never lossy.
- sequence metadata (lengths / sub_lengths) rides in the carrier as extra
  float32 columns (exact for lengths < 2^24); the carrier itself is
  float32 so metadata and bf16 activations coexist losslessly.
- feeds (data layers) are NOT pipelined: the batch is sharded over `data`
  and replicated over `pipe`, so stage s just slices microbatch t-s
  locally — labels reach the last stage without touching the ring.
- backward is `jax.grad` through scan+switch+ppermute: the ppermute
  transpose is the reverse-direction hop, reproducing the classic
  backward pipeline schedule that the reference hand-builds with
  inter-thread gradient copies.

Not supported under pp (asserted with clear errors): MUTABLE layer state
(training-mode batch-norm moving stats, prev_batch_state recurrences) and
generation; evaluators whose input layers live inside the pipeline are
skipped at the Trainer level.  Frozen BN (use_global_stats=True) IS
supported, fresh-init or fine-tuning from loaded moving stats — the
loaded stats are constants of the stage bodies (_check_frozen_state).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.config.schema import LayerConfig, ModelConfig, SubModelConfig
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import ForwardContext, TRAIN
from paddle_tpu.graph.registry import get_layer_fn
from paddle_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, axis_size
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.utils.jax_compat import shard_map

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class _CrossSpec:
    """Static layout of one Argument inside a boundary carrier."""
    name: str
    value_shape: tuple          # per-microbatch [mb, ...] value shape
    value_dtype: Any
    has_lengths: bool
    sub_shape: Optional[tuple]  # [mb, S] sub_lengths shape or None

    @property
    def width(self) -> int:
        w = int(np.prod(self.value_shape[1:])) if len(self.value_shape) > 1 else 1
        if self.has_lengths:
            w += 1
        if self.sub_shape is not None:
            w += int(np.prod(self.sub_shape[1:]))
        return w


def split_stages(model: ModelConfig, n_stages: int):
    """Partition the execution plan into `n_stages` contiguous stages from
    the per-layer `device` annotations.  Unannotated layers inherit the
    stage of the previous plan item (the reference's implicit placement);
    stage ids must be non-decreasing in config (topological) order."""
    ex = GraphExecutor(model)
    stages: list[list[tuple]] = [[] for _ in range(n_stages)]
    cur = 0
    for kind, item in ex._plan:
        if kind == "layer":
            dev = item.device
        else:
            sm: SubModelConfig = item
            devs = {ex.layer_map[ln].device for ln in sm.layer_names
                    if ln in ex.layer_map}
            devs.discard(-1)
            assert len(devs) <= 1, (
                f"recurrent group {sm.name!r} spans devices {sorted(devs)} — "
                f"a pipeline stage cannot split a scan; annotate all its "
                f"layers with one device")
            dev = devs.pop() if devs else -1
        if dev >= 0:
            assert dev >= cur, (
                f"layer {getattr(item, 'name', item)!r} is annotated "
                f"device={dev} but a later-executing layer already sits on "
                f"stage {cur} — stages must be contiguous in config order")
            assert dev < n_stages, (
                f"device={dev} exceeds the pipe axis ({n_stages} stages)")
            cur = dev
        stages[cur].append((kind, item))
    assert all(stages), (
        f"every pipeline stage needs at least one layer; got sizes "
        f"{[len(s) for s in stages]} for {n_stages} stages — annotate "
        f"layers with device=0..{n_stages - 1}")
    return ex, stages


def _stage_io(model: ModelConfig, stages):
    """Per-stage (produced, consumed-external) name sets and the boundary
    payloads: payload[b] = names produced in stages <= b and consumed in
    stages > b (carried through intermediate stages)."""
    data_names = {l.name for l in model.layers if l.type == "data"}
    produced, consumed = [], []
    for items in stages:
        prod, cons = set(), set()
        for kind, item in items:
            if kind == "layer":
                prod.add(item.name)
                for inp in item.inputs:
                    cons.add(inp.input_layer_name)
            else:
                sm: SubModelConfig = item
                prod.update(sm.output_layer_names)
                cons.update(sm.in_links)
                cons.update(sm.static_links)
                for mem in sm.memories:
                    if mem.boot_layer_name:
                        cons.add(mem.boot_layer_name)
        produced.append(prod)
        consumed.append(cons - prod - data_names)
    payloads = []
    for b in range(len(stages) - 1):
        up = set().union(*produced[: b + 1])
        down = set().union(*consumed[b + 1:])
        payloads.append(sorted(up & down))
    for s, cons in enumerate(consumed):
        earlier = set().union(*produced[:s]) if s else set()
        missing = cons - earlier
        assert not missing, (
            f"stage {s} consumes {sorted(missing)} which no earlier stage "
            f"produces — check the device= annotations")
    return payloads


@dataclasses.dataclass(frozen=True)
class _Table:
    """Static pipeline schedule, compiled per (S, v, M) by greedy
    dependency simulation — every per-(device, tick) decision is a table
    entry the SPMD scan body just looks up.  All arrays are int32 [S, T];
    run flags are 0/1, slot/deposit entries index the stash buffers with
    -1 = none and slot 0 reserved as an all-zeros slot (chunk 0's forward
    input, last chunk's backward output-cotangent)."""
    T: int
    n_fslots: int
    n_bslots: int
    f_dep: np.ndarray        # slot to deposit the fwd-ring arrival into
    f_run: np.ndarray
    f_chunk: np.ndarray
    f_m: np.ndarray
    f_slot: np.ndarray       # input-carrier slot the F op reads
    f_bank: np.ndarray       # 1 iff this F op produces the cost
    b_dep: np.ndarray        # slot to deposit the bwd-ring arrival into
    b_run: np.ndarray
    b_chunk: np.ndarray
    b_m: np.ndarray
    b_slot: np.ndarray       # cotangent slot the B op reads
    b_fslot: np.ndarray      # input-carrier slot the B op recomputes from
    n_ops: int               # scheduled ops (for bubble accounting)


def _compile_schedule(S: int, v: int, M: int,
                      fwd_only: bool = False) -> _Table:
    """Greedy list scheduler for (interleaved) 1F1B over C = S*v chunks,
    chunk c resident on device c % S (round-robin, so every chunk->chunk
    boundary is one +1 ring hop, wrapping S-1 -> 0 between virtual-stage
    groups).

    Constraints simulated exactly as the scan body executes them:
      * per device per tick: at most one forward op and one backward op
        (the body's two legs);
      * F(c, m) needs F(c-1, m)'s output, which travels one ppermute hop:
        available from tick t_F(c-1, m) + 1 (chunk 0 reads the feed);
      * B(c, m) needs B(c+1, m)'s carrier cotangent (one hop, so tick
        t_B(c+1, m) + 1) and the stashed input of F(c, m) (its own tick,
        so a last-chunk F and its B may share a tick: the F leg runs
        first);
      * priorities: forward leg takes the deepest ready chunk (drives the
        loss out and keeps later devices fed), backward the oldest
        microbatch — together they reproduce classic 1F1B order at v=1.

    Any dependency-valid order is exact (the computation is pure
    dataflow); the greedy choice only shapes the bubble, which
    schedule_info() reports from the table rather than a formula."""
    C = S * v
    INF = 1 << 30
    f_left = {(c, m) for c in range(C) for m in range(M)}
    b_left = set() if fwd_only else set(f_left)
    arr_f = {(0, m): 0 for m in range(M)}   # input availability ticks
    arr_b: dict = {}
    tF: dict = {}
    tB: dict = {}
    rows: list = []
    t = 0
    while f_left or b_left:
        tick_f: list = [None] * S
        tick_b: list = [None] * S
        for s in range(S):
            cand = [(c, m) for (c, m) in f_left
                    if c % S == s and arr_f.get((c, m), INF) <= t]
            if cand:
                c, m = max(cand, key=lambda cm: (cm[0], -cm[1]))
                tick_f[s] = (c, m)
                f_left.remove((c, m))
                tF[(c, m)] = t
                if c < C - 1:
                    arr_f[(c + 1, m)] = t + 1
                else:
                    arr_b[(c, m)] = t        # cost cotangent seeds in place
        for s in range(S):
            cand = [(c, m) for (c, m) in b_left
                    if c % S == s and tF.get((c, m), INF) <= t
                    and arr_b.get((c, m), INF) <= t]
            if cand:
                c, m = min(cand, key=lambda cm: (cm[1], -cm[0]))
                tick_b[s] = (c, m)
                b_left.remove((c, m))
                tB[(c, m)] = t
                if c > 0:
                    arr_b[(c - 1, m)] = t + 1
        rows.append((tick_f, tick_b))
        t += 1
        assert t < 4 * (C + 2) * (M + 2), "schedule simulation diverged"
    T = t

    # interval slot allocation per device (slot 0 = reserved zeros)
    def allocate(intervals):
        """intervals: {(c, m): (device, start, end)} -> slots, n_slots."""
        n_slots = 1
        slots: dict = {}
        per_dev: dict = {}
        for key_, (dev, a, b) in sorted(intervals.items(),
                                        key=lambda kv: kv[1][1]):
            busy = per_dev.setdefault(dev, [])
            sid = None
            for cand_id in range(1, n_slots + 1):
                if all(not (a <= e and s_ <= b)
                       for (s_, e, used) in busy if used == cand_id):
                    sid = cand_id
                    break
            n_slots = max(n_slots, sid + 1)
            busy.append((a, b, sid))
            slots[key_] = sid
        return slots, n_slots

    f_iv = {}
    for (c, m), tf in tF.items():
        end = tf if fwd_only else tB[(c, m)]
        if c == 0:
            continue                     # feed-fed: reads the zero slot
        f_iv[(c, m)] = (c % S, arr_f[(c, m)], end)
    f_slots, n_fslots = allocate(f_iv)
    b_iv = {}
    if not fwd_only:
        for (c, m), tb in tB.items():
            if c == C - 1:
                continue                 # cost-seeded: reads the zero slot
            b_iv[(c, m)] = (c % S, arr_b[(c, m)], tb)
    b_slots, n_bslots = allocate(b_iv)

    z = lambda: np.zeros((S, T), np.int32)
    mone = lambda: np.full((S, T), -1, np.int32)
    tbl = _Table(T=T, n_fslots=n_fslots, n_bslots=n_bslots,
                 f_dep=mone(), f_run=z(), f_chunk=z(), f_m=z(),
                 f_slot=z(), f_bank=z(),
                 b_dep=mone(), b_run=z(), b_chunk=z(), b_m=z(),
                 b_slot=z(), b_fslot=z(),
                 n_ops=len(tF) + len(tB))
    for (c, m), sid in f_slots.items():
        tbl.f_dep[c % S, arr_f[(c, m)]] = sid
    for (c, m), sid in b_slots.items():
        tbl.b_dep[c % S, arr_b[(c, m)]] = sid
    for t_, (tick_f, tick_b) in enumerate(rows):
        for s in range(S):
            if tick_f[s] is not None:
                c, m = tick_f[s]
                tbl.f_run[s, t_] = 1
                tbl.f_chunk[s, t_] = c
                tbl.f_m[s, t_] = m
                tbl.f_slot[s, t_] = f_slots.get((c, m), 0)
                tbl.f_bank[s, t_] = int(c == C - 1)
            if tick_b[s] is not None:
                c, m = tick_b[s]
                tbl.b_run[s, t_] = 1
                tbl.b_chunk[s, t_] = c
                tbl.b_m[s, t_] = m
                tbl.b_slot[s, t_] = b_slots.get((c, m), 0)
                tbl.b_fslot[s, t_] = f_slots.get((c, m), 0)
    return tbl


def _grad_acc_init(params):
    """Zero gradient accumulators: >= fp32 for floating params regardless
    of the compute dtype — the same semantics autodiff's cast-transpose
    gives the GPipe path.  Shared by the 1F1B and interleaved backwards."""
    return {k: jnp.zeros(v.shape,
                         jnp.promote_types(v.dtype, jnp.float32)
                         if jnp.issubdtype(v.dtype, jnp.floating)
                         else v.dtype)
            for k, v in params.items()}


def _cast_grads_back(grads, raw_dtypes):
    """Grads are w.r.t. the prepared (compute-dtype) params; cast back to
    the raw parameter dtypes, as autodiff's cast-transpose would."""
    return {k: g.astype(raw_dtypes[k]) for k, g in grads.items()}


def _vjp_branch(f):
    """Backward twin of a forward stage branch: recompute the stage under
    jax.vjp from its stashed input carrier.  The cotangents stack across
    lax.switch because every branch returns the same (out[mb, width],
    cost[mb]) shapes.  Shared by the 1F1B and interleaved hand-scheduled
    backwards — one definition so they can never diverge.  `frz` (frozen
    BN stats) is a constant of the recompute — never differentiated."""
    def bwd(p, stash_in, feed_mb, key, d_out, d_cost, frz):
        (_, _), vjp_fn = jax.vjp(
            lambda pp, rr: f(pp, rr, feed_mb, key,
                             jax.lax.stop_gradient(frz)), p, stash_in)
        d_p, d_recv = vjp_fn((d_out, d_cost))
        return d_p, d_recv

    return bwd


class PipelineExecutor:
    """GraphExecutor-compatible loss() that runs the config as a GPipe
    pipeline over the mesh's `pipe` axis.  Drop-in for Trainer: same
    constructor surface via from_config and the same
    loss(params, feed, state, mode, rng) signature."""

    def __init__(self, model: ModelConfig, mesh, n_micro: int = 0,
                 compute_dtype: str = "", schedule: str = "gpipe",
                 virtual_stages: int = 1):
        self.model = model
        self.mesh = mesh
        self.n_stages = axis_size(mesh, PIPE_AXIS)
        assert self.n_stages > 1, \
            "PipelineExecutor needs a pipe mesh axis of size > 1"
        self.n_micro = n_micro or self.n_stages
        assert schedule in ("gpipe", "1f1b", "interleaved"), (
            f"unknown pipeline_schedule {schedule!r}; use 'gpipe', '1f1b' "
            f"or 'interleaved'")
        assert virtual_stages >= 1, (
            f"pipeline_virtual_stages must be >= 1, got {virtual_stages}")
        assert virtual_stages == 1 or schedule == "interleaved", (
            "pipeline_virtual_stages > 1 needs "
            "pipeline_schedule='interleaved'")
        self.schedule = schedule
        self.virtual_stages = virtual_stages
        # 'interleaved': the graph splits into C = S*v chunks (annotate
        # device=0..C-1), chunk c resident on device c % S — each device
        # hosts v non-contiguous chunks, shrinking the warmup bubble
        self.n_chunks = self.n_stages * virtual_stages
        self.inner, self.stages = split_stages(model, self.n_chunks)
        self.inner.mesh = None        # stage bodies run mesh-local
        self.inner.compute_dtype = compute_dtype
        self.payload_names = _stage_io(model, self.stages)
        self._spec_cache: dict = {}

    def schedule_info(self) -> dict:
        """Bubble/memory accounting for the active schedule.  gpipe/1f1b
        share the bubble fraction (S-1)/(M+S-1) per direction; 1F1B's win
        is the in-flight boundary-carrier cap (S instead of M), and
        'interleaved' reports its simulated table: v virtual stages cut
        the warmup bubble roughly v-fold at equal M."""
        S, M = self.n_stages, self.n_micro
        info = {
            "schedule": self.schedule,
            "stages": S,
            "micro_batches": M,
            "bubble_fraction": (S - 1) / (M + S - 1),
            "in_flight_carriers": S if self.schedule == "1f1b" else M,
        }
        if self.schedule == "interleaved":
            tbl = _compile_schedule(S, self.virtual_stages, M)
            info.update({
                "virtual_stages": self.virtual_stages,
                "ticks": tbl.T,
                "bubble_fraction": 1.0 - tbl.n_ops / (2 * S * tbl.T),
                # live carrier/cotangent slots, excluding the two reserved
                # all-zeros slots (ids 0) that never hold data
                "in_flight_carriers": (tbl.n_fslots - 1) + (tbl.n_bslots - 1),
            })
        return info

    @property
    def compute_dtype(self) -> str:
        return self.inner.compute_dtype

    @compute_dtype.setter
    def compute_dtype(self, value: str) -> None:
        # checkgrad toggles executor.compute_dtype; the inner executor's
        # prepare() is what actually applies the cast.  Boundary specs
        # record traced dtypes, so a dtype change invalidates the cache
        # (its key is shapes-only).
        if value != self.inner.compute_dtype:
            self._spec_cache.clear()
        self.inner.compute_dtype = value

    # -- GraphExecutor surface -------------------------------------------
    def init_params(self, rng):
        return self.inner.init_params(rng)

    def init_state(self):
        return {}

    @property
    def static_param_names(self):
        return self.inner.static_param_names

    @property
    def layer_map(self):
        return self.inner.layer_map

    def forward(self, *a, **kw):
        """Diagnostics path (NaN localisation etc.): run the whole graph
        un-pipelined on this host's devices."""
        return self.inner.forward(*a, **kw)

    # -- frozen layer state ----------------------------------------------
    @property
    def _frozen_state_names(self) -> set:
        """Layers whose carried state is CONSTANT during training —
        explicitly-frozen batch norm (use_global_stats=True): its moving
        stats are read, never written, so loaded checkpoint stats can be
        embedded into the stage computation as graph constants and the
        frozen-fine-tune pattern pipelines exactly."""
        return {l.name for l in self.model.layers
                if l.use_global_stats is True}

    def _check_frozen_state(self, state) -> dict:
        """Validate that every net_state entry belongs to a frozen-BN
        layer; genuinely MUTABLE state (training-mode BN moving stats,
        prev_batch_state recurrences) cannot ride the stage ring."""
        state = dict(state or {})
        mutable = sorted(set(state) - self._frozen_state_names)
        assert not mutable, (
            f"layers with mutable state {mutable} are not supported under "
            f"pipeline parallelism (per-microbatch stat updates would "
            f"change the training numerics vs the un-pipelined oracle, "
            f"and the stage ring has no mutable-state channel).  Freeze "
            f"the stats with batch_norm_layer(..., use_global_stats=True) "
            f"— frozen BN pipelines exactly, fresh-init or with loaded "
            f"moving stats (they are embedded as constants); or train "
            f"this config without device= annotations")
        return state

    # -- boundary specs ---------------------------------------------------
    def _boundary_specs(self, feed: dict[str, Argument], mb: int,
                        state=None):
        """Derive each boundary's carrier layout by shape-tracing the full
        graph on a microbatch-shaped feed.  Static per batch signature."""
        sig = tuple(sorted(
            (n, a.value is not None and tuple(a.value.shape[1:]),
             a.ids is not None and tuple(a.ids.shape[1:]), a.sparse_dim,
             a.lengths is not None,
             a.sub_lengths is not None and tuple(a.sub_lengths.shape[1:]))
            for n, a in feed.items()))
        key = (sig, mb)
        if key in self._spec_cache:
            return self._spec_cache[key]

        def slice_leaf(x):
            return jax.ShapeDtypeStruct((mb,) + tuple(x.shape[1:]), x.dtype)

        mb_feed = jax.tree.map(slice_leaf, feed)
        params_sds = {p.name: jax.ShapeDtypeStruct(tuple(p.dims), jnp.float32)
                      for p in self.model.parameters}
        outs, costs, state_out = jax.eval_shape(
            lambda p, f: self.inner.forward(p, f, state, TRAIN,
                                            jax.random.PRNGKey(0)),
            params_sds, mb_feed)
        # scoped to GENUINELY mutable state: frozen-BN entries (loaded
        # moving stats round-tripping through state_out unchanged) are
        # constants and pipeline exactly (ADVICE r5)
        self._check_frozen_state(state_out)
        specs = []
        for names in self.payload_names:
            row = []
            for n in names:
                a = outs[n]
                assert a.value is not None, (
                    f"{n!r} crosses a pipeline stage boundary without a "
                    f"dense value (ids/sparse payloads can't ride the "
                    f"activation ring) — keep its consumers on the same "
                    f"stage")
                row.append(_CrossSpec(
                    name=n, value_shape=tuple(a.value.shape),
                    value_dtype=a.value.dtype,
                    has_lengths=a.lengths is not None,
                    sub_shape=(tuple(a.sub_lengths.shape)
                               if a.sub_lengths is not None else None)))
            specs.append(row)
        width = max((sum(s.width for s in row) for row in specs), default=1)
        self._spec_cache[key] = (specs, max(width, 1))
        return specs, max(width, 1)

    @staticmethod
    def _pack(row: list[_CrossSpec], ctx_out: dict, width: int,
              mb: int) -> Array:
        segs = []
        for s in row:
            a = ctx_out[s.name]
            segs.append(a.value.reshape(mb, -1).astype(jnp.float32))
            if s.has_lengths:
                segs.append(a.lengths.reshape(mb, 1).astype(jnp.float32))
            if s.sub_shape is not None:
                segs.append(a.sub_lengths.reshape(mb, -1).astype(jnp.float32))
        buf = (jnp.concatenate(segs, axis=1) if segs
               else jnp.zeros((mb, 0), jnp.float32))
        pad = width - buf.shape[1]
        return jnp.pad(buf, ((0, 0), (0, pad))) if pad else buf

    @staticmethod
    def _unpack(row: list[_CrossSpec], buf: Array, mb: int) -> dict:
        out, off = {}, 0
        for s in row:
            w = int(np.prod(s.value_shape[1:])) if len(s.value_shape) > 1 else 1
            val = buf[:, off:off + w].reshape(s.value_shape).astype(s.value_dtype)
            off += w
            lengths = sub = None
            if s.has_lengths:
                lengths = jnp.round(buf[:, off]).astype(jnp.int32)
                off += 1
            if s.sub_shape is not None:
                n = int(np.prod(s.sub_shape[1:]))
                sub = jnp.round(buf[:, off:off + n]).reshape(
                    s.sub_shape).astype(jnp.int32)
                off += n
            out[s.name] = Argument(value=val, lengths=lengths, sub_lengths=sub)
        return out

    def _stage_branches(self, specs, width: int, mb: int, mode: str):
        """Per-stage body functions with one UNIFORM signature
        (p, recv[mb,width], feed_mb, key) -> (out[mb,width], cost[mb]) —
        uniformity is what lets lax.switch host the heterogeneous stage
        (or virtual-stage chunk) bodies, and (for the hand-scheduled
        backwards) what makes per-stage jax.vjp cotangents stackable."""
        S = len(self.stages)             # chunks when interleaved
        model, inner = self.model, self.inner

        def make_branch(s: int):
            items = self.stages[s]
            in_row = specs[s - 1] if s > 0 else []
            out_row = specs[s] if s < S - 1 else []

            def branch(p, recv, feed_mb, key, frz):
                # frz: frozen-BN moving stats (use_global_stats=True),
                # loaded from a checkpoint — constants of the stage body
                ctx = ForwardContext(model=model, params=p, mode=mode,
                                     rng=key, state_in=frz)
                for n, a in feed_mb.items():
                    ctx.outputs[n] = a
                ctx.outputs.update(self._unpack(in_row, recv, mb))
                for kind, item in items:
                    if kind == "layer":
                        ctx.outputs[item.name] = get_layer_fn(item.type)(ctx, item)
                    else:
                        inner._run_scan(ctx, item)
                if s == S - 1:
                    from paddle_tpu.utils.dtypes import promote_compute
                    assert ctx.costs, "model has no cost layers"
                    cost = None
                    for c in ctx.costs.values():
                        c = promote_compute(c).reshape(mb)
                        cost = c if cost is None else cost + c
                    return jnp.zeros((mb, width), jnp.float32), \
                        cost.astype(jnp.float32)
                return self._pack(out_row, ctx.outputs, width, mb), \
                    jnp.zeros((mb,), jnp.float32)

            return branch

        return [make_branch(s) for s in range(S)]

    def _prologue(self, params, feed, rng, state=None):
        """Shared entry for both schedules: prepare, microbatch sizing,
        boundary specs, rng default.  One place so the divisibility rule
        and spec derivation can never diverge between GPipe and 1F1B."""
        params, feed = self.inner.prepare(params, feed)
        M = self.n_micro
        n_data = axis_size(self.mesh, DATA_AXIS)
        B = next(iter(feed.values())).batch_size
        assert B % (M * n_data) == 0, (
            f"batch {B} not divisible by {M} microbatches x {n_data} data "
            f"shards")
        mb = B // (M * n_data)
        specs, width = self._boundary_specs(feed, mb, state)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return params, feed, B, mb, specs, width, rng

    # -- the pipelined loss ----------------------------------------------
    def loss(self, params, feed, state=None, mode: str = TRAIN, rng=None):
        frozen = self._check_frozen_state(state)
        if self.schedule == "interleaved":
            return self._table_loss(params, feed, mode, rng, state=frozen)
        S, M = self.n_stages, self.n_micro
        params, feed, B, mb, specs, width, rng = self._prologue(
            params, feed, rng, state=frozen)

        branches = self._stage_branches(specs, width, mb, mode)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def local(p, feed_loc, key, frz):
            stage = lax.axis_index(PIPE_AXIS)

            def tick(carry, t):
                recv, loss_buf = carry
                # stage s processes microbatch t-s at tick t
                m_idx = jnp.clip(t - stage, 0, M - 1)
                feed_mb = jax.tree.map(
                    lambda x: lax.dynamic_slice_in_dim(x, m_idx * mb, mb),
                    feed_loc)
                # per-(microbatch, stage) rng stream for dropout etc.
                key_t = jax.random.fold_in(key, m_idx * S + stage)
                out, cost = lax.switch(stage, branches, p, recv, feed_mb,
                                       key_t, frz)
                j = t - (S - 1)
                banked = lax.dynamic_update_index_in_dim(
                    loss_buf, cost[None], jnp.maximum(j, 0), axis=0)
                valid = jnp.logical_and(stage == S - 1, j >= 0)
                loss_buf = jnp.where(valid, banked, loss_buf)
                recv = lax.ppermute(out, PIPE_AXIS, fwd_perm)
                return (recv, loss_buf), None

            carry0 = (jnp.zeros((mb, width), jnp.float32),
                      jnp.zeros((M, mb), jnp.float32))
            (recv, loss_buf), _ = lax.scan(tick, carry0, jnp.arange(M + S - 1))
            # only the last stage banked real losses; share + reduce
            local_sum = jnp.sum(jnp.where(stage == S - 1, loss_buf, 0.0))
            total = lax.psum(lax.psum(local_sum, PIPE_AXIS), DATA_AXIS)
            return total / B

        from jax.sharding import PartitionSpec as P
        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(DATA_AXIS), P(), P()), out_specs=P(),
            check_vma=False)
        total = fn(params, feed, rng, frozen)
        return total, ({}, {}, {})

    # -- 1F1B: hand-scheduled forward/backward --------------------------
    def loss_and_grad(self, params, feed, mode: str = TRAIN, rng=None,
                      state=None):
        """One-forward-one-backward schedule (pipeline_schedule='1f1b').

        GPipe above runs ALL forwards then lets autodiff transpose the
        scan — in-flight state grows with the microbatch count M.  Here
        the backward is hand-scheduled so each stage alternates F and B
        with per-stage recompute from the stashed INPUT carrier: at most
        S boundary carriers are live per stage, independent of M (the
        memory property 1F1B exists for).

        Lockstep schedule (global tick t, stage s, microbatch m):
          forward  F(s,m) at t = s + 2m
          backward B(s,m) at t = 2S - 1 - s + 2m
        Consecutive stages line up exactly one ppermute hop apart in both
        directions (F(s+1,m) = F(s,m)+1; B(s-1,m) = B(s,m)+1), so the two
        rings deliver just-in-time and only the input stash (m mod S)
        buffers state.  Dataflow-identical to GPipe/unpipelined — the
        phase-2a exactness oracle and tests/test_pipeline_config.py assert
        it; total ticks 2(M+S-1), bubble fraction (S-1)/(M+S-1) per
        direction (see schedule_info()).

        Returns (loss, grads) w.r.t. `params` — the Trainer calls this
        instead of wrapping loss() in jax.value_and_grad.
        """
        if self.schedule == "interleaved":
            return self._table_loss_and_grad(params, feed, mode, rng,
                                             state=state)
        frozen = self._check_frozen_state(state)
        raw_dtypes = {k: v.dtype for k, v in params.items()}
        S, M = self.n_stages, self.n_micro
        params, feed, B, mb, specs, width, rng = self._prologue(
            params, feed, rng, state=frozen)

        fwd_branches = self._stage_branches(specs, width, mb, mode)
        bwd_branches = [_vjp_branch(f) for f in fwd_branches]
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i, i - 1) for i in range(1, S)]
        gacc0 = _grad_acc_init(params)

        def local(p, feed_loc, key, frz):
            stage = lax.axis_index(PIPE_AXIS)
            T = 2 * (M + S - 1)

            def feed_at(m_idx):
                return jax.tree.map(
                    lambda x: lax.dynamic_slice_in_dim(x, m_idx * mb, mb),
                    feed_loc)

            def tick(carry, t):
                recv_f, recv_b, stash, loss_buf, gacc = carry
                # -- forward leg: F(s,m) at t = s + 2m
                ft = t - stage
                is_f = jnp.logical_and(ft >= 0, jnp.logical_and(
                    ft % 2 == 0, ft // 2 < M))
                m_f = jnp.clip(ft // 2, 0, M - 1)
                key_f = jax.random.fold_in(key, m_f * S + stage)

                def run_f(_):
                    return lax.switch(stage, fwd_branches, p, recv_f,
                                      feed_at(m_f), key_f, frz)

                def skip_f(_):
                    return (jnp.zeros((mb, width), jnp.float32),
                            jnp.zeros((mb,), jnp.float32))

                out_f, cost = lax.cond(is_f, run_f, skip_f, None)
                # stash this microbatch's input carrier for its backward
                stash = jnp.where(is_f,
                                  stash.at[m_f % S].set(recv_f), stash)
                banked = lax.dynamic_update_index_in_dim(
                    loss_buf, cost[None], m_f, axis=0)
                loss_buf = jnp.where(
                    jnp.logical_and(is_f, stage == S - 1), banked, loss_buf)

                # -- backward leg: B(s,m) at t = 2S - 1 - s + 2m
                bt = t - (2 * S - 1 - stage)
                is_b = jnp.logical_and(bt >= 0, jnp.logical_and(
                    bt % 2 == 0, bt // 2 < M))
                m_b = jnp.clip(bt // 2, 0, M - 1)
                key_b = jax.random.fold_in(key, m_b * S + stage)
                # the last stage's cost output seeds the chain; upstream
                # stages' cost outputs are constant zeros, so the shared
                # ones-cotangent only contributes there
                d_cost = jnp.ones((mb,), jnp.float32)

                def run_b(gacc_in):
                    d_p, d_recv = lax.switch(
                        stage, bwd_branches, p, stash[m_b % S],
                        feed_at(m_b), key_b, recv_b, d_cost, frz)
                    return jax.tree.map(
                        lambda a, g: a + g.astype(a.dtype), gacc_in, d_p), \
                        d_recv

                def skip_b(gacc_in):
                    # idle tick: no zeros tree, no dead accumulation adds
                    return gacc_in, jnp.zeros((mb, width), jnp.float32)

                gacc, d_recv = lax.cond(is_b, run_b, skip_b, gacc)

                recv_f = lax.ppermute(out_f, PIPE_AXIS, fwd_perm)
                recv_b = lax.ppermute(d_recv, PIPE_AXIS, bwd_perm)
                return (recv_f, recv_b, stash, loss_buf, gacc), None

            carry0 = (jnp.zeros((mb, width), jnp.float32),
                      jnp.zeros((mb, width), jnp.float32),
                      jnp.zeros((S, mb, width), jnp.float32),
                      jnp.zeros((M, mb), jnp.float32),
                      gacc0)
            (_, _, _, loss_buf, gacc), _ = lax.scan(tick, carry0,
                                                    jnp.arange(T))
            local_sum = jnp.sum(jnp.where(stage == S - 1, loss_buf, 0.0))
            total = lax.psum(lax.psum(local_sum, PIPE_AXIS), DATA_AXIS)
            grads = jax.tree.map(
                lambda g: lax.psum(lax.psum(g, PIPE_AXIS), DATA_AXIS) / B,
                gacc)
            return total / B, grads

        from jax.sharding import PartitionSpec as P
        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(DATA_AXIS), P(), P()), out_specs=(P(), P()),
            check_vma=False)
        total, grads = fn(params, feed, rng, frozen)
        return total, _cast_grads_back(grads, raw_dtypes)

    # -- interleaved virtual stages: table-driven schedule ---------------
    def _table_run(self, params, feed, mode, rng, fwd_only: bool,
                   state=None):
        """Execute the compiled interleaved schedule: one scan body serves
        both training (fwd_only=False: both legs, returns (loss, grads))
        and test/eval (fwd_only=True: forward leg only, returns loss).
        Each device hosts its v chunks' branches behind one lax.switch;
        stash slots (compile-time interval-allocated) buffer carriers and
        cotangents whose consumer isn't scheduled just-in-time; chunk
        round-robin makes EVERY chunk boundary a +1 ring hop (wrapping
        S-1 -> 0 between virtual-stage groups)."""
        frozen = self._check_frozen_state(state)
        raw_dtypes = None if fwd_only else \
            {k: v.dtype for k, v in params.items()}
        M, C, S = self.n_micro, self.n_chunks, self.n_stages
        params, feed, B, mb, specs, width, rng = self._prologue(
            params, feed, rng, state=frozen)
        fwd_branches = self._stage_branches(specs, width, mb, mode)
        bwd_branches = None if fwd_only else \
            [_vjp_branch(f) for f in fwd_branches]
        tbl = _compile_schedule(S, self.virtual_stages, M,
                                fwd_only=fwd_only)
        jt = {f.name: jnp.asarray(getattr(tbl, f.name))
              for f in dataclasses.fields(_Table)
              if isinstance(getattr(tbl, f.name), np.ndarray)}
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]
        gacc0 = None if fwd_only else _grad_acc_init(params)

        def local(p, feed_loc, key, frz):
            stage = lax.axis_index(PIPE_AXIS)

            def feed_at(m_idx):
                return jax.tree.map(
                    lambda x: lax.dynamic_slice_in_dim(x, m_idx * mb, mb),
                    feed_loc)

            def tick(carry, t):
                if fwd_only:
                    recv_f, fstash, loss_buf = carry
                else:
                    recv_f, recv_b, fstash, bstash, loss_buf, gacc = carry
                # deposits first: a just-in-time consumer reads its slot
                # the same tick the wire value lands (idle ticks park the
                # wire in the dump slot -- the last index, never read)
                fd = jt["f_dep"][stage, t]
                fstash = lax.dynamic_update_index_in_dim(
                    fstash, recv_f, jnp.where(fd >= 0, fd, tbl.n_fslots), 0)
                if not fwd_only:
                    bd = jt["b_dep"][stage, t]
                    bstash = lax.dynamic_update_index_in_dim(
                        bstash, recv_b,
                        jnp.where(bd >= 0, bd, tbl.n_bslots), 0)

                # -- forward leg
                fc, fm = jt["f_chunk"][stage, t], jt["f_m"][stage, t]
                fs = jt["f_slot"][stage, t]
                key_f = jax.random.fold_in(key, fm * C + fc)

                def run_f(_):
                    return lax.switch(
                        fc, fwd_branches, p,
                        lax.dynamic_index_in_dim(fstash, fs, 0, False),
                        feed_at(fm), key_f, frz)

                def skip_f(_):
                    return (jnp.zeros((mb, width), jnp.float32),
                            jnp.zeros((mb,), jnp.float32))

                out_f, cost = lax.cond(jt["f_run"][stage, t] == 1,
                                       run_f, skip_f, None)
                banked = lax.dynamic_update_index_in_dim(
                    loss_buf, cost[None], fm, axis=0)
                loss_buf = jnp.where(jt["f_bank"][stage, t] == 1,
                                     banked, loss_buf)
                recv_f = lax.ppermute(out_f, PIPE_AXIS, fwd_perm)
                if fwd_only:
                    return (recv_f, fstash, loss_buf), None

                # -- backward leg (after F: a last-chunk F and its B may
                # share a tick)
                bc, bm = jt["b_chunk"][stage, t], jt["b_m"][stage, t]
                bs, bf = jt["b_slot"][stage, t], jt["b_fslot"][stage, t]
                key_b = jax.random.fold_in(key, bm * C + bc)
                d_cost = jnp.ones((mb,), jnp.float32)

                def run_b(gacc_in):
                    d_p, d_recv = lax.switch(
                        bc, bwd_branches, p,
                        lax.dynamic_index_in_dim(fstash, bf, 0, False),
                        feed_at(bm), key_b,
                        lax.dynamic_index_in_dim(bstash, bs, 0, False),
                        d_cost, frz)
                    return jax.tree.map(
                        lambda a, g: a + g.astype(a.dtype), gacc_in, d_p), \
                        d_recv

                def skip_b(gacc_in):
                    return gacc_in, jnp.zeros((mb, width), jnp.float32)

                gacc, d_recv = lax.cond(jt["b_run"][stage, t] == 1,
                                        run_b, skip_b, gacc)
                recv_b = lax.ppermute(d_recv, PIPE_AXIS, bwd_perm)
                return (recv_f, recv_b, fstash, bstash, loss_buf, gacc), None

            zeros_wire = jnp.zeros((mb, width), jnp.float32)
            fstash0 = jnp.zeros((tbl.n_fslots + 1, mb, width), jnp.float32)
            loss0 = jnp.zeros((M, mb), jnp.float32)
            if fwd_only:
                carry0 = (zeros_wire, fstash0, loss0)
            else:
                carry0 = (zeros_wire, zeros_wire, fstash0,
                          jnp.zeros((tbl.n_bslots + 1, mb, width),
                                    jnp.float32),
                          loss0, gacc0)
            carry, _ = lax.scan(tick, carry0, jnp.arange(tbl.T))
            # only the device hosting the last chunk banks real costs
            loss_buf = carry[2] if fwd_only else carry[4]
            local_sum = jnp.sum(loss_buf)
            total = lax.psum(lax.psum(local_sum, PIPE_AXIS), DATA_AXIS)
            if fwd_only:
                return total / B
            grads = jax.tree.map(
                lambda g: lax.psum(lax.psum(g, PIPE_AXIS), DATA_AXIS) / B,
                carry[5])
            return total / B, grads

        from jax.sharding import PartitionSpec as P
        fn = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(DATA_AXIS), P(), P()),
            out_specs=P() if fwd_only else (P(), P()),
            check_vma=False)
        if fwd_only:
            return fn(params, feed, rng, frozen)
        total, grads = fn(params, feed, rng, frozen)
        return total, _cast_grads_back(grads, raw_dtypes)

    def _table_loss(self, params, feed, mode: str = TRAIN, rng=None,
                    state=None):
        """Forward-only (test/eval) execution of the interleaved table."""
        total = self._table_run(params, feed, mode, rng, fwd_only=True,
                                state=state)
        return total, ({}, {}, {})

    def _table_loss_and_grad(self, params, feed, mode: str = TRAIN,
                             rng=None, state=None):
        """Interleaved 1F1B training: both legs of the compiled table."""
        return self._table_run(params, feed, mode, rng, fwd_only=False,
                               state=state)
