from paddle_tpu.parallel.mesh import make_mesh, mesh_from_flag  # noqa: F401
from paddle_tpu.parallel.dp import shard_batch, shard_train_objects  # noqa: F401
