"""Pipeline parallelism — layer stages sharded over the `pipe` mesh axis.

TPU-native scaled-out analog of the reference's model parallelism, where
layers annotated `device=N` execute on per-device compute threads with
explicit inter-device output copies (ref: paddle/gserver/gradientmachines/
ParallelNeuralNetwork.h:35-70, Layer.h:112 copyOutputToOtherDevice).

Re-design: instead of threads + cudaMemcpyPeer, the model is split into S
stages laid out over the `pipe` mesh axis; a batch is split into M
microbatches that flow through the stages GPipe-style.  One `lax.scan` runs
M + S - 1 ticks; at every tick each device applies its stage to the
activation it received and `lax.ppermute`s the result one hop down the
ring — so at steady state all S stages compute simultaneously on different
microbatches, and XLA overlaps each hop's ICI transfer with the next tick's
compute.  The backward pass is jax.grad through the scan: the transpose of
ppermute is the reverse-direction ppermute, which reproduces the classic
backward pipeline schedule automatically — the reference's hand-built
inter-thread gradient plumbing is ~40 lines of pure function here.

Constraint (standard for SPMD pipelining): every stage maps activations
[mb, D] -> [mb, D] of one uniform width D = x.shape[-1]; pad the input and
narrower interfaces to D.  `out_dim` trims the final stage's output.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, axis_size
from paddle_tpu.utils.jax_compat import shard_map

Array = jax.Array


def stack_stage_params(per_stage: Sequence[Any]) -> Any:
    """Stack S per-stage parameter pytrees into one pytree whose leaves have
    a leading stage dim — shard that dim over `pipe`."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def stage_param_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for stacked stage params: stage dim over the pipe axis."""
    return NamedSharding(mesh, P(PIPE_AXIS))


def place_stage_params(mesh: Mesh, stacked: Any) -> Any:
    from paddle_tpu.parallel.dp import global_put
    sh = stage_param_sharding(mesh)
    return jax.tree.map(lambda x: global_put(x, sh), stacked)


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable[[Any, Array], Array],
    stacked_params: Any,       # leaves [S, ...], sharded over pipe
    x: Array,                  # [B, D_in]
    n_micro: int,
    out_dim: Optional[int] = None,
) -> Array:
    """Run x through S pipelined stages; returns [B, D_out].

    stage_fn(stage_params, act) is the per-stage computation; stage_params
    is one stage's slice of `stacked_params` (leading dim dropped).
    """
    S = axis_size(mesh, PIPE_AXIS)
    assert S > 1, "mesh has no pipe axis — use stage_fn directly"
    for leaf in jax.tree.leaves(stacked_params):
        assert leaf.shape[0] == S, \
            f"stacked stage dim {leaf.shape[0]} != pipe axis size {S}"
    n_data = axis_size(mesh, DATA_AXIS)
    B = x.shape[0]
    assert B % (n_micro * n_data) == 0, \
        f"batch {B} not divisible by {n_micro} microbatches x {n_data} data shards"
    D = x.shape[-1]                 # the uniform stage interface width
    D_out = out_dim or D

    def local(params_loc, x_full):
        # x_full is this data shard's slice; params_loc leaves are [1, ...]
        # (this device's stage) — drop the dim
        B_loc = x_full.shape[0]
        mb = B_loc // n_micro
        params = jax.tree.map(lambda p: p[0], params_loc)
        stage = lax.axis_index(PIPE_AXIS)
        micro = x_full.reshape(n_micro, mb, D)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]     # no wraparound

        def tick(carry, t):
            recv, out_buf = carry
            # stage 0 injects microbatch t (clamped; masked when t >= n_micro)
            inj = lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, n_micro - 1), keepdims=False)
            act = jnp.where(stage == 0, inj, recv)
            out = stage_fn(params, act)
            assert out.shape == (mb, D), \
                f"stage output {out.shape} != uniform interface {(mb, D)}"
            # last stage banks microbatch j = t - (S-1) once it emerges
            j = t - (S - 1)
            bank = lax.dynamic_update_index_in_dim(
                out_buf, _fit(out, D_out)[None], jnp.maximum(j, 0), axis=0)
            valid = jnp.logical_and(stage == S - 1, j >= 0)
            out_buf = jnp.where(valid, bank, out_buf)
            recv = lax.ppermute(out, PIPE_AXIS, fwd_perm)
            return (recv, out_buf), None

        carry0 = (jnp.zeros((mb, D), x_full.dtype),
                  jnp.zeros((n_micro, mb, D_out), x_full.dtype))
        (recv, out_buf), _ = lax.scan(tick, carry0, jnp.arange(n_micro + S - 1))
        # replicate the last stage's banked outputs to every pipe rank
        out_buf = lax.psum(
            jnp.where(stage == S - 1, out_buf, 0.0), PIPE_AXIS)
        return out_buf.reshape(B_loc, D_out)

    # batch sharded over data (true dp x pp), stages over pipe
    in_specs = (P(PIPE_AXIS), P(DATA_AXIS))
    fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                   out_specs=P(DATA_AXIS), check_vma=False)
    return fn(stacked_params, x)


def _fit(x: Array, width: int) -> Array:
    """Pad/trim the trailing dim to `width` (stage interface adaptation)."""
    d = x.shape[-1]
    if d == width:
        return x
    if d < width:
        return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, width - d)])
    return x[..., :width]
