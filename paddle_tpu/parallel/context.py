"""Sequence/context parallelism — shard the TOKEN axis over the mesh.

First-class long-context support, going beyond the reference (whose longest-
sequence story is zero-padding ragged batching + SequenceToBatch re-bucketing
on ONE device — SURVEY.md §5 "long-context"; ref: paddle/gserver/layers/
SequenceToBatch.h:20-40).  Here a sequence too long for one chip's HBM is
split over the `seq` mesh axis and attention runs as a ring
(ops/attention.py:ring_attention): K/V shards rotate via `lax.ppermute`
around ICI neighbors while each device folds incoming blocks into an
online-softmax accumulator — compute overlaps communication, and per-device
memory is O(T / seq_parallelism).

`ring_attention_sharded` is the mesh-level entry: it shard_maps the ring
kernel with batch on `data` and time on `seq`, usable directly or through the
`multi_head_attention` graph layer (graph/layers_attn.py) which picks the
ring path automatically when the executor's mesh has a seq axis > 1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.ops.attention import ring_attention
from paddle_tpu.utils.jax_compat import shard_map
from paddle_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, axis_size

Array = jax.Array


def seq_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the seq axis, 1 if absent/no mesh."""
    return axis_size(mesh, SEQ_AXIS)


def _data_axis(mesh: Mesh) -> Optional[str]:
    return DATA_AXIS if DATA_AXIS in mesh.axis_names else None


def shard_sequence(mesh: Mesh, x: Array) -> Array:
    """Place [B, T, ...] with batch on `data` and time on `seq`.  Works on
    multi-process meshes too (each process holds the full host copy)."""
    from paddle_tpu.parallel.dp import global_put
    spec = [_data_axis(mesh), SEQ_AXIS] + [None] * (x.ndim - 2)
    return global_put(x, NamedSharding(mesh, P(*spec)))


def _sharded_ctx_call(mesh, wrapped, q, k, v, q_valid, k_valid,
                      use_flash: bool):
    """Shared shard_map scaffolding for the context-parallel entries:
    batch on `data`, tokens on `seq`, optional masks threaded with
    placeholder args (shard_map needs every arg speced).  check_vma stays
    ON for the pure-jnp paths, where it validates the collective
    plumbing; pallas_call outputs carry no varying-mesh-axes annotation,
    so the flash path must opt out."""
    d = _data_axis(mesh)
    qkv_spec = P(d, SEQ_AXIS, None, None)
    val_spec = P(d, SEQ_AXIS)
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    args = [q, k, v]
    for m in (q_valid, k_valid):
        in_specs.append(val_spec if m is not None else P())
        args.append(m if m is not None else jnp.zeros((), q.dtype))
    fn = shard_map(wrapped, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=qkv_spec, check_vma=not use_flash)
    return fn(*args)


def ring_attention_sharded(
    mesh: Mesh,
    q: Array, k: Array, v: Array,          # [B, T, H, Dh], T % seq_axis == 0
    q_valid: Optional[Array] = None,       # [B, T]
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> Array:
    """Context-parallel attention over the mesh: batch sharded on `data`,
    time sharded on `seq`, ring over the seq axis.  Works under an outer
    jit — shard_map composes with the surrounding compiled step."""
    # resolve the flash choice OUTSIDE shard_map (see _sharded_ctx_call)
    from paddle_tpu.ops import pallas_attention
    use_flash = pallas_attention.supported()

    def wrapped(q, k, v, qm, km):
        qv = qm if q_valid is not None else None
        kv = km if k_valid is not None else None
        return ring_attention(q, k, v, SEQ_AXIS, q_valid=qv, k_valid=kv,
                              causal=causal, scale=scale,
                              use_flash=use_flash, window=window)

    return _sharded_ctx_call(mesh, wrapped, q, k, v, q_valid, k_valid,
                             use_flash)


def ring_attn_fn(mesh: Mesh, causal_default: bool = False):
    """An `attn_fn` for ops.attention.multi_head_attention that routes through
    the sharded ring. Signature matches dot_product_attention."""
    def fn(q, k, v, q_valid=None, k_valid=None, causal=causal_default,
           scale=None, window=None):
        return ring_attention_sharded(mesh, q, k, v, q_valid=q_valid,
                                      k_valid=k_valid, causal=causal,
                                      scale=scale, window=window)
    return fn


def ulysses_attention_sharded(
    mesh: Mesh,
    q: Array, k: Array, v: Array,          # [B, T, H, Dh], T % seq_axis == 0
    q_valid: Optional[Array] = None,       # [B, T]
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_min: Optional[int] = None,
) -> Array:
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses design) —
    the OTHER standard context-parallel layout beside the ring:

      tokens sharded [B, T/P, H, D]
        --all_to_all-->  heads sharded [B, T, H/P, D]
        --local full-sequence attention (flash on TPU)-->
        --all_to_all-->  tokens sharded [B, T/P, H, D]

    Two activation exchanges per layer instead of the ring's P-1 K/V
    rotations: communication is O(T*H*D/P) regardless of P, and the
    attention itself is a plain full-sequence call (any impl, no
    online-softmax combine).  Prefer it when heads >= the seq-axis size
    and ICI all-to-all bandwidth is plentiful; prefer the ring when
    per-device memory for the full [B, T, H/P] sequence is the binding
    constraint or H < P.  Requires H (and kv heads) % seq_axis == 0.
    """
    Pseq = axis_size(mesh, SEQ_AXIS)
    H, H_kv = q.shape[2], k.shape[2]
    assert H % Pseq == 0, (
        f"ulysses needs num_heads {H} divisible by the seq axis ({Pseq})")
    assert H_kv % Pseq == 0, (
        f"ulysses needs num_kv_heads {H_kv} divisible by the seq axis "
        f"({Pseq}); use attn_impl='ring' for narrower GQA")
    import functools

    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.ops.attention import (blockwise_attention,
                                          dot_product_attention)
    use_flash = pallas_attention.supported()
    T = q.shape[1]
    if block_k_min is None:
        # the ONE measured dense/blockwise crossover constant
        from paddle_tpu.graph.layers_attn import _BLOCKWISE_MIN_KEYS
        block_k_min = _BLOCKWISE_MIN_KEYS
    if use_flash:
        attn = (functools.partial(pallas_attention.flash_attention,
                                  block_k=block_k)
                if block_k else pallas_attention.flash_attention)
    elif T >= block_k_min:
        attn = (functools.partial(blockwise_attention, block_k=block_k)
                if block_k else blockwise_attention)
    else:
        attn = dot_product_attention

    def wrapped(q, k, v, qm, km):
        # token-shard -> head-shard: split heads (axis 2) over the seq
        # axis, concatenate token shards (axis 1) — tiled all_to_all
        # preserves the device order, so tokens land in GLOBAL order
        def a2a_fwd(x):
            return jax.lax.all_to_all(x, SEQ_AXIS, split_axis=2,
                                      concat_axis=1, tiled=True)

        qg, kg, vg = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
        qvg = (jax.lax.all_gather(qm, SEQ_AXIS, axis=1, tiled=True)
               if q_valid is not None else None)
        kvg = (jax.lax.all_gather(km, SEQ_AXIS, axis=1, tiled=True)
               if k_valid is not None else None)
        out = attn(qg, kg, vg, q_valid=qvg, k_valid=kvg, causal=causal,
                   **({"scale": scale} if scale is not None else {}),
                   **({"window": window} if window is not None else {}))
        # head-shard -> token-shard
        return jax.lax.all_to_all(out, SEQ_AXIS, split_axis=1,
                                  concat_axis=2, tiled=True)

    return _sharded_ctx_call(mesh, wrapped, q, k, v, q_valid, k_valid,
                             use_flash)


def ulysses_attn_fn(mesh: Mesh, causal_default: bool = False,
                    block_k: Optional[int] = None,
                    block_k_min: Optional[int] = None):
    """An `attn_fn` for ops.attention.multi_head_attention that routes
    through the all-to-all resharding. Signature matches
    dot_product_attention."""
    def fn(q, k, v, q_valid=None, k_valid=None, causal=causal_default,
           scale=None, window=None):
        return ulysses_attention_sharded(mesh, q, k, v, q_valid=q_valid,
                                         k_valid=k_valid, causal=causal,
                                         scale=scale, window=window,
                                         block_k=block_k,
                                         block_k_min=block_k_min)
    return fn
