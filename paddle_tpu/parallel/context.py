"""Sequence/context parallelism — shard the TOKEN axis over the mesh.

First-class long-context support, going beyond the reference (whose longest-
sequence story is zero-padding ragged batching + SequenceToBatch re-bucketing
on ONE device — SURVEY.md §5 "long-context"; ref: paddle/gserver/layers/
SequenceToBatch.h:20-40).  Here a sequence too long for one chip's HBM is
split over the `seq` mesh axis and attention runs as a ring
(ops/attention.py:ring_attention): K/V shards rotate via `lax.ppermute`
around ICI neighbors while each device folds incoming blocks into an
online-softmax accumulator — compute overlaps communication, and per-device
memory is O(T / seq_parallelism).

`ring_attention_sharded` is the mesh-level entry: it shard_maps the ring
kernel with batch on `data` and time on `seq`, usable directly or through the
`multi_head_attention` graph layer (graph/layers_attn.py) which picks the
ring path automatically when the executor's mesh has a seq axis > 1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.ops.attention import ring_attention
from paddle_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, axis_size

Array = jax.Array


def seq_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the seq axis, 1 if absent/no mesh."""
    return axis_size(mesh, SEQ_AXIS)


def _data_axis(mesh: Mesh) -> Optional[str]:
    return DATA_AXIS if DATA_AXIS in mesh.axis_names else None


def shard_sequence(mesh: Mesh, x: Array) -> Array:
    """Place [B, T, ...] with batch on `data` and time on `seq`.  Works on
    multi-process meshes too (each process holds the full host copy)."""
    from paddle_tpu.parallel.dp import global_put
    spec = [_data_axis(mesh), SEQ_AXIS] + [None] * (x.ndim - 2)
    return global_put(x, NamedSharding(mesh, P(*spec)))


def ring_attention_sharded(
    mesh: Mesh,
    q: Array, k: Array, v: Array,          # [B, T, H, Dh], T % seq_axis == 0
    q_valid: Optional[Array] = None,       # [B, T]
    k_valid: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> Array:
    """Context-parallel attention over the mesh: batch sharded on `data`,
    time sharded on `seq`, ring over the seq axis.  Works under an outer
    jit — shard_map composes with the surrounding compiled step."""
    d = _data_axis(mesh)
    qkv_spec = P(d, SEQ_AXIS, None, None)
    val_spec = P(d, SEQ_AXIS)

    # resolve the flash choice HERE (outside shard_map) so the vma check
    # stays on for the pure-jnp ring, where it still validates the
    # ppermute/accumulator plumbing; pallas_call outputs carry no
    # varying-mesh-axes annotation, so the flash path must opt out
    from paddle_tpu.ops import pallas_attention
    use_flash = pallas_attention.supported()

    def local(q, k, v, q_valid, k_valid):
        return ring_attention(q, k, v, SEQ_AXIS, q_valid=q_valid,
                              k_valid=k_valid, causal=causal, scale=scale,
                              use_flash=use_flash, window=window)

    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    args = [q, k, v]
    # shard_map needs every arg speced; thread optional masks only if present
    for m in (q_valid, k_valid):
        in_specs.append(val_spec if m is not None else P())
        args.append(m if m is not None else jnp.zeros((), q.dtype))

    def wrapped(q, k, v, qm, km):
        qv = qm if q_valid is not None else None
        kv = km if k_valid is not None else None
        return local(q, k, v, qv, kv)

    fn = shard_map(wrapped, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=qkv_spec, check_vma=not use_flash)
    return fn(*args)


def ring_attn_fn(mesh: Mesh, causal_default: bool = False):
    """An `attn_fn` for ops.attention.multi_head_attention that routes through
    the sharded ring. Signature matches dot_product_attention."""
    def fn(q, k, v, q_valid=None, k_valid=None, causal=causal_default,
           scale=None, window=None):
        return ring_attention_sharded(mesh, q, k, v, q_valid=q_valid,
                                      k_valid=k_valid, causal=causal,
                                      scale=scale, window=window)
    return fn
