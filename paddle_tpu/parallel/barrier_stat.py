"""Per-step barrier/straggler statistics for mesh runs.

TPU-native analog of the reference's BarrierStat machinery (ref:
paddle/utils/BarrierStat.h:198-389 BarrierStatBase/BarrierEndStat +
REGISTER_BARRIER_TIMER_SERVER): the pserver printed, per trainer, how
unevenly workers arrived at each gradient barrier.  Under XLA there is no
explicit barrier to instrument — collectives are compiled into the step —
so the observable quantities become:

- **dispatch wait**: host time to enqueue the compiled step (grows when the
  device queue is full, i.e. the host is ahead of the device);
- **sync wait**: host time blocked fetching buffered losses (the drain is
  the real device barrier — it completes only when every chip has finished
  its steps, so it carries the straggler signal);
- **cross-process skew**: each process's mean step wall-time allgathered and
  compared, the per-trainer table of the reference's BarrierEndStat LOG.

A `BarrierTimer` keeps rolling windows and renders a one-line summary every
log_period (see Trainer.train_one_pass).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np


def _pct(xs, unit_scale: float = 1e3) -> dict[str, float]:
    a = np.asarray(xs, np.float64) * unit_scale
    return {
        "p50": float(np.percentile(a, 50)),
        "p95": float(np.percentile(a, 95)),
        "p99": float(np.percentile(a, 99)),
        "max": float(a.max()),
    }


def _fmt_pct(name: str, p: dict[str, float]) -> str:
    return (f"{name} p50={p['p50']:.2f}ms p95={p['p95']:.2f}ms "
            f"p99={p['p99']:.2f}ms max={p['max']:.2f}ms")


class BarrierTimer:
    """Rolling per-step timing windows + cross-process straggler report.

    When a `tracer` (paddle_tpu/obs/trace.py) is attached and enabled,
    every timed window ALSO lands as a span on the given track — the
    trainer's per-dispatch phases (dispatch / sync / h2d / scan) become
    Perfetto-viewable without a second instrumentation layer; h2d spans
    are emitted from the prefetch thread onto their own track so the
    staging-vs-scan overlap is visible as parallel lanes."""

    def __init__(self, window: int = 500, tracer=None,
                 track: str = "trainer"):
        self.tracer = tracer
        self.track = track
        self.dispatch_s: deque[float] = deque(maxlen=window)
        self.sync_s: deque[float] = deque(maxlen=window)
        # fused-dispatch (--steps_per_dispatch > 1) windows: h2d is the
        # background thread's stack+device_put of one k-group, scan the
        # host time to enqueue one k-step lax.scan.  Overlap is observable
        # as h2d percentiles staying flat while scan windows absorb the
        # whole step budget (see PERF.md "steps_per_dispatch").
        self.h2d_s: deque[float] = deque(maxlen=window)
        self.scan_s: deque[float] = deque(maxlen=window)
        self._t_enter: Optional[float] = None

    # -- recording --------------------------------------------------------
    def time_dispatch(self):
        """Context manager timing one step dispatch."""
        return _Timed(self.dispatch_s, self.tracer, "dispatch", self.track)

    def time_sync(self):
        """Context manager timing one host<-device drain (the barrier)."""
        return _Timed(self.sync_s, self.tracer, "sync", self.track)

    def time_h2d(self):
        """Context manager timing one k-group host->device staging (runs on
        the prefetch thread — overlaps the current scan)."""
        return _Timed(self.h2d_s, self.tracer, "h2d", self.track + ":h2d")

    def time_scan(self):
        """Context manager timing one fused k-step scan dispatch."""
        return _Timed(self.scan_s, self.tracer, "scan", self.track)

    # -- reporting --------------------------------------------------------
    def local_summary(self) -> dict[str, dict[str, float]]:
        out = {}
        if self.dispatch_s:
            out["dispatch"] = _pct(self.dispatch_s)
        if self.sync_s:
            out["sync"] = _pct(self.sync_s)
        if self.h2d_s:
            out["h2d"] = _pct(self.h2d_s)
        if self.scan_s:
            out["scan"] = _pct(self.scan_s)
        return out

    def straggler_summary(self) -> Optional[dict[str, float]]:
        """Cross-process mean step-time table (multi-host only): allgather
        each process's mean dispatch+sync and report the skew — the
        reference's per-trainer avgGap table collapsed to its actionable
        numbers (slowest process and slow/mean ratio)."""
        import jax
        if jax.process_count() <= 1 or not (self.dispatch_s or self.sync_s):
            return None
        from jax.experimental import multihost_utils
        mine = np.asarray([
            float(np.mean(self.dispatch_s)) if self.dispatch_s else 0.0,
            float(np.mean(self.sync_s)) if self.sync_s else 0.0,
        ])
        table = np.asarray(multihost_utils.process_allgather(mine))  # [P, 2]
        per_proc = table.sum(axis=1)
        mean = float(per_proc.mean()) or 1e-12
        slowest = int(per_proc.argmax())
        return {
            "slowest_process": slowest,
            "slowest_ms": float(per_proc[slowest]) * 1e3,
            "mean_ms": mean * 1e3,
            "skew": float(per_proc[slowest]) / mean,
        }

    def render(self) -> str:
        """One log line, emitted every log_period on mesh runs."""
        parts = [_fmt_pct(k, v) for k, v in self.local_summary().items()]
        strag = self.straggler_summary()
        if strag is not None:
            parts.append(
                f"straggler: process {strag['slowest_process']} "
                f"{strag['slowest_ms']:.2f}ms vs mean {strag['mean_ms']:.2f}ms "
                f"(skew {strag['skew']:.2f}x)")
        return "; ".join(parts) if parts else "no samples"


class _Timed:
    def __init__(self, sink: deque, tracer=None, name: str = "",
                 track: str = "trainer"):
        self.sink = sink
        self.tracer = tracer
        self.name = name
        self.track = track

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        self.sink.append(dt)
        t = self.tracer
        if t is not None and t.enabled:
            t.add(self.name, self.t0, dt, track=self.track)
        return False
