"""Data-parallel (and tensor-sharded) training via shardings.

TPU-native replacement for BOTH of the reference's data-parallel paths:
  - single-node thread DP with its ring gradient gather / value scatter
    (ref: gserver/gradientmachines/MultiGradientMachine.{h,cpp}:61-90), and
  - multi-node parameter-server sync SGD (ref: paddle/pserver/ParameterServer2
    addGradient/sendBackParameter; trainer/RemoteParameterUpdater.cpp).

Re-design: parameters are replicated (or sharded by `partition_spec`) over the
mesh, batches are sharded on the `data` axis, and XLA inserts the gradient
all-reduce over ICI during the backward pass — overlapping it with remaining
computation exactly like the reference's pipelined per-parameter update
callbacks, but scheduled by the compiler.  The pserver's sharded-optimizer
trick (each server updates 1/N of every parameter) maps to optionally sharding
optimizer slots with the same partition specs.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.config.schema import ModelConfig
from paddle_tpu.parallel.mesh import DATA_AXIS, axis_size
from paddle_tpu.parameter.argument import Argument


def param_sharding(mesh: Mesh, partition_spec: Optional[list]) -> NamedSharding:
    """partition_spec like ['model', None] -> NamedSharding; None -> replicated."""
    if not partition_spec:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(*[a if a else None for a in partition_spec]))


def global_put(x, sharding: NamedSharding):
    """device_put that also works on multi-process meshes: every process
    holds the same full host value (deterministic seeded init / loaded
    checkpoint) and materializes only its addressable shards — device_put
    cannot target non-addressable devices.  Use for REPLICATED host data
    (params, slots, identical copies); per-process-distinct data goes
    through jax.make_array_from_process_local_data instead."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


_global_put = global_put


def effective_zero_stage(opt_config) -> int:
    """ZeRO stage from an OptimizationConfig: zero_stage, floored at 1 when
    the older shard_optimizer_state flag is set."""
    stage = int(getattr(opt_config, "zero_stage", 0))
    if getattr(opt_config, "shard_optimizer_state", False):
        stage = max(stage, 1)
    return stage


def _zero_eligible(spec, n_data: int, leaf) -> bool:
    """A leaf can shard its leading dim over `data`: no explicit (tp/emb)
    spec, a divisible leading dim, and a real array."""
    return (not spec and n_data > 1 and hasattr(leaf, "ndim")
            and leaf.ndim >= 1 and leaf.shape[0] % n_data == 0)


def effective_param_specs(mesh: Mesh, model: ModelConfig) -> dict:
    """Per-parameter partition specs INCLUDING the implicit vocab-dim
    defaulting for sparse_update embedding tables (parallel/sparse.py) —
    the single source of eligibility for params, slots AND gradients, so
    the three can never disagree about a parameter's home axis."""
    from paddle_tpu.parallel.sparse import embedding_partition_spec
    specs = {p.name: p.partition_spec for p in model.parameters}
    emb_spec = embedding_partition_spec(mesh)
    if emb_spec is not None:
        n_emb = axis_size(mesh, emb_spec[0])
        for p in model.parameters:
            if p.sparse_update and not p.partition_spec \
                    and len(p.dims) == 2 and p.dims[0] % n_emb == 0:
                specs[p.name] = emb_spec
    return specs


def zero_grad_shardings(mesh: Mesh, model: ModelConfig,
                        params: dict) -> dict[str, Optional[NamedSharding]]:
    """Per-parameter gradient shardings for ZeRO stage >= 2: the gradient of
    every eligible parameter is reduce-scattered onto the data axis (XLA
    replaces its all-reduce) so the optimizer update runs sharded — the
    pserver addGradient design, where each server only ever receives its
    own 1/N of each gradient (ref: ParameterServer2.h:501 addGradient +
    :120-145 block maps).  Explicitly-sharded params (tp, vocab-sharded
    embeddings) are left alone — their gradients already follow the
    parameter's own axis."""
    specs = effective_param_specs(mesh, model)
    n_data = axis_size(mesh, DATA_AXIS)
    return {name: NamedSharding(mesh, P(DATA_AXIS))
            if _zero_eligible(specs.get(name), n_data, leaf) else None
            for name, leaf in params.items()}


def shard_train_objects(mesh: Mesh, model: ModelConfig, params: dict,
                        opt_state: Any, shard_opt: bool = False,
                        zero_stage: int = 0):
    """Place params (+ optimizer slots) on the mesh per their partition specs.
    Parameters marked sparse_update (embedding tables) default to vocab-dim
    sharding — the pserver-shard analog (see parallel/sparse.py).

    shard_opt=True (ZeRO-1; settings(shard_optimizer_state=True)) shards
    every optimizer slot buffer's leading dim over the `data` axis — the
    TPU-native form of the pserver design where each server holds and
    updates 1/N of every parameter's optimizer state (ref:
    ParameterServer2's per-server parameter blocks); XLA partitions the
    update math along the slot sharding and inserts the gathers the next
    step needs.  Slots of explicitly-sharded (tp) parameters keep their
    parameter's spec; leaves whose leading dim doesn't divide stay
    replicated.

    zero_stage extends this (settings(zero_stage=N)): stage >= 1 implies
    shard_opt; stage >= 3 (FSDP) also stores every eligible PARAMETER
    sharded on its leading dim — XLA all-gathers a parameter just before
    use and discards the gathered copy, and the sharded optimizer update
    writes each shard in place (grads arrive reduce-scattered via
    zero_grad_shardings at stage >= 2)."""
    shard_opt = shard_opt or zero_stage >= 1
    specs = effective_param_specs(mesh, model)
    n_data = axis_size(mesh, DATA_AXIS)
    if zero_stage >= 3:
        # FSDP parameter sharding: eligible params get P(data) on dim 0 so
        # their slots/grads/update all follow the same shards
        for name, v in params.items():
            if name in specs and specs[name]:
                continue
            if _zero_eligible(specs.get(name), n_data, v):
                specs[name] = [DATA_AXIS] + [None] * (np.ndim(v) - 1)

    out_params = {
        name: _global_put(v, param_sharding(mesh, specs.get(name)))
        for name, v in params.items()
    }

    def slot_sharding(name, leaf):
        spec = specs.get(name)
        if shard_opt and _zero_eligible(spec, n_data, leaf):
            return NamedSharding(mesh, P(DATA_AXIS))
        if spec and hasattr(leaf, "ndim") and leaf.ndim != len(spec):
            # a slot whose rank differs from its parameter's (e.g. a scalar
            # accumulator) cannot reuse the parameter's spec
            return NamedSharding(mesh, P())
        return param_sharding(mesh, spec)

    def place_slots(slots_for_param, name):
        return jax.tree.map(
            lambda x: _global_put(x, slot_sharding(name, x)), slots_for_param)

    opt_state = dict(opt_state)
    if "slots" in opt_state:
        opt_state["slots"] = {
            name: place_slots(s, name) for name, s in opt_state["slots"].items()}
    if "average" in opt_state:
        opt_state["average"] = {
            name: place_slots(v, name)
            for name, v in opt_state["average"].items()}
    if "grad_accum" in opt_state:
        # gradient accumulators follow their parameter's spec (like
        # averaging copies); ZeRO slot-sharding applies to them too
        opt_state["grad_accum"] = {
            name: place_slots(v, name)
            for name, v in opt_state["grad_accum"].items()}
    return out_params, opt_state


def stage_stacked_batch(mesh: Mesh, stacked):
    """Device-stage a k-group: a pytree of [k, B, ...] arrays (k batches
    stacked along a leading step axis) placed with the STEP axis replicated
    and the batch axis sharded over `data` — each scanned step then sees
    exactly what `shard_batch` gives the per-batch path.  Multi-process:
    every process stages its OWN k local batches and the global array
    concatenates them along the batch dim (device_put cannot target
    non-addressable devices)."""
    sh = NamedSharding(mesh, P(None, DATA_AXIS))
    multiproc = jax.process_count() > 1

    def place(x):
        if not (hasattr(x, "ndim") and x.ndim >= 2):
            return x
        if multiproc:
            return jax.make_array_from_process_local_data(sh, np.asarray(x))
        return jax.device_put(x, sh)

    return jax.tree.map(place, stacked)


def shard_batch(mesh: Mesh, batch: dict[str, Argument]) -> dict[str, Argument]:
    """Shard every array's leading (batch) dim over the data axis — the analog
    of MultiGradientMachine slicing inArgs per thread (ref: .h:330-340).

    Single-process: a plain device_put.  Multi-process (jax.distributed):
    each process feeds its OWN local batch — the per-host data-parallel
    input pipeline, like each trainer of the pserver fleet reading its own
    file shard — and the local batches concatenate along the batch dim
    into the global array (device_put cannot target non-addressable
    devices)."""
    sh = NamedSharding(mesh, P(DATA_AXIS))
    multiproc = jax.process_count() > 1

    def place(x):
        if not (hasattr(x, "ndim") and x.ndim >= 1):
            return x
        if multiproc:
            return jax.make_array_from_process_local_data(sh, np.asarray(x))
        return jax.device_put(x, sh)

    return jax.tree.map(place, batch)
