"""Distributed vector math over sharded parameter vectors — the TPU analog
of the pserver's `doOperation` algebra.

The reference lets a trainer-side controller run vector math ON the
parameter servers: `PreparedOperations` batches opcodes (ref:
pserver/ParameterClient2.h:53-120 `addOperation(optype, args...)`), ships
them in one `DoOperationRequest`, and each pserver executes them over its
1/N block of the global vector, returning partial scalars the client sums
(ref: pserver/ParameterServer2.h:402 doOperation; :660-705 op table).  This
is the substrate for remote L-BFGS/OWL-QN: the full parameter vector never
visits one machine.

On TPU the whole RPC layer collapses: a 'pserver vector' is a jax.Array
sharded over the mesh, and every op below is a jnp one-liner that XLA
partitions automatically — `utv` compiles to a shard-local partial dot plus
one psum over ICI, exactly the pserver's partial-scalar-then-client-sum
dance, and the elementwise ops never communicate at all.  Ops are
functional (new arrays, no in-place mutation); under jit the buffer reuse
the reference got from writing in place comes back via donation.

The OWL-QN-specific opcodes (ref: ParameterServer2.cpp:1293-1385) are kept
with their exact semantics so the reference's remote optimizer loop can be
transcribed term-for-term against sharded arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def utv(u: Array, v: Array) -> Array:
    """Global inner product u.v (ref: op_utv, ParameterServer2.cpp:1231).

    Accumulates in float32 — NARROWER than the reference's double
    accumulator (TPUs have no fast f64): expect ~3-4 fewer significant
    digits on 1e7+-element vectors.  An outer optimizer needing tighter
    dots should chunk-and-sum on host (jnp.float64 under
    jax_enable_x64) — the collective structure stays the same."""
    return jnp.vdot(u.astype(jnp.float32), v.astype(jnp.float32))


def au(u: Array, a) -> Array:
    """a*u (ref: op_au, ParameterServer2.cpp:1267)."""
    return a * u


def au_bv(u: Array, v: Array, a, b) -> Array:
    """a*u + b*v, the axpby kernel every L-BFGS two-loop step is made of
    (ref: op_au_bv, ParameterServer2.cpp:1243)."""
    return a * u + b * v


def au_bv_cw(u: Array, v: Array, w: Array, a, b, c) -> Array:
    """a*u + b*v + c*w (ref: op_au_bv_cw, ParameterServer2.cpp:1278)."""
    return a * u + b * v + c * w


def make_steepest_desc_dir(grad: Array, x: Array, l1weight) -> Array:
    """OWL-QN pseudo-gradient descent direction: -grad shifted by the L1
    subgradient, zeroed where the subdifferential contains 0
    (ref: op_make_steepest_desc_dir, ParameterServer2.cpp:1293-1316)."""
    neg = -grad + l1weight
    pos = -grad - l1weight
    at_zero = jnp.where(grad < -l1weight, pos,
                        jnp.where(grad > l1weight, neg, 0.0))
    return jnp.where(x < 0, neg, jnp.where(x > 0, pos, at_zero))


def fix_dir_signs(dir: Array, steepest_desc_dir: Array) -> Array:
    """Zero direction components disagreeing with the steepest-descent
    orthant (ref: op_fix_dir_signs, ParameterServer2.cpp:1318)."""
    return jnp.where(dir * steepest_desc_dir <= 0, 0.0, dir)


def dir_deriv(dir: Array, grad: Array, x: Array, l1weight) -> Array:
    """Directional derivative of f + l1*|x| along `dir`
    (ref: op_dir_deriv, ParameterServer2.cpp:1344-1366)."""
    shifted = jnp.where(
        x < 0, grad - l1weight,
        jnp.where(x > 0, grad + l1weight,
                  jnp.where(dir < 0, grad - l1weight, grad + l1weight)))
    return jnp.sum(jnp.where(dir != 0, dir * shifted, 0.0)
                   .astype(jnp.float32))


def fix_omega_signs(x: Array, newx: Array) -> Array:
    """Project the trial point back into x's orthant: zero coordinates that
    crossed zero (ref: op_fix_omega_signs, ParameterServer2.cpp:1331)."""
    return jnp.where(x * newx < 0, 0.0, newx)


def l1_cost(x: Array, l1weight) -> Array:
    """The L1 penalty term the pserver added server-side
    (ref: op_cost, ParameterServer2.cpp:1368-1385)."""
    return l1weight * jnp.sum(jnp.abs(x).astype(jnp.float32))
