"""Sharded embedding tables — the TPU replacement for the reference's
sparse-parameter machinery.

The reference keeps big embedding tables on parameter servers and moves only
the touched rows: `GradientMachine::prefetch` collects the row ids a batch
needs, `SparsePrefetchRowCpuMatrix` pulls them (ref:
paddle/math/SparseRowMatrix.h:211; trainer/TrainerInternal.cpp:93-97), and
`SparseRemoteParameterUpdater` pushes row-sparse gradients back over dedicated
pserver ports (ref: trainer/RemoteParameterUpdater.h:244-335,
--ports_num_for_sparse).

TPU re-design: the table lives sharded over a mesh axis — each device owns a
contiguous `vocab/N` row block (the analog of a pserver shard).  Lookup is a
local gather of owned rows with zeros elsewhere, followed by one `psum` over
the owning axis (one ICI all-reduce replaces the prefetch RPC round-trip).
Autodiff through the psum+where gives each device a gradient touching ONLY
its own rows — the row-sparse update economics of the reference, with the
optimizer applying shard-locally like `ParameterServer2::blockTraverse`.

Two paths:
  * implicit — mark the parameter `sparse_update=True`; `shard_train_objects`
    (parallel/dp.py) shards its vocab dim and XLA GSPMD partitions the gather.
  * explicit — `sharded_embedding_lookup` inside `shard_map`, for when the
    GSPMD choice is poor (e.g. it all-gathers the table).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def embedding_partition_spec(mesh: Mesh) -> Optional[list]:
    """Vocab-dim spec for a sharded table: prefer the model axis, fall back
    to data (FSDP-style) — mirrors the reference striping tables over ALL
    pserver instances (ref: ParameterClient2 sendAndReceiveParameter)."""
    from paddle_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axes.get(MODEL_AXIS, 1) > 1:
        return [MODEL_AXIS, None]
    if axes.get(DATA_AXIS, 1) > 1:
        return [DATA_AXIS, None]
    return None


def local_shard_lookup(table_shard: Array, ids: Array, axis_name: str) -> Array:
    """One device's contribution to an embedding lookup, inside shard_map.

    table_shard: [V/N, D] — this device's contiguous row block.
    ids: [...] global row ids (identical on every device of `axis_name`).
    Returns [..., D] after a psum over `axis_name`.
    """
    shard_rows = table_shard.shape[0]
    shard_idx = jax.lax.axis_index(axis_name)
    base = shard_idx * shard_rows
    local = ids - base
    owned = (local >= 0) & (local < shard_rows)
    rows = jnp.take(table_shard, jnp.clip(local, 0, shard_rows - 1), axis=0)
    rows = jnp.where(owned[..., None], rows, 0.0)
    return jax.lax.psum(rows, axis_name)


def sharded_embedding_lookup(mesh: Mesh, table: Array, ids: Array,
                             axis: Optional[str] = None) -> Array:
    """Explicit sharded lookup: shard `table` rows over `axis`, replicate
    `ids`, one psum over ICI.  Differentiable; the table gradient is
    computed shard-locally."""
    from paddle_tpu.parallel.mesh import MODEL_AXIS
    from paddle_tpu.utils.jax_compat import shard_map
    axis = axis or MODEL_AXIS

    fn = shard_map(
        partial(local_shard_lookup, axis_name=axis),
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
    )
    return fn(table, ids)


# ---------------------------------------------------------------------------
# shard-traffic diagnostics (ref: pserver/SparseParameterDistribution.{h,cpp})
# ---------------------------------------------------------------------------

def sharded_table_feeds(mesh: Mesh, model) -> dict[str, tuple[list[str], int, int]]:
    """Map each vocab-sharded sparse_update table to the data layers whose
    ids index it: {param_name: (input_layer_names, vocab, n_shards)}.

    A table is fed wherever a layer input names the parameter and either
    carries a `table` projection or belongs to a layer type that indexes its
    weight by ids (table_projection / selective_fc's gather path).  Only the
    edges whose source layer's ids actually arrive in the batch (data layers)
    can be probed host-side — in-graph id producers are skipped, like the
    reference only probing what prepareSendData ships."""
    from paddle_tpu.parallel.dp import effective_param_specs
    from paddle_tpu.parallel.mesh import axis_size
    specs = effective_param_specs(mesh, model)
    data_layers = {l.name for l in model.layers if l.type == "data"}
    out: dict[str, tuple[list[str], int, int]] = {}
    for p in model.parameters:
        spec = specs.get(p.name)
        if not (p.sparse_update and spec and len(p.dims) == 2):
            continue
        n = axis_size(mesh, spec[0]) if spec[0] else 1
        if n <= 1:
            continue
        feeds = []
        for layer in model.layers:
            for inp in layer.inputs:
                if inp.input_parameter_name != p.name:
                    continue
                if inp.input_layer_name in data_layers \
                        and inp.input_layer_name not in feeds:
                    feeds.append(inp.input_layer_name)
        if feeds:
            out[p.name] = (feeds, p.dims[0], n)
    return out


class SparseShardStats:
    """Row-touch balance check for vocab-sharded tables — the TPU analog of
    the reference's SparseParameterDistribution (ref:
    pserver/SparseParameterDistribution.cpp:49-119): there the client
    counted bytes shipped to each pserver for sparse parameters and, after
    `check_sparse_distribution_batches`, crashed if too many batches were
    unbalanced.  Here the 'traffic' is which table shard each batch's ids
    touch: an id-skewed dataset concentrates gather+grad work (and, on the
    explicit path, psum payload utility) on one device's rows.

    Same flags, same thresholds: a batch is unbalanced when any shard's
    touch count exceeds `unbalance_degree` x the mean or falls below
    mean / `unbalance_degree`; after `batches` probes, raise if the
    unbalanced fraction exceeds `ratio` (strict=True) else warn."""

    def __init__(self, tables: dict[str, tuple[list[str], int, int]],
                 batches: int = 100, unbalance_degree: float = 2.0,
                 ratio: float = 0.6, strict: bool = True,
                 show_log: bool = False):
        import numpy as np
        self.tables = tables
        self.batches = batches
        self.unbalance_degree = unbalance_degree
        self.ratio = ratio
        self.strict = strict
        self.show_log = show_log
        self.counts = {name: np.zeros(n, dtype=np.int64)
                       for name, (_, _, n) in tables.items()}
        self.batch_passed = 0
        self.unbalance_cnt = 0
        self.done = False
        # hard cap on probes: batches that never meet the evidence
        # threshold must not pay the host id-fetch forever
        self.probe_budget = 10 * max(batches, 1)

    def probe_batch(self, batch: dict) -> None:
        """Accumulate one batch's per-shard touch counts and run the
        per-batch balance check (ref: probeDistribution +
        checkAndResetDistribution, called once per prepareSendData)."""
        import numpy as np
        if self.done:
            return
        self.probe_budget -= 1
        if self.probe_budget < 0:
            from paddle_tpu.utils.logger import get_logger
            get_logger("sparse_dist").info(
                "sparse distribution check stopping: probe budget spent "
                "with only %d/%d judged batches (per-batch id counts too "
                "small to carry balance evidence)", self.batch_passed,
                self.batches)
            self.done = True
            return
        touched = False
        for name, (feeds, vocab, n) in self.tables.items():
            # ceil like GSPMD's uneven sharding (and explicit specs need not
            # divide evenly), so ids map to the shard that actually owns them
            shard_rows = -(-vocab // n)
            for feed in feeds:
                arg = batch.get(feed)
                ids = getattr(arg, "ids", None)
                if ids is None:
                    continue
                ids = np.asarray(jax.device_get(ids))
                lengths = getattr(arg, "lengths", None)
                if lengths is not None and ids.ndim == 2:
                    # padded cells are not traffic — the feeder pads id
                    # slots with 0, which would inflate shard 0's count
                    valid = (np.arange(ids.shape[1])[None, :]
                             < np.asarray(jax.device_get(lengths))[:, None])
                    flat = ids[valid]
                else:
                    flat = ids.reshape(-1)
                flat = flat[(flat >= 0) & (flat < vocab)]
                if flat.size == 0:
                    continue
                self.counts[name] += np.bincount(
                    np.minimum(flat // shard_rows, n - 1), minlength=n)
                touched = True
        if touched:
            self._check_and_reset()

    def _check_and_reset(self) -> None:
        import numpy as np
        from paddle_tpu.utils.logger import get_logger
        log = get_logger("sparse_dist")
        unbalanced = False
        judged = False
        for name, c in self.counts.items():
            tot = int(c.sum())
            if self.show_log and tot:
                log.info("sparse distribution %s: %s rows/shard", name,
                         c.tolist())
            # a batch with fewer than ~16 ids per shard carries no balance
            # evidence: with avg touches a ~ Poisson(tot/n), the low-side
            # test (c*degree < avg) false-positives with non-trivial
            # probability until avg >= ~16 — don't judge such batches
            if tot < 16 * len(c):
                continue
            judged = True
            avg = tot / len(c)
            if (c > self.unbalance_degree * avg).any() or \
                    (c * self.unbalance_degree < avg).any():
                unbalanced = True
        if not judged:
            for c in self.counts.values():
                c[:] = 0
            return
        self.unbalance_cnt += int(unbalanced)
        self.batch_passed += 1
        if self.batch_passed >= self.batches:
            self.done = True
            frac = self.unbalance_cnt / self.batch_passed
            for name, c in self.counts.items():
                log.info("last sparse distribution sample %s: %s", name,
                         c.tolist())
            log.info("unbalanced sparse batches: %d / %d",
                     self.unbalance_cnt, self.batch_passed)
            if frac > self.ratio:
                msg = (f"unbalanced sparse id distribution across table "
                       f"shards ({self.unbalance_cnt}/{self.batch_passed} "
                       f"batches > degree {self.unbalance_degree}): id-skew "
                       f"concentrates embedding work on one device — try "
                       f"shuffling/remapping ids (ref: "
                       f"SparseParameterDistribution.cpp:108-118)")
                if self.strict:
                    raise RuntimeError(msg)
                log.warning(msg)
        for c in self.counts.values():
            c[:] = 0
