"""Sharded embedding tables — the TPU replacement for the reference's
sparse-parameter machinery.

The reference keeps big embedding tables on parameter servers and moves only
the touched rows: `GradientMachine::prefetch` collects the row ids a batch
needs, `SparsePrefetchRowCpuMatrix` pulls them (ref:
paddle/math/SparseRowMatrix.h:211; trainer/TrainerInternal.cpp:93-97), and
`SparseRemoteParameterUpdater` pushes row-sparse gradients back over dedicated
pserver ports (ref: trainer/RemoteParameterUpdater.h:244-335,
--ports_num_for_sparse).

TPU re-design: the table lives sharded over a mesh axis — each device owns a
contiguous `vocab/N` row block (the analog of a pserver shard).  Lookup is a
local gather of owned rows with zeros elsewhere, followed by one `psum` over
the owning axis (one ICI all-reduce replaces the prefetch RPC round-trip).
Autodiff through the psum+where gives each device a gradient touching ONLY
its own rows — the row-sparse update economics of the reference, with the
optimizer applying shard-locally like `ParameterServer2::blockTraverse`.

Two paths:
  * implicit — mark the parameter `sparse_update=True`; `shard_train_objects`
    (parallel/dp.py) shards its vocab dim and XLA GSPMD partitions the gather.
  * explicit — `sharded_embedding_lookup` inside `shard_map`, for when the
    GSPMD choice is poor (e.g. it all-gathers the table).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def embedding_partition_spec(mesh: Mesh) -> Optional[list]:
    """Vocab-dim spec for a sharded table: prefer the model axis, fall back
    to data (FSDP-style) — mirrors the reference striping tables over ALL
    pserver instances (ref: ParameterClient2 sendAndReceiveParameter)."""
    from paddle_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axes.get(MODEL_AXIS, 1) > 1:
        return [MODEL_AXIS, None]
    if axes.get(DATA_AXIS, 1) > 1:
        return [DATA_AXIS, None]
    return None


def local_shard_lookup(table_shard: Array, ids: Array, axis_name: str) -> Array:
    """One device's contribution to an embedding lookup, inside shard_map.

    table_shard: [V/N, D] — this device's contiguous row block.
    ids: [...] global row ids (identical on every device of `axis_name`).
    Returns [..., D] after a psum over `axis_name`.
    """
    shard_rows = table_shard.shape[0]
    shard_idx = jax.lax.axis_index(axis_name)
    base = shard_idx * shard_rows
    local = ids - base
    owned = (local >= 0) & (local < shard_rows)
    rows = jnp.take(table_shard, jnp.clip(local, 0, shard_rows - 1), axis=0)
    rows = jnp.where(owned[..., None], rows, 0.0)
    return jax.lax.psum(rows, axis_name)


def sharded_embedding_lookup(mesh: Mesh, table: Array, ids: Array,
                             axis: Optional[str] = None) -> Array:
    """Explicit sharded lookup: shard `table` rows over `axis`, replicate
    `ids`, one psum over ICI.  Differentiable; the table gradient is
    computed shard-locally."""
    from jax import shard_map
    from paddle_tpu.parallel.mesh import MODEL_AXIS
    axis = axis or MODEL_AXIS

    fn = shard_map(
        partial(local_shard_lookup, axis_name=axis),
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
    )
    return fn(table, ids)
