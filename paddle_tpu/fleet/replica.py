"""The router's replica table: registration state + polled health.

One `Replica` per registered engine-pump server.  The router owns all
mutation (single asyncio loop — same no-cross-thread-mutation discipline
as the serving server's pump); everything here is plain bookkeeping so it
stays unit-testable without sockets.

State machine:

    JOINING --connect+hello ok--> HEALTHY
    HEALTHY --ctl drain--> DRAINING --ctl undrain--> HEALTHY
    HEALTHY/DRAINING --polled pump wedged--> BROKEN --beat recovers--> back
    any --connection lost / heartbeat expiry / ctl leave--> DEAD (dropped)

Placement only ever considers HEALTHY replicas; DRAINING and BROKEN stay
in the table (their in-flight work may still finish — a draining replica
is SUPPOSED to finish it) but receive nothing new.  DEAD replicas are
removed; their not-yet-streamed requests retry elsewhere.
"""

from __future__ import annotations

import time
from typing import Optional

JOINING = "joining"
HEALTHY = "healthy"
DRAINING = "draining"
BROKEN = "broken"          # circuit open: pump wedged/dead per polled stats
DEAD = "dead"

#: states a replica can be placed on
PLACEABLE = (HEALTHY,)
#: states the poller keeps polling (everything still in the table)
POLLABLE = (HEALTHY, DRAINING, BROKEN)


class Replica:
    """One registered engine-pump server, as the router sees it."""

    __slots__ = ("rid", "host", "port", "state", "hello", "stats",
                 "last_poll_t", "poll_fails", "pending", "external",
                 "joined_t", "backend", "routed_total", "broken_reason",
                 "drain_requested", "polling")

    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = int(port)
        self.state = JOINING
        # drain survives a circuit-break episode: a replica that wedges
        # WHILE draining must come back as draining, not placeable
        self.drain_requested = False
        self.polling = False           # one in-flight stats poll at a time
        self.hello: dict = {}          # the replica's hello reply
        self.stats: dict = {}          # last polled stats frame
        self.last_poll_t: Optional[float] = None
        self.poll_fails = 0
        # router-owned outstanding request ids (grid -> True): exact and
        # fresh, unlike the polled inflight — this is the primary load
        # signal between polls
        self.pending: set = set()
        # polled inflight the router did NOT place (other direct clients
        # of the replica), computed at poll time — the least-loaded score
        # must see traffic it never routed
        self.external = 0
        self.joined_t = time.monotonic()
        self.backend = None            # fleet.router._Backend, once up
        self.routed_total = 0
        self.broken_reason = ""

    # -- identity ----------------------------------------------------------
    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- capacity / load ---------------------------------------------------
    @property
    def max_inflight(self) -> int:
        """Admission cap learned from the hello handshake (fallback: the
        last polled stats; final fallback 1 so an unknown replica is
        conservatively one-request saturated rather than unbounded)."""
        v = self.hello.get("max_inflight") or self.stats.get("max_inflight")
        return max(1, int(v)) if v else 1

    @property
    def page_size(self) -> int:
        return int(self.hello.get("page_size") or 0)

    @property
    def role(self) -> str:
        """Advertised placement role (hello `role_mode`): "prefill",
        "decode", or "both".  ADVISORY — any replica can serve any
        request; the router's disaggregated placement tiers read it, and
        normal placement merely prefers non-prefill replicas when any
        exist (docs/serving.md "Disaggregated prefill/decode")."""
        return str(self.hello.get("role_mode") or "both")

    def load(self) -> int:
        """Requests this replica is carrying as far as the router knows:
        its own outstanding placements (exact) plus the externally-placed
        inflight seen at the last poll (stale but better than blind)."""
        return len(self.pending) + self.external

    def saturated(self) -> bool:
        return self.load() >= self.max_inflight

    def score(self) -> tuple:
        """Least-loaded ordering key — lower is better.  Primary: load
        fraction of the admission cap (the queue-depth signal: pending
        beyond the slots IS the replica's queue).  Secondary: KV page
        occupancy from the last poll (two equally-loaded replicas break
        toward the one with more free pages, where a long prompt is least
        likely to pause or preempt).  Final: rid, for determinism."""
        frac = self.load() / self.max_inflight
        pages = self.stats.get("pages_in_use") or 0
        num = self.stats.get("num_pages") or 0
        page_frac = pages / num if num else 0.0
        return (frac, page_frac, self.rid)

    def poll_age_s(self) -> float:
        if self.last_poll_t is None:
            return -1.0
        return time.monotonic() - self.last_poll_t

    def absorb_poll(self, stats: dict) -> None:
        """Record one stats reply; recompute the external-traffic term."""
        self.stats = stats
        self.last_poll_t = time.monotonic()
        self.poll_fails = 0
        inflight = int(stats.get("inflight") or 0)
        self.external = max(0, inflight - len(self.pending))

    def pump_wedged(self, wedge_age_s: float) -> str:
        """Non-empty reason iff the last poll shows a wedged/dead pump —
        the per-replica circuit-breaker predicate (stats are polled
        stale_ok, so they stay readable while the pump is stuck; see
        serving/server.py's watchdog)."""
        if not self.stats:
            return ""
        if self.stats.get("pump_alive") is False:
            return "pump_alive=false"
        age = self.stats.get("pump_last_step_age_s")
        if age is not None and float(age) > wedge_age_s:
            return f"pump_last_step_age_s={float(age):.1f}s"
        return ""

    def summary(self) -> dict:
        """One row of the router's fleet stats frame."""
        s = self.stats
        return {
            "replica": self.rid, "addr": self.addr, "state": self.state,
            "role": self.role,
            "draining": self.drain_requested,
            "pending": len(self.pending), "external": self.external,
            "max_inflight": self.max_inflight,
            "routed_total": self.routed_total,
            "poll_age_s": round(self.poll_age_s(), 3),
            "poll_fails": self.poll_fails,
            "broken_reason": self.broken_reason,
            # the KV-awareness inputs, echoed so an operator sees what
            # placement saw
            "queue_depth": s.get("queue_depth"),
            "slots_in_use": s.get("slots_in_use"),
            "num_slots": s.get("num_slots"),
            "pages_in_use": s.get("pages_in_use"),
            "num_pages": s.get("num_pages"),
            "inflight": s.get("inflight"),
            "pump_last_step_age_s": s.get("pump_last_step_age_s"),
            "prefix_hits": s.get("prefix_hits"),
            "prefix_misses": s.get("prefix_misses"),
            # cross-replica kv transfer, echoed from the polled stats so
            # `ctl list` shows each replica's disagg traffic in place
            "kv_pushes": s.get("kv_pushes"),
            "kv_push_failures": s.get("kv_push_failures"),
            "kv_pages_shipped": s.get("kv_pages_shipped"),
            "kv_pages_received": s.get("kv_pages_received"),
        }


class ReplicaTable:
    """All registered replicas, keyed by router-assigned id r0, r1, ..."""

    def __init__(self):
        self._seq = 0
        self.replicas: dict[str, Replica] = {}

    @property
    def ever_registered(self) -> bool:
        """True once any replica has ever joined — losing the LAST
        replica is a total-fleet-unhealthy event, an empty table at
        startup is not."""
        return self._seq > 0

    def add(self, host: str, port: int) -> Replica:
        r = Replica(f"r{self._seq}", host, port)
        self._seq += 1
        self.replicas[r.rid] = r
        return r

    def drop(self, rid: str) -> Optional[Replica]:
        r = self.replicas.pop(rid, None)
        if r is not None:
            r.state = DEAD
        return r

    def get(self, rid: str) -> Optional[Replica]:
        return self.replicas.get(rid)

    def by_addr(self, host: str, port: int) -> Optional[Replica]:
        for r in self.replicas.values():
            if r.host == host and r.port == int(port):
                return r
        return None

    def __iter__(self):
        return iter(list(self.replicas.values()))

    def __len__(self):
        return len(self.replicas)

    def in_state(self, *states: str) -> list[Replica]:
        return [r for r in self.replicas.values() if r.state in states]

    def placeable(self) -> list[Replica]:
        """Replicas a new request may land on: healthy AND not saturated.
        Empty while any are registered = the fleet-level overload
        condition (shed, never queue unboundedly)."""
        return [r for r in self.replicas.values()
                if r.state in PLACEABLE and not r.saturated()]

    def counts(self) -> dict:
        out = {HEALTHY: 0, DRAINING: 0, BROKEN: 0, JOINING: 0}
        for r in self.replicas.values():
            out[r.state] = out.get(r.state, 0) + 1
        return out
