"""KV-aware placement: prefix affinity first, least-loaded otherwise.

Why affinity matters: PR 7's prefix cache lives INSIDE each replica — a
cached system prompt only pays off when the next request sharing it lands
on the SAME replica.  Under naive fan-out a pool of K popular prefixes
spread over N replicas costs ~K*N cold prefills instead of K, and the
steady-state hit rate drops with every replica added (the fleet bench's
affinity-vs-random A/B measures exactly this).

The index keys on the FIRST page_size-aligned token run of the prompt —
`tuple(prompt[:page_size])` — deliberately mirroring
`serving/prefix_tree.py`'s node granularity: two prompts that agree on
that run share at least one cached page on whichever replica saw either
first, and prompts shorter than one page have nothing cacheable to steer
by (they place least-loaded).  Hashing deeper would split traffic that
shares a long prefix but diverges late (worse: those requests WANT the
same replica); hashing shallower than a page would collide prompts that
share no cached page at all.

The index is a bounded LRU (capacity knob): the router stays a thin
stateless-restartable tier — losing the index costs a few extra cold
prefills, never correctness.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from paddle_tpu.fleet.replica import Replica

#: placement reasons — the `policy` label on fleet_placements_total and
#: the flight recorder's `route` events
AFFINITY = "affinity"
LEAST_LOADED = "least_loaded"
RANDOM = "random"
#: not a mode — the placement REASON stamped when the router routes the
#: decode half of a disaggregated prefill/decode request at the replica
#: its KV pages were just kv_push-mounted on (docs/serving.md)
DISAGG = "disagg"


class AffinityIndex:
    """Bounded LRU: first-page token run -> replica id."""

    def __init__(self, window: int, capacity: int = 8192):
        self.window = int(window)
        self.capacity = int(capacity)
        self._map: OrderedDict = OrderedDict()

    def key_of(self, prompt) -> Optional[tuple]:
        """The first page_size-aligned run, or None when the prompt is
        shorter than one page (nothing cacheable to steer by)."""
        if self.window <= 0 or len(prompt) < self.window:
            return None
        return tuple(int(t) for t in prompt[:self.window])

    def get(self, key) -> Optional[str]:
        rid = self._map.get(key)
        if rid is not None:
            self._map.move_to_end(key)
        return rid

    def put(self, key, rid: str) -> None:
        self._map[key] = rid
        self._map.move_to_end(key)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def drop_replica(self, rid: str) -> int:
        """Forget every key steering at a departed replica (its cached
        pages died with it); returns entries dropped."""
        stale = [k for k, v in self._map.items() if v == rid]
        for k in stale:
            del self._map[k]
        return len(stale)

    def __len__(self):
        return len(self._map)


class PlacementPolicy:
    """Pick a replica for one prompt from the placeable candidates.

    Modes: "affinity" (the default — affinity index, falling back to
    least-loaded and recording the fallback so the NEXT request with the
    same prefix sticks), "least_loaded" (ignore the index), "random"
    (the degenerate baseline the fleet bench A/Bs hit rates against).
    """

    def __init__(self, mode: str = AFFINITY, window: int = 0,
                 capacity: int = 8192, rng=None):
        if mode not in (AFFINITY, LEAST_LOADED, RANDOM):
            raise ValueError(f"unknown placement mode {mode!r}")
        self.mode = mode
        self.index = AffinityIndex(window, capacity)
        import random as _random

        self.rng = rng or _random.Random(0)

    def set_window(self, window: int) -> None:
        """Adopt the fleet's page size once the first replica's hello
        reveals it (the index starts empty, so re-keying is free)."""
        if window and window != self.index.window:
            self.index = AffinityIndex(window, self.index.capacity)

    def place(self, prompt, candidates: list[Replica]) -> tuple[Replica, str]:
        """(replica, reason) — `candidates` must be non-empty (the router
        sheds BEFORE calling when the fleet is saturated)."""
        assert candidates
        if self.mode == RANDOM:
            return self.rng.choice(candidates), RANDOM
        key = self.index.key_of(prompt) if self.mode == AFFINITY else None
        if key is not None:
            rid = self.index.get(key)
            if rid is not None:
                for r in candidates:
                    if r.rid == rid:
                        return r, AFFINITY
                # the remembered replica is gone/draining/saturated:
                # fall through to least-loaded and RE-POINT the key —
                # the new replica is about to cache this prefix, so
                # followers should chase it there, not the old home
        best = min(candidates, key=lambda r: r.score())
        if key is not None:
            self.index.put(key, best.rid)
        return best, LEAST_LOADED
