"""Fleet tier: multi-replica serving with KV-aware placement.

The reference's layer-5 pserver networking (ProtoServer/LightNetwork —
a thin RPC tier fanning many trainers over many parameter servers) reborn
on the serving side: a front-tier ROUTER speaking the existing
`serving/wire.py` frame protocol on both faces.  Clients connect to the
router exactly as they connect to one `serving/server.py` replica (same
generate/cancel/stats/metrics/dump frames, per-token streaming
preserved); the router multiplexes them across N engine-pump replicas —
separate processes or hosts each running the unchanged `tools/serve.py`.

Pieces (stdlib-only — no jax anywhere in this package, mirroring the
client/wire discipline, so the router can run on a box with no
accelerator at all):

  * `fleet.replica` — the replica table: per-replica registration state
    (joining/healthy/draining/broken/dead), the last polled stats
    snapshot, and the router's own outstanding-request accounting.
  * `fleet.policy` — KV-aware placement: a bounded prefix-affinity index
    (hash of the first page_size-aligned token run, mirroring
    `serving/prefix_tree.py` granularity) steers shared-prefix traffic to
    the replica that already holds the prefix's KV pages; everything else
    goes least-loaded on polled queue/slot/page occupancy.
  * `fleet.router` — the router itself: asyncio TCP listener, one
    persistent multiplexed backend connection per replica, a background
    stats poller doubling as the heartbeat, live join/leave, per-replica
    circuit breaking on a wedged pump, transparent retry of
    not-yet-streamed requests on replica death, and fleet-level overload
    shedding (never unbounded queueing).
  * `fleet.ctl` — operator control: join/leave/drain/undrain over the
    wire plus the drain-aware rolling-restart runbook as code.

CLI: `tools/fleet_router.py` (serve a router), `python -m
paddle_tpu.fleet.ctl` (drive one).  Design notes: docs/serving.md
"Fleet".
"""

from paddle_tpu.fleet.policy import AffinityIndex, PlacementPolicy  # noqa: F401
from paddle_tpu.fleet.replica import Replica, ReplicaTable  # noqa: F401
from paddle_tpu.fleet.router import FleetRouter  # noqa: F401

__all__ = ["FleetRouter", "FleetCtl", "Replica", "ReplicaTable",
           "PlacementPolicy", "AffinityIndex"]


def __getattr__(name):
    # ctl imports lazily: `python -m paddle_tpu.fleet.ctl` would otherwise
    # warn about the module landing in sys.modules twice (the runpy
    # double-import), and nothing in the router path needs it
    if name == "FleetCtl":
        from paddle_tpu.fleet.ctl import FleetCtl
        return FleetCtl
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
