"""Fleet control: join/leave/drain/undrain over the wire, and the
drain-aware rolling-restart runbook as code.

`FleetCtl` is a thin client over the router's `fleet` op frames (it rides
`serving/client.py`, so it inherits the reconnect-with-backoff that makes
a restarting router a wait, not an error).  The CLI form:

  python -m paddle_tpu.fleet.ctl --router 127.0.0.1:8440 list
  python -m paddle_tpu.fleet.ctl --router ... join 127.0.0.1:8431
  python -m paddle_tpu.fleet.ctl --router ... drain r0
  python -m paddle_tpu.fleet.ctl --router ... wait-drained r0
  python -m paddle_tpu.fleet.ctl --router ... leave r0
  python -m paddle_tpu.fleet.ctl --router ... undrain r0

Rolling restart of a replica, zero dropped requests (the runbook
docs/serving.md "Fleet" spells out; `rolling_restart()` below automates
it given a restart callback):

  1. `drain rX` — the router stops placing on rX; its in-flight work
     keeps streaming.
  2. `wait-drained rX` — until the router's own outstanding count AND the
     replica's polled inflight both reach zero (the replica may have
     direct clients the router never sees).
  3. `leave rX` — drop it from the table (nothing pending, so nothing to
     retry).
  4. restart the replica process — its own SIGTERM path drains whatever
     the router could not see, then the new process binds.
  5. `join host:port` — hello handshake, back in rotation.

Stdlib-only, like everything on the fleet tier.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Callable, Optional

from paddle_tpu.serving.client import ServerError, ServingClient


class FleetCtl:
    """Operator handle on one fleet router."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 **client_kw):
        self.client = ServingClient(host, port, timeout=timeout,
                                    **client_kw)

    # -- context management ------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        self.client.close()

    # -- ops ---------------------------------------------------------------
    def _op(self, op: str, **fields) -> dict:
        self.client.send({"type": "fleet", "op": op, **fields})
        reply = self.client._route(
            lambda m: m.get("type") == "fleet" and m.get("op") == op)
        if not reply.get("ok"):
            raise ServerError(f"fleet {op} failed: "
                              f"{reply.get('error', 'unknown')}")
        return reply

    def join(self, host: str, port: int) -> str:
        """Register a replica; returns its router-assigned id."""
        return self._op("join", host=host, port=int(port))["replica"]

    def leave(self, replica: str) -> None:
        self._op("leave", replica=replica)

    def drain(self, replica: str) -> dict:
        """Stop placing on `replica`; returns {state, pending}."""
        return self._op("drain", replica=replica)

    def undrain(self, replica: str) -> dict:
        return self._op("undrain", replica=replica)

    def list(self) -> list[dict]:
        return self._op("list")["replicas"]

    def status(self, replica: str) -> dict:
        for row in self.list():
            if row["replica"] == replica:
                return row
        raise ServerError(f"no replica {replica!r} in the fleet")

    def stats(self) -> dict:
        return self.client.stats()

    # -- the rolling-restart runbook ---------------------------------------
    def wait_drained(self, replica: str, timeout_s: float = 300.0,
                     poll_s: float = 0.1) -> dict:
        """Block until the router has ZERO outstanding requests on
        `replica` AND the replica's own polled inflight is zero (it may
        serve direct clients the router never placed).  Returns the final
        status row; raises TimeoutError with the stuck counts."""
        deadline = time.monotonic() + timeout_s
        row = self.status(replica)
        while time.monotonic() < deadline:
            row = self.status(replica)
            if row["pending"] == 0 and not (row.get("inflight") or 0):
                return row
            time.sleep(poll_s)
        raise TimeoutError(
            f"replica {replica} still busy after {timeout_s:.0f}s "
            f"(router pending={row['pending']}, "
            f"replica inflight={row.get('inflight')}) — is a request "
            f"ignoring its deadline?")

    def rolling_restart(self, restart: Callable[[dict], tuple[str, int]],
                        replicas: Optional[list[str]] = None,
                        drain_timeout_s: float = 300.0,
                        log=lambda s: print(s, file=sys.stderr,
                                            flush=True)) -> list[str]:
        """Restart every replica (or the given ids) one at a time with
        zero dropped requests: drain -> wait-drained -> leave ->
        `restart(status_row)` (stop the old process — its SIGTERM drain
        finishes anything the router could not see — and start the new
        one; return its (host, port)) -> join.  Returns the new replica
        ids.  A failing restart raises with the fleet still serving on
        the remaining replicas — the operator fixes the one box and
        re-runs."""
        todo = replicas if replicas is not None \
            else [row["replica"] for row in self.list()]
        new_ids = []
        for rid in todo:
            row = self.status(rid)
            log(f"fleet ctl: draining {rid} ({row['addr']})")
            self.drain(rid)
            self.wait_drained(rid, timeout_s=drain_timeout_s)
            self.leave(rid)
            log(f"fleet ctl: {rid} drained and left; restarting")
            host, port = restart(row)
            new_id = self.join(host, port)
            new_ids.append(new_id)
            log(f"fleet ctl: {host}:{port} rejoined as {new_id}")
        return new_ids


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="drive a fleet router: join/leave/drain/undrain/"
                    "list/wait-drained")
    ap.add_argument("--router", required=True, metavar="HOST:PORT")
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("op", choices=["join", "leave", "drain", "undrain",
                                   "list", "wait-drained", "stats"])
    ap.add_argument("target", nargs="?", default="",
                    help="replica id (drain/undrain/leave/wait-drained) "
                         "or HOST:PORT (join)")
    args = ap.parse_args(argv)
    host, _, port = args.router.rpartition(":")
    try:
        ctl_handle = FleetCtl(host or "127.0.0.1", int(port),
                              timeout=args.timeout)
    except OSError as e:
        print(f"error: cannot reach the router at {args.router}: {e}",
              file=sys.stderr)
        return 1
    with ctl_handle as ctl:
        try:
            if args.op == "list":
                print(json.dumps(ctl.list(), indent=2))
            elif args.op == "stats":
                print(json.dumps(ctl.stats(), indent=2))
            elif args.op == "join":
                h, _, p = args.target.rpartition(":")
                if not p:
                    print("join needs HOST:PORT", file=sys.stderr)
                    return 2
                print(ctl.join(h or "127.0.0.1", int(p)))
            elif not args.target:
                print(f"{args.op} needs a replica id (see `list`)",
                      file=sys.stderr)
                return 2
            elif args.op == "leave":
                ctl.leave(args.target)
            elif args.op == "drain":
                print(json.dumps(ctl.drain(args.target)))
            elif args.op == "undrain":
                print(json.dumps(ctl.undrain(args.target)))
            elif args.op == "wait-drained":
                print(json.dumps(ctl.wait_drained(
                    args.target, timeout_s=args.timeout)))
        except (ServerError, TimeoutError, ConnectionError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
