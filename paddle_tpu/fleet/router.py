"""The fleet router: one wire-protocol front tier over N serving replicas.

The TPU-native echo of the reference's pserver networking layer
(ProtoServer/LightNetwork — a thin RPC tier fanning many clients over
many servers, PAPER.md layer 5): clients speak to the router EXACTLY as
they speak to one `serving/server.py` replica — same length-prefixed JSON
frames (`serving/wire.py`), same generate/cancel/stats/metrics/dump/ping
message types, per-token streaming preserved — and the router multiplexes
them across N engine-pump replicas (separate processes/hosts running the
unchanged `tools/serve.py`).  Placement state lives HERE, in a thin
restartable tier, never in the replicas (the PS-vs-graph lesson of
arXiv:1605.08695): losing the router loses an affinity index worth a few
cold prefills, nothing correctness-bearing.

Architecture — ONE asyncio loop owns everything (no pump thread: the
router computes nothing):

  * one persistent multiplexed backend connection per replica, opened at
    join with a `hello` handshake that CLASSIFIES the peer (a non-replica
    answering the hello — or failing to — is refused);
  * a background POLLER sends each replica `{"stats", stale_ok: true}`
    every `poll_interval_s` — stale-ok so the poll keeps answering while
    a replica's pump is wedged, which is exactly when the circuit breaker
    below needs the data.  The poll doubles as the heartbeat: a replica
    missing `heartbeat_misses` consecutive polls (or dropping its backend
    connection) LEAVES the fleet;
  * KV-aware placement (`fleet/policy.py`): prefix-affinity first (the
    first page_size-aligned token run steers shared-prefix traffic to the
    replica already holding those KV pages, so PR 7's prefix cache hits
    under fan-out), least-loaded otherwise (load fraction of the
    admission cap, then KV page occupancy);
  * per-replica CIRCUIT BREAKING: polled `pump_last_step_age_s` past
    `wedge_age_s` (or `pump_alive` false) opens the circuit — placement
    stops, not-yet-streamed requests are cancelled there and retried
    elsewhere — and a recovering beat closes it;
  * transparent RETRY on replica death: a request whose client has seen
    ZERO streamed tokens is re-sent verbatim to a surviving replica (same
    prompt/knobs/seed → bit-identical tokens); one that already streamed
    gets an honest error frame (re-running it could emit a divergent
    stream mid-flight);
  * fleet-level OVERLOAD SHEDDING: when every healthy replica is
    saturated (router-tracked outstanding + polled external traffic at
    the replica's admission cap) the router answers `overload`
    immediately — it never queues, so it can never queue unboundedly;
  * drain-aware ops (`fleet/ctl.py`): drain marks a replica unplaceable
    while its in-flight work finishes, which is the first half of the
    rolling-restart runbook (docs/serving.md "Fleet").

Observability: flight events (`replica_join`/`replica_leave`/`route`/
`retry`/`shed` + broken/recovered/fleet_unhealthy) on the process-global
recorder, a strict metrics registry behind the `metrics` frame
(fleet_* rows in obs.metrics.CATALOG), and a postmortem bundle frozen
the moment the WHOLE fleet goes unhealthy — `obs/flight.py` reused
unchanged.

Stdlib-only: the router never imports jax (it can run on a box with no
accelerator, in front of replicas that have them).
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
from typing import Optional

from paddle_tpu.fleet import replica as rep
from paddle_tpu.fleet.policy import DISAGG, PlacementPolicy
from paddle_tpu.fleet.replica import Replica, ReplicaTable
from paddle_tpu.obs import (MetricsRegistry, statset_collector,
                            tracer_collector)
from paddle_tpu.obs.flight import flight_collector, get_flight_recorder
from paddle_tpu.obs.slo import SloEvaluator, default_router_slos
from paddle_tpu.obs.timeseries import (HistorySampler, MetricHistory,
                                       history_collector, history_reply,
                                       merge_history)
from paddle_tpu.obs.trace import (get_tracer, new_span_id, new_trace_id,
                                  trace_reply)
from paddle_tpu.serving import wire
from paddle_tpu.utils.stat import StatSet


#: one client connection (the router's client face): the SAME slow-reader
#: severing frame connection the replica server uses — shared via wire.py
#: so the backpressure discipline can never drift between the two front
#: ends (conn.rids maps client id -> router grid here)
_ClientConn = wire.FrameConn


class _RoutedReq:
    """One accepted generate, across however many placements it takes."""

    __slots__ = ("conn", "cid", "msg", "grid", "rid", "stream", "streamed",
                 "retries", "t_submit", "trace_id", "span_id",
                 "client_parent", "t0", "t_last_tok", "burst_left",
                 "burst_share", "phase", "decode_rid", "disagg_pages")

    def __init__(self, conn, cid, msg, grid):
        self.conn = conn
        self.cid = cid
        self.msg = msg                 # the original frame, resent verbatim
        self.grid = grid               # router-global id (re-minted on retry
        self.rid = None                # so a stale replica's late frames
        self.stream = bool(msg.get("stream", True))   # can never route)
        self.streamed = 0              # token frames the CLIENT has seen
        self.retries = 0
        # disaggregated prefill/decode (docs/serving.md): "prefill" while
        # the prefill_only leg is in flight at a prefill-tier replica;
        # its done frame then routes the ORIGINAL msg to decode_rid (the
        # replica the kv_push mounted the prompt's pages on) — or falls
        # back to colocated placement when the push failed
        self.phase = None              # None | "prefill"
        self.decode_rid = None         # planned decode replica
        self.disagg_pages = 0          # pages shipped for this request
        self.t_submit = time.monotonic()
        # burst-aware relay inter-token latency (multi-step decode): a
        # replica running decode_steps=k relays ≤k token frames back to
        # back, each stamped with `burst` = fresh tokens remaining in its
        # burst including itself — the router divides the inter-burst
        # arrival gap by the burst size so relay ITL percentiles stay
        # comparable across decode_steps settings (one arrival is k
        # tokens of progress, not one)
        self.t_last_tok = 0.0          # last relayed-token arrival
        self.burst_left = 0            # burst tokens still to charge
        self.burst_share = 0.0         # per-token share of the burst gap
        # distributed-trace identity, stamped at ingress: one trace_id per
        # request (adopted from the client's frame when it sent one), and
        # the router's ingress span id — the `parent` every router-side
        # span AND the replica's lifecycle spans point back at
        tc = msg.get("trace") if isinstance(msg.get("trace"), dict) else {}
        self.trace_id = tc.get("trace_id") if \
            isinstance(tc.get("trace_id"), str) else new_trace_id()
        # a tracing CLIENT's own span id: the ingress span parents on it,
        # so the client's span stitches above the router's in a merge
        self.client_parent = tc.get("parent") if \
            isinstance(tc.get("parent"), str) else None
        self.span_id = new_span_id()
        self.t0 = time.perf_counter()  # ingress-span base (tracer timebase)


class _Backend:
    """One persistent multiplexed connection router -> replica."""

    def __init__(self, router: "FleetRouter", replica: Replica):
        self.router = router
        self.replica = replica
        self.reader = None
        self.writer = None
        self.dead = False
        self.expected_down = False     # intentional close (leave/shutdown):
        self._task = None              # skip the death-handling path
        # one outstanding router-originated RPC per REPLY TYPE (stats/
        # metrics/trace carry no ids the replica echoes back usefully on
        # a multiplexed backend connection, so the reply type IS the
        # correlation key); one lock PER TYPE — a slow metrics/trace
        # collection must never hold up the heartbeat stats poll, whose
        # cadence is the dead-replica detector
        self._rpc_futs: dict[str, asyncio.Future] = {}
        self._rpc_locks: dict[str, asyncio.Lock] = {}

    async def connect(self, timeout_s: float = 20.0) -> dict:
        """Open + hello handshake; returns the replica's hello reply.
        Raises on a peer that is not a serving replica — the router must
        classify what it is about to route traffic at."""
        self.reader, self.writer = await asyncio.wait_for(
            asyncio.open_connection(self.replica.host, self.replica.port),
            timeout_s)
        try:
            self.writer.write(wire.encode({"type": "hello"}))
            msg = await asyncio.wait_for(wire.read_frame(self.reader),
                                         timeout_s)
            if not isinstance(msg, dict) or msg.get("type") != "hello" \
                    or msg.get("role") != "replica":
                got = None if not isinstance(msg, dict) else \
                    (msg.get("role") or msg.get("type") or
                     msg.get("error", "")[:80])
                raise ConnectionError(
                    f"peer at {self.replica.addr} is not a serving "
                    f"replica (hello answered {got!r}; expected role "
                    f"'replica' — is this a router, or something else "
                    f"entirely?)")
        except BaseException:
            # EVERY handshake failure closes the socket — a silent
            # non-replica peer that times out here would otherwise leak
            # one fd per JOINING retry for the life of the router
            self.writer.close()
            raise
        self._task = asyncio.get_running_loop().create_task(
            self._read_loop())
        return msg

    def send(self, msg: dict) -> bool:
        if self.dead or self.writer is None or self.writer.is_closing():
            return False
        try:
            self.writer.write(wire.encode(msg))
            return True
        except (ConnectionError, RuntimeError):
            self.dead = True
            return False

    async def rpc(self, msg: dict, reply_type: str,
                  timeout_s: float) -> Optional[dict]:
        """One router-originated round trip correlated by reply type
        (stats poll, metrics aggregation, trace collection).  Returns
        None on a dead connection or timeout — callers treat that as
        'replica did not answer', never an error."""
        lock = self._rpc_locks.get(reply_type)
        if lock is None:
            lock = self._rpc_locks[reply_type] = asyncio.Lock()
        async with lock:
            fut = asyncio.get_running_loop().create_future()
            self._rpc_futs[reply_type] = fut
            if not self.send(msg):
                return None
            try:
                return await asyncio.wait_for(fut, timeout_s)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                return None
            finally:
                if self._rpc_futs.get(reply_type) is fut:
                    del self._rpc_futs[reply_type]

    async def poll_stats(self, timeout_s: float) -> Optional[dict]:
        """One stale-ok stats round trip (the heartbeat probe)."""
        return await self.rpc({"type": "stats", "stale_ok": True},
                              "stats", timeout_s)

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await wire.read_frame(self.reader)
                if msg is None:
                    break
                self.router._on_backend_frame(self.replica, self, msg)
        except (wire.FrameError, ConnectionError):
            pass
        finally:
            self.dead = True
            for fut in list(self._rpc_futs.values()):
                if not fut.done():
                    fut.set_result(None)
            if not self.expected_down:
                self.router._backend_lost(self.replica, self)

    def close(self, expected: bool = True) -> None:
        self.expected_down = self.expected_down or expected
        self.dead = True
        if self.writer is not None:
            try:
                self.writer.close()
            except (ConnectionError, RuntimeError):
                pass

    def abort(self) -> None:
        """Hard RST — the 'replica host vanished' path (tests use this to
        make a replica die abruptly without the graceful-close frames a
        drain would send)."""
        self.expected_down = False
        self.dead = True
        if self.writer is not None:
            try:
                self.writer.transport.abort()
            except (ConnectionError, RuntimeError):
                pass


class FleetRouter:
    """Front-tier router over N serving replicas (see module docstring).

    >>> rt = FleetRouter(port=0, replicas=[("127.0.0.1", 8431),
    ...                                    ("127.0.0.1", 8432)])
    >>> host, port = rt.start_background()
    >>> # clients now use serving/client.py against (host, port)
    >>> rt.stop_background(drain=True)
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 replicas=(), policy: str = "affinity",
                 affinity_capacity: int = 8192,
                 poll_interval_s: float = 0.5,
                 heartbeat_misses: int = 10,
                 wedge_age_s: float = 30.0,
                 retry_limit: int = 2,
                 disagg_min_prompt: int = 0,
                 postmortem_dir: Optional[str] = None,
                 tracer=None,
                 history_resolution_s: float = 5.0,
                 history_retention_s: float = 1800.0,
                 slo_specs=None):
        self.host = host
        self.port = port
        # router-side distributed tracing: every router action for a
        # traced request (ingress, placement, token relay, retry, shed)
        # records on this ring carrying the request's trace_id, so a
        # merged trace threads client -> router -> replica.  Off by
        # default like every tracer; `tracer=` gives an in-process
        # embedder (tests, bench) a private ring.
        self.tracer = tracer if tracer is not None else get_tracer()
        self._initial = [(h, int(p)) for h, p in replicas]
        self.table = ReplicaTable()
        self.policy = PlacementPolicy(policy, window=0,
                                      capacity=affinity_capacity)
        self.poll_interval_s = float(poll_interval_s)
        self.heartbeat_misses = int(heartbeat_misses)
        self.wedge_age_s = float(wedge_age_s)
        self.retry_limit = int(retry_limit)
        # disaggregated prefill/decode: prompts at least this long place
        # on a prefill-tier replica first (kv_push to the chosen decode
        # replica, then the real generate follows).  0 = auto (one
        # affinity window, i.e. one KV page — the smallest prefix worth
        # shipping); negative disables disagg placement entirely.  Only
        # fires while BOTH a prefill-role and a decode-role replica are
        # placeable; everything else places colocated as before.
        self.disagg_min_prompt = int(disagg_min_prompt)
        self.postmortem_dir = postmortem_dir
        self._last_dump_error = "unknown"
        self.flight = get_flight_recorder()
        self.flight.enabled = True
        # router-side latency stats (utils/stat.py): today one stat —
        # relay_token_latency, the burst-honest inter-token gap clients
        # actually observed at the router tier
        self.stats = StatSet("fleet_router")
        self._routes: dict[str, _RoutedReq] = {}
        self._seq = 0
        self._draining = False
        self._unhealthy_dumped = False
        self._conns: set = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._poll_task = None
        self._dump_task = None        # in-flight fleet_unhealthy dump
        self._idle: Optional[asyncio.Event] = None
        self._closed: Optional[asyncio.Event] = None
        self._bg_thread: Optional[threading.Thread] = None
        self._init_metrics()
        # the health plane (obs/timeseries.py + obs/slo.py): the router
        # records its OWN fleet_* series only — per-replica series come
        # in over the aggregate `history` fanout, never sampled here —
        # and its SLOs (shed ratio, zero-healthy) burn over them.  The
        # sampler thread reads lock-guarded registry state, so it rides
        # alongside the asyncio loop without touching it.
        self.history = MetricHistory(self.metrics,
                                     resolution_s=history_resolution_s,
                                     retention_s=history_retention_s)
        self.metrics.register_collector(history_collector(self.history))
        self.slo = SloEvaluator(
            self.history,
            default_router_slos() if slo_specs is None else slo_specs,
            flight=self.flight, registry=self.metrics,
            dump_fn=self._slo_dump)
        self.history_sampler = HistorySampler(self.history,
                                              on_sample=self.slo.evaluate)

    # -- metrics -----------------------------------------------------------
    def _init_metrics(self) -> None:
        reg = self.metrics = MetricsRegistry(strict=True)
        self._m_accepted = reg.counter("fleet_requests_accepted_total")
        self._m_placements = reg.counter("fleet_placements_total",
                                         labels=("policy",))
        self._m_retries = reg.counter("fleet_retries_total")
        self._m_sheds = reg.counter("fleet_sheds_total")
        self._m_joins = reg.counter("fleet_joins_total")
        self._m_leaves = reg.counter("fleet_leaves_total")
        # disaggregated prefill/decode accounting (docs/serving.md)
        self._m_kv_pushes = reg.counter("fleet_kv_pushes_total")
        self._m_kv_push_fail = reg.counter("fleet_kv_push_failures_total")
        self._m_kv_fallbacks = reg.counter("fleet_kv_fallbacks_total")
        self._m_kv_pages = reg.counter("fleet_kv_pages_shipped_total")
        for m in (self._m_accepted, self._m_retries, self._m_sheds,
                  self._m_joins, self._m_leaves, self._m_kv_pushes,
                  self._m_kv_push_fail, self._m_kv_fallbacks,
                  self._m_kv_pages):
            m.inc(0.0)     # unlabeled counters render 0, not absent
        reg.gauge("fleet_inflight").set_fn(lambda: float(len(self._routes)))
        reg.gauge("fleet_replicas_registered").set_fn(
            lambda: float(len(self.table)))
        reg.gauge("fleet_replicas_healthy").set_fn(
            lambda: float(self.table.counts()[rep.HEALTHY]))
        reg.gauge("fleet_replicas_draining").set_fn(
            lambda: float(self.table.counts()[rep.DRAINING]))
        reg.gauge("fleet_replicas_broken").set_fn(
            lambda: float(self.table.counts()[rep.BROKEN]))
        reg.gauge("fleet_affinity_keys").set_fn(
            lambda: float(len(self.policy.index)))
        reg.gauge("fleet_draining").set_fn(
            lambda: 1.0 if self._draining else 0.0)
        reg.register_collector(statset_collector(
            self.stats, "fleet_relay_latency_seconds",
            "fleet_relay_latency_count"))
        reg.register_collector(tracer_collector(self.tracer))
        reg.register_collector(flight_collector(self.flight))

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._idle = asyncio.Event()
        self._closed = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        for h, p in self._initial:
            # a replica not up yet stays JOINING; the poller keeps
            # retrying the connect, so start order is never a crash
            try:
                await self._join(h, p, keep_on_fail=True)
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                print(f"fleet: replica {h}:{p} not reachable yet ({e}); "
                      f"will keep trying", file=sys.stderr, flush=True)
        self._poll_task = self._loop.create_task(self._poll_loop())
        self.history_sampler.start()
        return self.host, self.port

    async def drain(self) -> None:
        """Stop placing (new generates get overload/draining), let every
        routed request finish, then close."""
        self._draining = True
        if self._routes:
            self._idle.clear()
            await self._idle.wait()
        await self._shutdown()

    async def stop(self) -> None:
        """Hard shutdown: cancel everything routed, then close (replicas
        answer done/cancelled, which drains the route table)."""
        self._draining = True
        for st in list(self._routes.values()):
            r = self.table.get(st.rid)
            if r is not None and r.backend is not None:
                r.backend.send({"type": "cancel", "id": st.grid})
        if self._routes:
            self._idle.clear()
            try:
                await asyncio.wait_for(self._idle.wait(), 30.0)
            except asyncio.TimeoutError:
                for st in list(self._routes.values()):
                    self._finish_error(st, "router stopped")
        await self._shutdown()

    async def _shutdown(self) -> None:
        self.history_sampler.stop()
        if self._poll_task is not None:
            self._poll_task.cancel()
            self._poll_task = None
        if self._dump_task is not None and not self._dump_task.done():
            # a fleet_unhealthy dump in flight (it pulls replica traces
            # asynchronously) must commit before the loop dies — losing
            # the black box to the shutdown race would defeat it
            try:
                await asyncio.wait_for(self._dump_task, 10.0)
            except (asyncio.TimeoutError, Exception):  # noqa: BLE001
                pass
        self._dump_task = None
        for r in list(self.table):
            if r.backend is not None:
                r.backend.close(expected=True)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self._conns):
            conn.dead = True
            try:
                conn.writer.close()
            except (ConnectionError, RuntimeError):
                pass
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()

    def start_background(self) -> tuple[str, int]:
        started = threading.Event()
        addr: list = []

        async def _amain():
            addr.extend(await self.start())
            started.set()
            await self.wait_closed()

        self._bg_thread = threading.Thread(
            target=lambda: asyncio.run(_amain()),
            name="fleet-router-loop", daemon=True)
        self._bg_thread.start()
        if not started.wait(timeout=60):
            raise RuntimeError("fleet router failed to bind within 60s")
        return addr[0], addr[1]

    def stop_background(self, drain: bool = True, timeout: float = 120):
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.drain() if drain else self.stop(), self._loop)
        fut.result(timeout=timeout)
        if self._bg_thread is not None:
            self._bg_thread.join(timeout=timeout)

    # -- join/leave --------------------------------------------------------
    async def _join(self, host: str, port: int,
                    keep_on_fail: bool = False) -> Replica:
        """Register + connect one replica.  `keep_on_fail` leaves a
        JOINING entry behind on connect failure for the poller to keep
        retrying (the static start()-list path: replicas may come up
        after the router); an explicit ctl join reports the failure and
        leaves no residue."""
        existing = self.table.by_addr(host, port)
        if existing is not None and existing.state != rep.JOINING:
            raise ConnectionError(
                f"{host}:{port} is already registered as "
                f"{existing.rid} ({existing.state})")
        r = existing or self.table.add(host, port)
        try:
            await self._connect_replica(r)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            if not keep_on_fail and existing is None:
                self.table.drop(r.rid)
            raise
        return r

    async def _connect_replica(self, r: Replica) -> None:
        backend = _Backend(self, r)
        hello = await backend.connect()
        r.hello = hello
        r.backend = backend
        r.poll_fails = 0
        r.state = rep.DRAINING if r.drain_requested else rep.HEALTHY
        if self.policy.index.window == 0 and r.page_size:
            # adopt the fleet's page size for the affinity granularity
            # from the first replica's hello (homogeneous fleets — a
            # mixed-page-size fleet would shard its own prefix cache)
            self.policy.set_window(r.page_size)
        self._m_joins.inc()
        self.flight.record("replica_join", replica=r.rid, addr=r.addr,
                           num_slots=hello.get("num_slots"),
                           max_inflight=hello.get("max_inflight"))
        self._unhealthy_dumped = False

    def _leave(self, rid: str, why: str) -> Optional[Replica]:
        """Remove a replica; retry its unstreamed requests elsewhere."""
        r = self.table.drop(rid)
        if r is None:
            return None
        if r.backend is not None:
            r.backend.close(expected=True)
        dropped = self.policy.index.drop_replica(rid)
        self._m_leaves.inc()
        self.flight.record("replica_leave", replica=rid, addr=r.addr,
                           why=why, pending=len(r.pending),
                           affinity_keys_dropped=dropped)
        for grid in sorted(r.pending):
            st = self._routes.get(grid)
            if st is not None:
                self._requeue(st, why=f"replica {rid} {why}")
        r.pending.clear()
        self._fleet_health_check()
        return r

    def _backend_lost(self, r: Replica, backend: _Backend) -> None:
        """Reader task saw EOF/reset on a connection we did not close —
        the replica (or the path to it) died."""
        if self.table.get(r.rid) is not r or r.backend is not backend:
            return
        self._leave(r.rid, "connection_lost")

    # -- the poller (heartbeat + circuit breaker) --------------------------
    async def _poll_loop(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval_s)
            for r in list(self.table):
                if r.polling:
                    continue
                r.polling = True
                asyncio.get_running_loop().create_task(self._poll_one(r))

    async def _poll_one(self, r: Replica) -> None:
        try:
            if self.table.get(r.rid) is not r:
                return
            if r.state == rep.JOINING:
                # a statically-configured replica that was not up at
                # start(): keep attempting the connect+hello
                try:
                    await self._connect_replica(r)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass
                return
            if r.backend is None or r.backend.dead:
                return                 # the death path owns this one
            stats = await r.backend.poll_stats(
                timeout_s=max(0.05, self.poll_interval_s * 0.9))
            if self.table.get(r.rid) is not r:
                return
            if stats is None:
                r.poll_fails += 1
                if r.poll_fails >= self.heartbeat_misses:
                    self._leave(r.rid, "heartbeat_expired")
                return
            r.absorb_poll(stats)
            why = r.pump_wedged(self.wedge_age_s)
            if why and r.state in (rep.HEALTHY, rep.DRAINING):
                self._break_replica(r, why)
            elif not why and r.state == rep.BROKEN:
                self._recover_replica(r)
        finally:
            r.polling = False

    def _break_replica(self, r: Replica, why: str) -> None:
        """Open the circuit on a wedged pump: stop placing, cancel+retry
        the requests its clients have seen nothing of (the wedged pump
        cannot be streaming them anyway), leave streamed ones pinned —
        they resume if the wedge clears."""
        r.state = rep.BROKEN
        r.broken_reason = why
        self.flight.record("replica_broken", replica=r.rid, why=why)
        for grid in sorted(r.pending):
            st = self._routes.get(grid)
            if st is not None and st.streamed == 0:
                # best-effort cancel at the broken replica (processed
                # whenever its pump unwedges); the retry mints a fresh
                # grid, so a late done/cancelled frame routes nowhere
                r.backend.send({"type": "cancel", "id": grid})
                r.pending.discard(grid)
                self._requeue(st, why=f"replica {r.rid} circuit open "
                                      f"({why})")
        self._fleet_health_check()

    def _recover_replica(self, r: Replica) -> None:
        r.state = rep.DRAINING if r.drain_requested else rep.HEALTHY
        r.broken_reason = ""
        self.flight.record("replica_recovered", replica=r.rid)
        self._unhealthy_dumped = False

    def _fleet_health_check(self) -> None:
        """Freeze ONE postmortem bundle per total-fleet-unhealthy episode
        (zero healthy replicas while any are registered) — the black-box
        moment for the fleet tier, mirroring the replica server's
        pump-death dump.  The dump itself runs as a task so it can first
        pull span snapshots from the still-connected (wedged/draining)
        replicas — a fleet_unhealthy bundle is cross-process."""
        counts = self.table.counts()
        if counts[rep.HEALTHY] > 0 or not self.table.ever_registered:
            return
        if self._unhealthy_dumped:
            return
        self._unhealthy_dumped = True
        self.flight.record("fleet_unhealthy", counts=counts,
                           inflight=len(self._routes))
        err = (f"no healthy replicas "
               f"({len(self.table)} registered: {counts})")
        if self._loop is not None and self._loop.is_running():
            self._dump_task = self._loop.create_task(
                self._dump_unhealthy(err))
        else:
            self._write_bundle("fleet_unhealthy", error=err)

    async def _dump_unhealthy(self, error: str) -> None:
        self._write_bundle("fleet_unhealthy", error=error,
                           replica_traces=await
                           self._collect_replica_traces())

    async def _collect_replica_traces(self, timeout_s: float = 2.0) -> dict:
        """Span-ring snapshots from every replica whose backend
        connection still answers (a BROKEN replica's loop thread does —
        the trace RPC is loop-side like stats stale_ok; a dead one is
        skipped).  Keyed by rid; embedded in the bundle's engine.json."""
        targets = [r for r in self.table
                   if r.backend is not None and not r.backend.dead]
        if not targets:
            return {}
        replies = await asyncio.gather(
            *[r.backend.rpc({"type": "trace"}, "trace", timeout_s)
              for r in targets])
        out = {}
        for r, msg in zip(targets, replies):
            if isinstance(msg, dict):
                out[r.rid] = {"process": msg.get("process"),
                              "recorded": msg.get("recorded"),
                              "dropped": msg.get("dropped"),
                              "spans": msg.get("spans") or []}
        return out

    async def _aggregate_metrics(self) -> tuple[str, int]:
        """The router's render + each answering replica's metrics frame,
        merged into one Prometheus text with replica samples labeled
        `replica="rN"` (families regrouped so HELP/TYPE render once even
        for names both tiers emit, e.g. the tracer/flight accounting)."""
        targets = [r for r in self.table
                   if r.backend is not None and not r.backend.dead]
        replies = await asyncio.gather(
            *[r.backend.rpc({"type": "metrics"}, "metrics", 5.0)
              for r in targets]) if targets else []
        parts = [(None, self.metrics.render())]
        answered = 0
        for r, msg in zip(targets, replies):
            if isinstance(msg, dict) and isinstance(msg.get("text"), str):
                answered += 1
                parts.append((r.rid, msg["text"]))
        return _merge_prometheus(parts), answered

    async def _aggregate_history(self, msg: dict) -> dict:
        """The fleet history view: the router's own series plus every
        answering replica's, each labeled `replica="rN"` — the history
        analog of _aggregate_metrics, over the same per-reply-type rpc
        lane (so a slow fanout never holds up the stats heartbeat)."""
        fwd = {"type": "history"}
        for k in ("last_s", "names"):
            if msg.get(k) is not None:
                fwd[k] = msg[k]
        targets = [r for r in self.table
                   if r.backend is not None and not r.backend.dead]
        replies = await asyncio.gather(
            *[r.backend.rpc(dict(fwd), "history", 5.0)
              for r in targets]) if targets else []
        parts = [(None, self.history.snapshot(
            last_s=msg.get("last_s"), names=msg.get("names")))]
        for r, reply in zip(targets, replies):
            if isinstance(reply, dict) and reply.get("type") == "history":
                parts.append((r.rid, reply))
        return merge_history(parts)

    # -- postmortem --------------------------------------------------------
    def _slo_dump(self, fired: list) -> None:
        """One proactive bundle per SLO episode (obs/slo.py calls this on
        the sampler thread at the no-SLOs -> some-SLOs transition).  Same
        contract as the replica server's: the bundle freezes BEFORE the
        operator asks, with the offending series in history.json."""
        names = ",".join(sorted({str(f.get("slo", "?")) for f in fired}))
        self._write_bundle(f"slo:{names}", error=f"slo firing: {names}")

    def _router_snapshot(self) -> dict:
        return {
            "router": True,
            "replicas": [r.summary() for r in self.table],
            "inflight": len(self._routes),
            "routes": [{"grid": st.grid, "replica": st.rid,
                        "streamed": st.streamed, "retries": st.retries}
                       for st in list(self._routes.values())],
            "affinity_keys": len(self.policy.index),
            "policy": self.policy.mode,
            "draining": self._draining,
        }

    def _config_snapshot(self) -> dict:
        return {
            "host": self.host, "port": self.port, "router": True,
            "policy": self.policy.mode,
            "affinity_window": self.policy.index.window,
            "poll_interval_s": self.poll_interval_s,
            "heartbeat_misses": self.heartbeat_misses,
            "wedge_age_s": self.wedge_age_s,
            "retry_limit": self.retry_limit,
            "disagg_min_prompt": self.disagg_min_prompt,
            "postmortem_dir": self.postmortem_dir,
        }

    def _write_bundle(self, reason: str, error: Optional[str] = None,
                      replica_traces: Optional[dict] = None
                      ) -> Optional[str]:
        if not self.postmortem_dir:
            return None
        try:
            engine = self._router_snapshot()
            if replica_traces:
                # per-replica span snapshots (pulled over the trace RPC
                # just before this dump), tagged with process identity:
                # the fleet bundle holds every tier's view of the episode
                engine["replica_traces"] = replica_traces
            path = self.flight.dump(
                self.postmortem_dir, reason,
                spans=self.tracer.snapshot(),
                engine=engine,
                metrics=self.metrics.snapshot(),
                config=self._config_snapshot(),
                history=self.history.snapshot(),
                error=error)
            print(f"fleet postmortem bundle ({reason}): {path}",
                  file=sys.stderr, flush=True)
            return path
        except Exception as e:             # noqa: BLE001 — a broken dump
            self._last_dump_error = f"{type(e).__name__}: {e}"
            print(f"fleet postmortem dump failed ({reason}): "
                  f"{self._last_dump_error}", file=sys.stderr, flush=True)
            return None

    # -- backend frame routing ---------------------------------------------
    def _on_backend_frame(self, r: Replica, backend: _Backend,
                          msg: dict) -> None:
        t = msg.get("type")
        if t in ("stats", "metrics", "trace", "history"):
            fut = backend._rpc_futs.get(t)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        if t in ("pong", "hello"):
            return
        grid = msg.get("id")
        st = self._routes.get(grid) if isinstance(grid, str) else None
        if st is None or st.grid != grid:
            return                     # a retried/finished request's ghost
        if t == "token":
            # `streamed` counts tokens DELIVERED to the client (the retry
            # safety predicate), not tokens the replica produced: a
            # stream=False client has seen nothing no matter how far its
            # replica got, so its request stays transparently retryable
            # (the router always asks the replica to stream — that is how
            # it forwards per-token — but only st.stream clients receive)
            if st.stream:
                st.streamed += 1
                # relay ITL, burst-honest: charge each token of a ≤k
                # burst an equal share of the inter-burst gap.  Kept to
                # arithmetic + one Stat.add (~100ns lock) — per-token
                # loop-thread work beyond that measurably costs tok/s
                # (see the tracer note below).
                now = time.monotonic()
                if st.streamed > 1:
                    if st.burst_left > 0:
                        st.burst_left -= 1
                        self.stats.get("relay_token_latency").add(
                            st.burst_share)
                    else:
                        b = max(1, int(msg.get("burst") or 1))
                        st.burst_share = (now - st.t_last_tok) / b
                        st.burst_left = b - 1
                        self.stats.get("relay_token_latency").add(
                            st.burst_share)
                st.t_last_tok = now
                if self.tracer.enabled and st.streamed == 1:
                    # FIRST-token relay only: the router-side TTFT stitch
                    # point.  A marker per token here would put python
                    # dict+ring work on the loop thread's per-token
                    # critical path (measured ~3-5% tok/s at CPU rates,
                    # blowing the <= 2% tracing budget); the per-token
                    # cadence is already on the replica's engine lane,
                    # and the ingress span carries the relayed count.
                    self.tracer.instant(
                        "relay", track=f"req:{st.trace_id[:12]}",
                        index=msg.get("index"), trace_id=st.trace_id,
                        parent=st.span_id)
                st.conn.send({"type": "token", "id": st.cid,
                              "token": msg.get("token"),
                              "index": msg.get("index")})
        elif t == "done":
            r.pending.discard(grid)
            if st.phase == "prefill":
                self._on_prefill_done(st, msg)
                return
            self._finish(st, {"type": "done", "id": st.cid,
                              "tokens": msg.get("tokens"),
                              "reason": msg.get("reason"),
                              "timing": self._merge_timing(st, msg)})
        elif t == "error":
            r.pending.discard(grid)
            self._finish(st, {"type": "error", "id": st.cid,
                              "error": msg.get("error")})
        elif t == "overload":
            # admission race: the replica filled up (external traffic, or
            # our poll went stale) between placement and arrival — force
            # the saturated view until the next poll tells us better, and
            # try the remaining capacity
            r.pending.discard(grid)
            r.external = max(r.external,
                             r.max_inflight - len(r.pending))
            self._requeue(st, why=f"replica {r.rid} answered overload",
                          count_retry=False)

    def _merge_timing(self, st: _RoutedReq, msg: dict) -> dict:
        """Extend the replica's per-request timing breakdown with the
        router-side attribution: hops (placements) and retries, the
        replica that finally served it, and the router-observed request
        wall — so the `done` frame alone answers "where did this
        request's seconds go" across the fleet."""
        timing = dict(msg.get("timing") or {})
        timing["router"] = {
            "hops": st.retries + 1,
            "retries": st.retries,
            "replica": st.rid,
            "total_ms": round((time.perf_counter() - st.t0) * 1e3, 3),
        }
        if st.disagg_pages:
            timing["router"]["disagg_pages"] = st.disagg_pages
        return timing

    def _finish(self, st: _RoutedReq, frame: dict) -> None:
        self._routes.pop(st.grid, None)
        st.conn.rids.pop(st.cid, None)
        if self.tracer.enabled:
            # the ingress span: the request's whole router-side lifetime,
            # ending at the terminal frame (done/error/overload) — the
            # parent of every place/relay/retry span and of the replica's
            # lifecycle spans
            attrs = {"trace_id": st.trace_id, "span_id": st.span_id,
                     "terminal": frame.get("type"),
                     "streamed": st.streamed, "retries": st.retries}
            if st.client_parent:
                attrs["parent"] = st.client_parent
            self.tracer.add(
                "ingress", st.t0, time.perf_counter() - st.t0,
                track=f"req:{st.trace_id[:12]}", attrs=attrs)
        st.conn.send(frame)
        if not self._routes and self._idle is not None:
            self._idle.set()

    def _finish_error(self, st: _RoutedReq, message: str) -> None:
        self._finish(st, {"type": "error", "id": st.cid, "error": message})

    # -- placement + retry -------------------------------------------------
    def _requeue(self, st: _RoutedReq, why: str,
                 count_retry: bool = True) -> None:
        """Re-place one routed request after its replica failed it.  Only
        a request the CLIENT has seen nothing of may retry — re-running a
        partially-streamed request could splice a divergent stream."""
        self._routes.pop(st.grid, None)
        if st.streamed > 0:
            self._finish_error(
                st, f"{why} after {st.streamed} tokens were already "
                    f"streamed; not retried (a retry would re-stream "
                    f"from the start) — resubmit the request")
            return
        if st.phase == "prefill":
            # the prefill leg died under us (replica left, circuit open,
            # overload race) — a prefill_only request never streams, so
            # the retry below IS the disagg fallback: re-place the
            # ORIGINAL generate colocated and count the degradation
            st.phase = None
            st.decode_rid = None
            self._m_kv_fallbacks.inc()
        if count_retry:
            st.retries += 1
            if st.retries > self.retry_limit:
                self._finish_error(
                    st, f"{why}; retry limit {self.retry_limit} reached")
                return
        candidates = self._decode_candidates(
            [c for c in self.table.placeable() if c.rid != st.rid])
        if not candidates:
            if not count_retry:
                # the replica REFUSED admission (overload race) and nobody
                # else has capacity: that is fleet saturation, and the
                # client must see the retryable `overload` contract —
                # a terminal error frame would turn transient saturation
                # into a hard failure
                self._m_sheds.inc()
                self.flight.record("shed", reason="replica_overload",
                                   inflight=len(self._routes))
                if self.tracer.enabled:
                    self.tracer.instant(
                        "shed", track=f"req:{st.trace_id[:12]}",
                        reason="replica_overload", trace_id=st.trace_id,
                        parent=st.span_id)
                self._finish(st, {"type": "overload", "id": st.cid,
                                  "reason": "fleet_saturated",
                                  "inflight": len(self._routes),
                                  "max_inflight": sum(
                                      r.max_inflight for r in
                                      self.table.in_state(rep.HEALTHY))})
                return
            self._finish_error(
                st, f"{why}; no healthy replica to retry on")
            return
        replica, policy = self.policy.place(st.msg.get("prompt", []),
                                            candidates)
        if count_retry:
            self._m_retries.inc()
            self.flight.record("retry", req=st.grid, to=replica.rid,
                               why=why, attempt=st.retries)
            if self.tracer.enabled:
                self.tracer.instant(
                    "retry", track=f"req:{st.trace_id[:12]}",
                    to=replica.rid, why=why, attempt=st.retries,
                    trace_id=st.trace_id, parent=st.span_id)
        self._send_to(st, replica, policy)

    def _decode_candidates(self, candidates: list) -> list:
        """Placement preference for the DECODE/colocated path: keep
        prefill-role replicas out of it while any other capacity exists
        (their pool is sized for prompt churn, not long residencies) —
        but roles are ADVISORY, so an all-prefill fleet still serves."""
        return [c for c in candidates if c.role != "prefill"] or candidates

    def _send_to(self, st: _RoutedReq, replica: Replica,
                 policy: str, extra: Optional[dict] = None) -> None:
        # anything that can raise runs BEFORE the routing state mutates:
        # an exception after routes/rids/pending were touched would leak
        # a phantom in-flight request (inflated load, drain wedged)
        t_place = time.perf_counter()
        akey = self.policy.index.key_of(st.msg.get("prompt", []))
        # wire-level trace context: the forwarded frame carries the
        # request's trace_id with the router's ingress span as parent —
        # the replica server adopts it (serving/server.py), which is the
        # whole cross-process stitch
        fwd = dict(st.msg, id=None, stream=True,
                   trace={"trace_id": st.trace_id, "parent": st.span_id})
        if extra:
            fwd.update(extra)          # the prefill_only/push_to leg
        grid = f"g{self._seq}"
        self._seq += 1
        fwd["id"] = grid
        st.grid = grid
        st.rid = replica.rid
        self._routes[grid] = st
        st.conn.rids[st.cid] = grid
        replica.pending.add(grid)
        replica.routed_total += 1
        self._m_placements.inc(policy=policy)
        self.flight.record("route", req=grid, replica=replica.rid,
                           policy=policy,
                           akey=None if akey is None else
                           (hash(akey) & 0xFFFFFFFF))
        ok = replica.backend.send(fwd)
        if self.tracer.enabled:
            # placement decision + backend send, as one span: which
            # replica, under which policy, and whether the send stuck
            self.tracer.add(
                "place", t_place, time.perf_counter() - t_place,
                track=f"req:{st.trace_id[:12]}",
                attrs={"replica": replica.rid, "policy": policy,
                       "sent": ok, "trace_id": st.trace_id,
                       "parent": st.span_id})
        if not ok:
            # the connection died under us before the reader task noticed;
            # take the leave path NOW so this request retries immediately
            self._leave(replica.rid, "connection_lost")

    # -- client connection handling ----------------------------------------
    async def _handle(self, reader, writer) -> None:
        conn = _ClientConn(writer)
        self._conns.add(conn)
        first_frame = True
        try:
            while True:
                try:
                    msg = await wire.read_frame(reader)
                except wire.FrameError as e:
                    err = str(e)
                    if first_frame:
                        err += f"; expected the {wire.PROTO_DESC}"
                    conn.send({"type": "error", "error": err})
                    break
                if msg is None:
                    break
                first_frame = False
                try:
                    await self._dispatch(conn, msg)
                except Exception as e:         # noqa: BLE001 — protocol
                    bad_id = msg.get("id")
                    conn.send({"type": "error",
                               "id": bad_id if isinstance(bad_id, (str, int))
                               else None,
                               "error": f"bad {msg.get('type')!r} frame: "
                                        f"{type(e).__name__}: {e}"})
                try:
                    await writer.drain()
                except ConnectionError:
                    break
        finally:
            conn.dead = True
            self._conns.discard(conn)
            # a vanished client's in-flight work is a cancel, forwarded to
            # whichever replica holds each request
            for grid in list(conn.rids.values()):
                st = self._routes.get(grid)
                if st is None:
                    continue
                r = self.table.get(st.rid)
                if r is not None and r.backend is not None:
                    r.backend.send({"type": "cancel", "id": grid})
            try:
                writer.close()
            except ConnectionError:
                pass

    async def _dispatch(self, conn: _ClientConn, msg: dict) -> None:
        t = msg.get("type")
        if t == "generate":
            self._handle_generate(conn, msg)
        elif t == "cancel":
            cid = msg.get("id")
            grid = conn.rids.get(cid) if isinstance(cid, (str, int)) \
                else None
            st = self._routes.get(grid) if grid else None
            if st is not None:
                r = self.table.get(st.rid)
                if r is not None and r.backend is not None:
                    r.backend.send({"type": "cancel", "id": st.grid})
        elif t == "stats":
            conn.send(self._stats_msg())
        elif t == "metrics":
            if msg.get("aggregate"):
                # the fleet scrape endpoint: the router's own fleet_*
                # rows plus every reachable replica's families under a
                # `replica` label — one text blob for the whole fleet
                text, answered = await self._aggregate_metrics()
                conn.send({"type": "metrics", "text": text,
                           "aggregate": True, "replicas": answered,
                           "content_type": "text/plain; version=0.0.4"})
            else:
                conn.send({"type": "metrics",
                           "text": self.metrics.render(),
                           "content_type": "text/plain; version=0.0.4"})
        elif t == "trace":
            # the router's own span ring, same shape as a replica's
            # trace reply — trace_dump --pull treats both alike, and
            # `enable` flips router-side tracing live (see server.py)
            conn.send(trace_reply(self.tracer, msg, "router",
                                  self.host, self.port))
        elif t == "history":
            # the health plane's ring (loop-thread, stale-ok — see
            # obs/timeseries.py); `aggregate` fans out to every live
            # replica and merges their series under `replica` labels
            if msg.get("aggregate"):
                body = await self._aggregate_history(msg)
                reply = history_reply(self.history,
                                      {"id": msg.get("id")}, "router",
                                      self.host, self.port)
                reply.update(body)
                reply["aggregate"] = True
                conn.send(reply)
            else:
                conn.send(history_reply(self.history, msg, "router",
                                        self.host, self.port))
        elif t == "dump":
            self.flight.record("dump_rpc", router=True)
            if not self.postmortem_dir:
                conn.send({"type": "error", "id": msg.get("id"),
                           "error": "no postmortem dir configured "
                                    "(FleetRouter(postmortem_dir=...) / "
                                    "tools/fleet_router.py "
                                    "--postmortem-dir)"})
                return
            path = self._write_bundle(
                "rpc", replica_traces=await self._collect_replica_traces())
            if path is None:
                conn.send({"type": "error", "id": msg.get("id"),
                           "error": f"postmortem dump failed: "
                                    f"{self._last_dump_error}"})
            else:
                conn.send({"type": "dump", "id": msg.get("id"),
                           "path": path, "events": self.flight.recorded,
                           "spans": self.tracer.recorded})
        elif t == "hello":
            conn.send(wire.hello_msg(
                "router",
                server="paddle_tpu-fleet-router",
                capabilities=sorted(["hello", "generate", "cancel", "stats",
                                     "metrics", "dump", "ping", "fleet",
                                     "trace", "history"]),
                replicas=len(self.table),
                policy=self.policy.mode,
                page_size=self.policy.index.window,
                draining=self._draining))
        elif t == "ping":
            conn.send({"type": "pong"})
        elif t == "fleet":
            await self._handle_fleet_op(conn, msg)
        else:
            conn.send({"type": "error", "id": msg.get("id"),
                       "error": f"unknown message type {t!r}"})

    def _handle_generate(self, conn: _ClientConn, msg: dict) -> None:
        cid = msg.get("id")
        if not isinstance(cid, (str, int)):
            conn.send({"type": "error", "id": cid,
                       "error": "generate needs a string or int 'id'"})
            return
        if cid in conn.rids:
            conn.send({"type": "error", "id": cid,
                       "error": f"id {cid!r} is already in flight on this "
                                f"connection"})
            return
        prompt = msg.get("prompt", [])
        if not isinstance(prompt, list) or \
                not all(isinstance(t, (int, float)) and
                        not isinstance(t, bool) for t in prompt):
            # shape-check the prompt BEFORE placement: the affinity key
            # and every later retry re-read this frame, and garbage must
            # answer an error frame without ever touching routing state
            # (content validation — lengths, ranges — stays the
            # replica's job; its error frame forwards back as-is)
            conn.send({"type": "error", "id": cid,
                       "error": "generate needs a 'prompt' list of "
                                "token ids"})
            return
        if self._draining:
            self._m_sheds.inc()
            self.flight.record("shed", reason="draining")
            conn.send({"type": "overload", "id": cid, "reason": "draining"})
            return
        candidates = self.table.placeable()
        if not candidates:
            # the fleet-level backpressure contract: every healthy
            # replica saturated (or none registered) answers overload
            # NOW — the router holds no queue, so it cannot hold an
            # unbounded one
            reason = "no_replicas" if len(self.table) == 0 \
                else "fleet_saturated"
            self._m_sheds.inc()
            self.flight.record("shed", reason=reason,
                               inflight=len(self._routes))
            if self.tracer.enabled:
                self.tracer.instant("shed", track="router", reason=reason,
                                    inflight=len(self._routes))
            conn.send({"type": "overload", "id": cid, "reason": reason,
                       "inflight": len(self._routes),
                       "max_inflight": sum(
                           r.max_inflight for r in
                           self.table.in_state(rep.HEALTHY))})
            return
        prompt = msg.get("prompt", [])
        st = _RoutedReq(conn, cid, msg, grid="")
        self._m_accepted.inc()
        plan = self._disagg_plan(prompt, candidates)
        if plan is not None:
            prefill_r, decode_r = plan
            st.phase = "prefill"
            st.decode_rid = decode_r.rid
            self._m_kv_pushes.inc()
            self._send_to(st, prefill_r, DISAGG,
                          extra={"prefill_only": True,
                                 "push_to": {"host": decode_r.host,
                                             "port": decode_r.port}})
            return
        replica, policy = self.policy.place(
            prompt, self._decode_candidates(candidates))
        self._send_to(st, replica, policy)

    def _disagg_plan(self, prompt, candidates) -> Optional[tuple]:
        """(prefill replica, decode replica) for a disaggregated
        placement, or None to place colocated.  Fires only for prompts
        past the threshold while BOTH role tiers have a placeable
        member: the decode replica is chosen FIRST (affinity — its
        prefix tree is where the pushed pages will live, so followers
        sharing the prefix chase it there), the prefill replica
        least-loaded within its tier."""
        if self.disagg_min_prompt < 0:
            return None
        floor = self.disagg_min_prompt or self.policy.index.window
        if floor <= 0 or len(prompt) < floor:
            return None
        prefill_tier = [c for c in candidates if c.role == "prefill"]
        decode_tier = [c for c in candidates if c.role == "decode"]
        if not prefill_tier or not decode_tier:
            return None
        decode_r, _ = self.policy.place(prompt, decode_tier)
        prefill_r = min(prefill_tier, key=lambda r: r.score())
        return prefill_r, decode_r

    def _on_prefill_done(self, st: _RoutedReq, msg: dict) -> None:
        """The prefill leg finished: on a successful kv_push route the
        ORIGINAL generate to the decode replica holding the pages (its
        admission is now a prefix hit); on any failure — push refused,
        decode replica gone/unplaceable, prefill cancelled — degrade
        honestly (fallback colocated, or forward the terminal frame)."""
        self._routes.pop(st.grid, None)
        st.conn.rids.pop(st.cid, None)
        st.phase = None
        reason = msg.get("reason")
        if reason not in ("stop", "length"):
            # the client cancelled (or the deadline fired) during the
            # prefill leg — that terminates the REQUEST, not just the leg
            self._finish(st, {"type": "done", "id": st.cid,
                              "tokens": msg.get("tokens"),
                              "reason": reason,
                              "timing": self._merge_timing(st, msg)})
            return
        ok = bool(msg.get("push_ok"))
        if ok:
            st.disagg_pages = int(msg.get("pushed_pages") or 0)
            self._m_kv_pages.inc(float(st.disagg_pages))
        else:
            self._m_kv_push_fail.inc()
        decode_r = self.table.get(st.decode_rid)
        st.decode_rid = None
        if ok and decode_r is not None and decode_r.state == rep.HEALTHY \
                and not decode_r.saturated():
            self._send_to(st, decode_r, DISAGG)
            return
        # fallback: the push failed, or the decode replica died/filled
        # while the prompt prefilled — place colocated like a both-mode
        # fleet would have (zero client-visible failures: nothing
        # streamed, so the re-place is transparent)
        self._m_kv_fallbacks.inc()
        st.disagg_pages = 0
        candidates = self._decode_candidates(self.table.placeable())
        if not candidates:
            self._m_sheds.inc()
            self.flight.record("shed", reason="disagg_fallback",
                               inflight=len(self._routes))
            self._finish(st, {"type": "overload", "id": st.cid,
                              "reason": "fleet_saturated",
                              "inflight": len(self._routes),
                              "max_inflight": sum(
                                  r.max_inflight for r in
                                  self.table.in_state(rep.HEALTHY))})
            return
        replica, policy = self.policy.place(st.msg.get("prompt", []),
                                            candidates)
        self._send_to(st, replica, policy)

    async def _handle_fleet_op(self, conn: _ClientConn, msg: dict) -> None:
        """Operator control frames (fleet/ctl.py): join/leave/drain/
        undrain/list.  Replies echo `op` (and the request id, if any)."""
        op = msg.get("op")
        base = {"type": "fleet", "op": op}
        if msg.get("id") is not None:
            base["id"] = msg["id"]
        try:
            if op == "join":
                r = await self._join(str(msg["host"]), int(msg["port"]))
                conn.send({**base, "ok": True, "replica": r.rid,
                           "state": r.state})
            elif op == "leave":
                r = self._leave(str(msg["replica"]), "ctl_leave")
                if r is None:
                    raise KeyError(f"no replica {msg.get('replica')!r}")
                conn.send({**base, "ok": True, "replica": r.rid})
            elif op in ("drain", "undrain"):
                r = self.table.get(str(msg.get("replica")))
                if r is None:
                    raise KeyError(f"no replica {msg.get('replica')!r}")
                r.drain_requested = op == "drain"
                if r.state in (rep.HEALTHY, rep.DRAINING):
                    r.state = rep.DRAINING if r.drain_requested \
                        else rep.HEALTHY
                # literal kinds on both branches: the event-table lint
                # (tools/check_metrics_names.py) reads first-arg string
                # literals, so a computed kind could ship undocumented
                if op == "drain":
                    self.flight.record("replica_drain", replica=r.rid)
                else:
                    self.flight.record("replica_undrain", replica=r.rid)
                conn.send({**base, "ok": True, "replica": r.rid,
                           "state": r.state,
                           "pending": len(r.pending)})
            elif op == "list":
                conn.send({**base, "ok": True,
                           "replicas": [r.summary() for r in self.table]})
            else:
                conn.send({**base, "ok": False,
                           "error": f"unknown fleet op {op!r} (know: "
                                    f"join/leave/drain/undrain/list)"})
        except (KeyError, ValueError, TypeError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            conn.send({**base, "ok": False,
                       "error": f"{type(e).__name__}: {e}"})

    def _stats_msg(self) -> dict:
        counts = self.table.counts()
        placements = {k[0]: v for k, v in
                      self._m_placements._vals.items()}
        return {
            "type": "stats", "fleet": True,
            "inflight": len(self._routes),
            "draining": self._draining,
            "policy": self.policy.mode,
            "affinity_window": self.policy.index.window,
            "affinity_keys": len(self.policy.index),
            "replicas_registered": len(self.table),
            "replicas_healthy": counts[rep.HEALTHY],
            "replicas_draining": counts[rep.DRAINING],
            "replicas_broken": counts[rep.BROKEN],
            "placements": placements,
            "retries": self._m_retries.value(),
            "sheds": self._m_sheds.value(),
            # disaggregated prefill/decode traffic (docs/serving.md)
            "disagg_min_prompt": self.disagg_min_prompt,
            "kv_pushes": self._m_kv_pushes.value(),
            "kv_push_failures": self._m_kv_push_fail.value(),
            "kv_fallbacks": self._m_kv_fallbacks.value(),
            "kv_pages_shipped": self._m_kv_pages.value(),
            # burst-honest relay inter-token latency (ms): one scanned
            # k-token burst is k tokens of progress, each charged an
            # equal share of the inter-burst gap — comparable across
            # replicas running different decode_steps
            "relay_itl_ms": {k: round(v * 1e3, 3) for k, v in
                             self.stats.percentiles(
                                 "relay_token_latency",
                                 (50.0, 90.0, 99.0)).items()},
            "replicas": [r.summary() for r in self.table],
        }


def _merge_prometheus(parts: list[tuple[Optional[str], str]]) -> str:
    """Merge several Prometheus text expositions into one.

    `parts` is [(replica_label_or_None, text), ...] — the router's own
    render first (unlabeled), then each replica's frame.  Labeled parts
    get `replica="<label>"` injected into every sample, and families are
    REGROUPED so each base name renders exactly one HELP/TYPE pair even
    when both tiers emit it (the tracer/flight accounting does): a
    scraper must never see a family's TYPE declared twice.

    Relies on the renderer's contract (obs/metrics.py render()): samples
    follow their family's HELP/TYPE header contiguously, histogram
    samples (`_bucket`/`_sum`/`_count`) under the base-name header."""
    families: dict = {}            # base -> {"kind", "help", "samples"}
    order: list[str] = []

    def family(base: str) -> dict:
        fam = families.get(base)
        if fam is None:
            fam = families[base] = {"kind": "untyped", "help": "",
                                    "samples": []}
            order.append(base)
        return fam

    for label, text in parts:
        current = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP "):
                base, _, help_ = line[len("# HELP "):].partition(" ")
                fam = family(base)
                fam["help"] = fam["help"] or help_
                current = base
            elif line.startswith("# TYPE "):
                base, _, kind = line[len("# TYPE "):].partition(" ")
                fam = family(base)
                if fam["kind"] == "untyped" and kind:
                    fam["kind"] = kind
                current = base
            elif line.startswith("#"):
                continue
            else:
                head, _, value = line.rpartition(" ")
                if not head:
                    continue
                if label is not None:
                    if head.endswith("}"):
                        head = head[:-1] + f',replica="{label}"}}'
                    else:
                        head = head + f'{{replica="{label}"}}'
                name = head.partition("{")[0]
                base = (current if current and name.startswith(current)
                        else name)
                family(base)["samples"].append(f"{head} {value}")
    lines = []
    for base in order:
        fam = families[base]
        if fam["help"]:
            lines.append(f"# HELP {base} {fam['help']}")
        lines.append(f"# TYPE {base} {fam['kind']}")
        lines.extend(fam["samples"])
    return "\n".join(lines) + ("\n" if lines else "")
