"""Core layers: data, fc, mixed (projections/operators), addto, concat.

Covers the reference's bread-and-butter layer types (ref:
paddle/gserver/layers/{DataLayer,FullyConnectedLayer,MixedLayer,AddtoLayer,
ConcatenateLayer}.cpp and the projection zoo in FullMatrixProjection.cpp,
TableProjection.cpp, IdentityProjection.cpp, DotMulProjection.cpp,
ContextProjection.cpp, DotMulOperator.cpp).  Every op is a jnp expression on
the padded batch — one XLA fusion region instead of per-layer virtual calls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig, OperatorConfig, ProjectionConfig
from paddle_tpu.graph.common import finish_layer, tp_constrain
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.ops import sequence as seqops
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


@register_layer("data")
def data_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Input placeholder — the feed dict supplies its value
    (ref: DataLayer.cpp; builder pre-populates ctx.outputs)."""
    raise AssertionError("data layers are fed, not computed")


def _matmul(x: Array, w: Array) -> Array:
    """Last-dim matmul that works for [B,D] and [B,T,D]."""
    return jnp.matmul(x, w)


def _input_matmul(arg: Argument, w: Array) -> Array:
    """x @ W where x may be a sparse-row argument: gather the K touched
    parameter rows and weight-sum them — compute and memory ∝ nnz, and the
    backward pass is a scatter-add into only those rows (ref: the reference's
    SparseRowMatrix / hl_matrix_dense_mul_csr path)."""
    if arg.sparse_dim:
        rows = w[arg.ids]                                  # [..., K, Dout]
        return jnp.sum(rows * arg.sparse_vals[..., None].astype(rows.dtype),
                       axis=-2)
    return _matmul(arg.value, w)


@register_layer("fc")
def fc_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Fully connected: sum_i x_i @ W_i + b, then activation
    (ref: FullyConnectedLayer.cpp forward: Matrix::mul per input + addBias).

    Under tensor-parallel serving the engine may stamp `tp_out` on this
    layer (the Megatron FFN/LM-head split) — the pre-bias pin forces a
    row-sharded matmul's partial sums into their all-reduce BEFORE the
    (replicated) bias adds, and finish_layer's tp_constrain re-pins the
    activated output."""
    inputs = ctx.get_inputs(cfg)
    acc = None
    for i, arg in enumerate(inputs):
        w = ctx.param_of(cfg, i)
        y = _input_matmul(arg, w)
        acc = y if acc is None else acc + y
    acc = tp_constrain(ctx, cfg, acc)
    b = ctx.bias_of(cfg)
    if b is not None:
        acc = acc + b
    return finish_layer(ctx, cfg, acc, like=inputs[0])


# ---------------------------------------------------------------------------
# mixed layer: sum of projections + operators (ref: MixedLayer.cpp)
# ---------------------------------------------------------------------------

def _apply_projection(
    ctx: ForwardContext, proj: ProjectionConfig, arg: Argument, w: Optional[Array]
) -> Array:
    t = proj.type
    if t in ("fc", "full_matrix"):
        return _input_matmul(arg, w)
    if t == "trans_full_matrix":
        return _matmul(arg.value, w.T)
    if t == "identity":
        assert not arg.sparse_dim, (
            "identity projection over a sparse-row input would expose raw "
            "column indices as activations — use a full_matrix projection "
            "(gather path) or Argument.to_dense()")
        return arg.data
    if t == "dot_mul":
        # elementwise scale by a learned vector (ref: DotMulProjection.cpp)
        return arg.value * w
    if t == "scaling":
        # one learned scalar (ref: ScalingProjection.cpp)
        return arg.value * w.reshape(())
    if t == "table":
        # embedding lookup (ref: TableProjection.cpp, hl_matrix_select_rows)
        assert not arg.sparse_dim, (
            "table projection expects token ids, not sparse-row column "
            "indices (padding slots would embed id 0) — a sparse slot wants "
            "a full_matrix projection, which gathers+sums the touched rows")
        return w[arg.ids]
    if t == "context":
        padding = None
        if proj.trainable_padding:
            padding = w
        return seqops.context_projection(
            arg.value, arg.lengths, proj.context_start, proj.context_length, padding)
    if t == "conv":
        from paddle_tpu.graph.layers_conv import conv_projection_forward
        return conv_projection_forward(proj, arg, w)
    raise NotImplementedError(f"projection type {t!r}")


def _apply_operator(ctx: ForwardContext, op: OperatorConfig, inputs: list[Argument]) -> Array:
    if op.type == "dot_mul":
        a, b = (inputs[i] for i in op.input_indices[:2])
        return op.dotmul_scale * a.value * b.value
    if op.type == "conv":
        from paddle_tpu.graph.layers_conv import conv_operator_forward
        a, b = (inputs[i] for i in op.input_indices[:2])
        return conv_operator_forward(op, a, b)
    raise NotImplementedError(f"operator type {op.type!r}")


@register_layer("mixed")
def mixed_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Sum of per-input projections plus operators plus bias
    (ref: MixedLayer.cpp forward)."""
    inputs = ctx.get_inputs(cfg)
    acc = None
    like = inputs[0] if inputs else None
    for i, (inp, arg) in enumerate(zip(cfg.inputs, inputs)):
        if inp.proj is None:
            continue
        w = ctx.param_of(cfg, i)
        y = _apply_projection(ctx, inp.proj, arg, w)
        if arg.is_sequence and (like is None or not like.is_sequence):
            like = arg
        acc = y if acc is None else acc + y
    for op in cfg.operators:
        y = _apply_operator(ctx, op, inputs)
        acc = y if acc is None else acc + y
    b = ctx.bias_of(cfg)
    if b is not None:
        acc = acc + b
    # sequence structure: a table projection over id sequences yields [B,T,D]
    lengths = like.lengths if (like is not None and acc.ndim >= 3) else None
    return finish_layer(ctx, cfg, acc, like=like, lengths=lengths)


@register_layer("addto")
def addto_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Elementwise sum of all inputs + bias (ref: AddtoLayer.cpp)."""
    inputs = ctx.get_inputs(cfg)
    acc = inputs[0].value
    for arg in inputs[1:]:
        acc = acc + arg.value
    b = ctx.bias_of(cfg)
    if b is not None:
        acc = acc + b
    return finish_layer(ctx, cfg, acc, like=inputs[0])


@register_layer("concat")
def concat_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Feature-dim concatenation (ref: ConcatenateLayer.cpp)."""
    inputs = ctx.get_inputs(cfg)
    acc = jnp.concatenate([a.value for a in inputs], axis=-1)
    return finish_layer(ctx, cfg, acc, like=inputs[0])


@register_layer("concat2")
def concat2_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Concatenation of projected inputs + bias (ref: ConcatenateLayer2)."""
    inputs = ctx.get_inputs(cfg)
    parts = []
    for i, (inp, arg) in enumerate(zip(cfg.inputs, inputs)):
        w = ctx.param_of(cfg, i)
        parts.append(_apply_projection(ctx, inp.proj, arg, w) if inp.proj else arg.value)
    acc = jnp.concatenate(parts, axis=-1)
    b = ctx.bias_of(cfg)
    if b is not None:
        acc = acc + b
    return finish_layer(ctx, cfg, acc, like=inputs[0])
