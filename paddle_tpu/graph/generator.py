"""Sequence generation: greedy and beam search over a generator sub-model.

TPU re-design of the reference's generation machinery (ref:
paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp:
generateSequence :804, oneWaySearch :876, beamSearch :1211, Path struct
RecurrentGradientMachine.h:180-250).

The reference steps frame networks one timestep at a time on the host,
expanding an explicit Path list per beam candidate.  Here the whole search is
ONE `lax.scan` with static shapes: the beam is flattened into the batch
dimension ([B*K] rows through the decoder step), candidate expansion is a
top-k over K*V scores, beam-parent gathers re-index the memory carries, and
finished beams are frozen with masks.  XLA compiles the entire search,
including the decoder step, into a single program — no host round-trips per
token.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import ModelConfig, SubModelConfig
from paddle_tpu.graph.context import GEN, ForwardContext
from paddle_tpu.parameter.argument import Argument

Array = jax.Array
_NEG_INF = -1e9


@dataclasses.dataclass
class BeamSearchControls:
    """User control hooks for beam search — the TPU re-design of
    registerBeamSearchControlCallbacks / registerBeamSearchStatisticsCallbacks
    (ref: RecurrentGradientMachine.h:86-170).

    The reference's hooks are host-side std::functions invoked per step;
    that shape would force a host round-trip every token.  Here each hook
    is a JAX-TRACEABLE function compiled straight into the search scan, so
    constrained decoding runs at full device speed:

    - adjust_logp(step, tokens, logp) -> logp': reshape next-token
      log-probabilities [B, K, V] before candidate expansion (the
      BeamSearchCandidatesAdjustCallback analog — ban words, force
      prefixes, add lexical bonuses).  `tokens` is the previous step's
      [B, K] choices.
    - stop_path(step, tokens, scores) -> [B, K] bool: force-finish paths
      (the DropCallback analog; a stopped path is frozen exactly like one
      that emitted EOS).
    - norm_path(scores, lengths) -> scores': final path-score
      normalization, replacing the default length normalization (the
      NormOrDropNodeCallback analog).
    - on_step(step): host-side statistics hook dispatched via
      jax.debug.callback (the EachStepCallback analog; async, diagnostic
      only).
    """

    adjust_logp: Optional[Callable[[Array, Array, Array], Array]] = None
    stop_path: Optional[Callable[[Array, Array, Array], Array]] = None
    norm_path: Optional[Callable[[Array, Array], Array]] = None
    on_step: Optional[Callable[[Any], None]] = None


def _tile_beam(x: Array, K: int) -> Array:
    """[B, ...] -> [B*K, ...] repeating each row K times."""
    return jnp.repeat(x, K, axis=0)


def _gather_beam(x: Array, parent: Array, B: int, K: int) -> Array:
    """Re-select beam rows after top-k: x [B*K, ...], parent [B, K] in [0,K)."""
    xs = x.reshape((B, K) + x.shape[1:])
    out = jnp.take_along_axis(
        xs, parent.reshape(B, K, *([1] * (x.ndim - 1))), axis=1)
    return out.reshape((B * K,) + x.shape[1:])


class SequenceGenerator:
    """Runs a generator sub-model (ref: SubModelConfig.generator).

    Usage: gen = SequenceGenerator(executor, sm); ids, scores = gen(params, feed).
    `feed` supplies the root-graph inputs (encoder side); the root layers are
    executed first, then the search loop.
    """

    def __init__(self, executor, sm: SubModelConfig,
                 beam_size: Optional[int] = None,
                 max_length: Optional[int] = None,
                 controls: Optional[BeamSearchControls] = None):
        assert sm.generator is not None, f"sub-model {sm.name!r} has no generator"
        self.executor = executor
        self.sm = sm
        self.gen = sm.generator
        self.beam_size = beam_size or self.gen.beam_size or 1
        self.max_length = max_length or self.gen.max_num_frames
        self.controls = controls or BeamSearchControls()
        # the WHOLE search — encoder + scan — compiles once per feed shape;
        # repeat decodes with the same shapes skip tracing entirely
        self._jitted = jax.jit(self._search)

    def __call__(self, params: dict[str, Array], feed: dict[str, Argument],
                 rng: Optional[jax.Array] = None) -> tuple[Array, Array]:
        return self._jitted(params, feed, rng)

    def _search(self, params: dict[str, Array], feed: dict[str, Argument],
                rng: Optional[jax.Array] = None) -> tuple[Array, Array]:
        """Returns (ids [B, K, L] int32 with EOS-padding, scores [B, K] log p).

        Beams are sorted best-first; K = beam_size.
        """
        ex = self.executor
        sm, gen = self.sm, self.gen
        K, L = self.beam_size, self.max_length

        # run the root graph (encoder) up to the group boundary
        ctx = ForwardContext(model=ex.model, params=params, mode=GEN, rng=rng)
        for name, arg in feed.items():
            ctx.outputs[name] = arg
        for kind, item in ex._plan:
            if kind == "layer":
                cfg = item
                if any(i.input_layer_name not in ctx.outputs for i in cfg.inputs):
                    continue
                from paddle_tpu.graph.registry import get_layer_fn
                ctx.outputs[cfg.name] = get_layer_fn(cfg.type)(ctx, cfg)
            elif item is not sm and not (item.generator is not None and not item.in_links):
                ex._run_scan(ctx, item)

        # batch size from any static link / feed
        static_alias = dict(zip(sm.static_links, sm.static_link_layers))
        some = next(iter(feed.values()))
        B = some.batch_size

        # static (encoder) inputs tiled K-fold into the flattened beam batch
        static_feeds: dict[str, Argument] = {}
        for outer, inner in static_alias.items():
            arg = ctx.outputs[outer]
            static_feeds[inner] = Argument(
                value=None if arg.value is None else _tile_beam(arg.value, K),
                ids=None if arg.ids is None else _tile_beam(arg.ids, K),
                lengths=None if arg.lengths is None else _tile_beam(arg.lengths, K))

        # initial memory carries, tiled
        id_mem_name = gen.id_memory_layer_name
        carry0: dict[str, Array] = {}
        mem_by_agent: dict[str, Any] = {}
        for mem in sm.memories:
            mem_by_agent[mem.layer_name] = mem
            if mem.layer_name == id_mem_name:
                continue  # token memory handled by the beam state
            if mem.boot_layer_name:
                boot = ctx.outputs[mem.boot_layer_name].data
            else:
                boot = jnp.zeros((B, mem.size), jnp.float32)
            carry0[mem.layer_name] = _tile_beam(boot, K)

        prob_layer = gen.prob_layer_name
        eos = gen.eos_id

        ctl = self.controls

        def decode_step(state, t):
            tokens, scores, finished, carries = state
            if ctl.on_step is not None:
                jax.debug.callback(ctl.on_step, t)
            sub = ForwardContext(model=ex.model, params=params, mode=GEN, rng=rng)
            sub.outputs.update(static_feeds)
            sub.outputs[id_mem_name] = Argument(ids=tokens.reshape(B * K))
            for agent_name, c in carries.items():
                sub.outputs[agent_name] = Argument(value=c)
            ex.run_group_layers(sm, sub)
            probs = sub.outputs[prob_layer].data.reshape(B, K, -1)
            V = probs.shape[-1]
            logp = jnp.log(jnp.maximum(probs, 1e-12))
            if ctl.adjust_logp is not None:
                logp = ctl.adjust_logp(t, tokens, logp)
            if ctl.stop_path is not None:
                finished = finished | ctl.stop_path(t, tokens, scores)
            # finished beams may only emit EOS at zero cost
            eos_only = jnp.full((V,), _NEG_INF).at[eos].set(0.0)
            step_logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
            total = scores[..., None] + step_logp          # [B, K, V]
            flat = total.reshape(B, K * V)
            new_scores, flat_idx = jax.lax.top_k(flat, K)  # [B, K]
            parent = flat_idx // V
            new_tok = (flat_idx % V).astype(jnp.int32)
            # reorder state by beam parent
            new_carries = {}
            for agent_name in carries:
                link = mem_by_agent[agent_name].link_name
                out = sub.outputs[link].data
                out = _gather_beam(out, parent, B, K)
                prev = _gather_beam(carries[agent_name], parent, B, K)
                fin = jnp.take_along_axis(finished, parent, axis=1).reshape(B * K)
                new_carries[agent_name] = jnp.where(
                    fin.reshape(B * K, *([1] * (out.ndim - 1))), prev, out)
            new_finished = jnp.take_along_axis(finished, parent, axis=1) | (new_tok == eos)
            return ((new_tok, new_scores, new_finished, new_carries),
                    (new_tok, parent))

        tokens0 = jnp.full((B, K), gen.bos_id, jnp.int32)
        scores0 = jnp.tile(jnp.where(jnp.arange(K) == 0, 0.0, _NEG_INF)[None, :], (B, 1))
        finished0 = jnp.zeros((B, K), bool)

        init = (tokens0, scores0, finished0, carry0)
        (tok_f, scores_f, fin_f, _), (toks, parents) = jax.lax.scan(
            decode_step, init, jnp.arange(L))
        # toks: [L, B, K]; parents: [L, B, K] — backtrack to recover sequences
        def back(nxt_parent, inp):
            tok_t, par_t = inp
            tok = jnp.take_along_axis(tok_t, nxt_parent, axis=1)
            par = jnp.take_along_axis(par_t, nxt_parent, axis=1)
            return par, tok

        last_parent = jnp.tile(jnp.arange(K)[None, :], (B, 1))
        _, seq_rev = jax.lax.scan(back, last_parent, (toks, parents), reverse=True)
        seqs = jnp.moveaxis(seq_rev, 0, 2)          # [B, K, L]
        # pad everything after the first EOS with EOS
        eos_seen = jnp.cumsum((seqs == eos).astype(jnp.int32), axis=-1)
        seqs = jnp.where(eos_seen > 1, eos, seqs)
        if ctl.norm_path is not None:
            lengths = jnp.sum((eos_seen == 0).astype(jnp.float32), axis=-1) + 1.0
            out_scores = ctl.norm_path(scores_f, lengths)
        elif gen.log_prob:
            out_scores = scores_f
        else:
            lengths = jnp.sum((eos_seen == 0).astype(jnp.float32), axis=-1) + 1.0
            out_scores = scores_f / lengths
        return seqs, out_scores


def generate(executor, params: dict[str, Array], feed: dict[str, Argument],
             rng: Optional[jax.Array] = None,
             beam_size: Optional[int] = None,
             max_length: Optional[int] = None,
             controls: Optional[BeamSearchControls] = None) -> tuple[Array, Array]:
    """Convenience: find the generator sub-model and run the search
    (ref: GradientMachine::generateSequence dispatch)."""
    gens = [sm for sm in executor.model.sub_models if sm.generator is not None]
    assert gens, "model has no generator sub-model"
    ctl = controls or BeamSearchControls()
    # memoize generators on the executor so repeat generate() calls reuse
    # the compiled search instead of re-tracing.  Keyed on hook IDENTITY —
    # reuse one long-lived BeamSearchControls per constraint set; a fresh
    # lambda every call recompiles every call.  LRU-bounded so per-call
    # closures degrade to recompiles, not unbounded memory growth.
    from collections import OrderedDict
    cache = executor.__dict__.setdefault("_generator_cache", OrderedDict())
    key = (gens[0].name, beam_size, max_length, ctl.adjust_logp,
           ctl.stop_path, ctl.norm_path, ctl.on_step)
    if key in cache:
        cache.move_to_end(key)
    else:
        cache[key] = SequenceGenerator(executor, gens[0], beam_size,
                                       max_length, ctl)
        while len(cache) > 8:
            cache.popitem(last=False)
    return cache[key](params, feed, rng)
