"""Layer registry: LayerConfig.type string -> implementation function.

TPU-native analog of the reference's REGISTER_LAYER/ClassRegistrar pattern
(ref: paddle/gserver/layers/Layer.h:32-37, paddle/utils/ClassRegistrar.h),
with layer *functions* instead of stateful Layer objects: a layer impl is a
pure function (ctx, cfg, inputs) -> Argument traced under jit, and autodiff
replaces every hand-written backward() in the reference's layer zoo.
"""

from __future__ import annotations

from typing import Callable, TYPE_CHECKING

if TYPE_CHECKING:
    from paddle_tpu.graph.context import ForwardContext
    from paddle_tpu.config.schema import LayerConfig
    from paddle_tpu.parameter.argument import Argument

LayerFn = Callable[..., "Argument"]

layer_registry: dict[str, LayerFn] = {}

# Layer types whose output is a training cost (they write ctx.costs) —
# the analog of the reference's CostLayer subtree (ref:
# paddle/gserver/layers/CostLayer.cpp). Consumers (e.g. lm_decode's
# logits-layer default) use this instead of string-matching type names.
cost_layer_types: set[str] = set()

# Validation layer types (ref: ValidationLayer.h) — in-graph evaluator
# hosts; pass-throughs, never a model's real output.
validation_layer_types: set[str] = set()


def register_layer(*type_names: str, cost: bool = False,
                   validation: bool = False):
    def deco(fn: LayerFn) -> LayerFn:
        for name in type_names:
            if name in layer_registry:
                raise ValueError(f"duplicate layer type {name!r}")
            layer_registry[name] = fn
            if cost:
                cost_layer_types.add(name)
            if validation:
                validation_layer_types.add(name)
        return fn
    return deco


def get_layer_fn(type_name: str) -> LayerFn:
    try:
        return layer_registry[type_name]
    except KeyError:
        raise NotImplementedError(
            f"layer type {type_name!r} not implemented; known: {sorted(layer_registry)}")
