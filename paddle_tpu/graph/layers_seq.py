"""Sequence & recurrent layers.

Covers the reference's sequence layer family (ref: paddle/gserver/layers/
{SequencePoolLayer,MaxLayer,AverageLayer,SequenceLastInstanceLayer,ExpandLayer,
SequenceConcatLayer,SequenceReshapeLayer,LstmLayer,GatedRecurrentLayer,
RecurrentLayer,MaxIdLayer,SamplingIdLayer,EosIdCheckLayer,CRFLayer,
CRFDecodingLayer,CTCLayer,NCELayer,HierarchicalSigmoidLayer}.cpp) on the
padded-dense sequence representation with lax.scan recurrence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.common import finish_layer
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.ops import rnn as rnnops
from paddle_tpu.ops import sequence as seqops
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.utils.flags import FLAGS

Array = jax.Array


def _prev_state(ctx: ForwardContext, cfg: LayerConfig, B: int,
                names: tuple[str, ...]) -> list:
    """Truncated-BPTT continuation (ref: RecurrentLayer.cpp prevOutput_;
    --prev_batch_state): under the flag, a forward recurrent layer boots
    from the previous batch's final state, carried through the net_state
    channel (the same jit-friendly path as batch-norm moving stats).
    Returns one initial state per name (None = zeros).  The state is
    stop_gradiented — BPTT truncates at the batch boundary — and ignored
    when the batch size changes (stream restart)."""
    if not FLAGS.prev_batch_state or cfg.reversed:
        return [None] * len(names)
    out = []
    for n in names:
        s = ctx.state_in.get(f"{cfg.name}:{n}")
        out.append(jax.lax.stop_gradient(s)
                   if s is not None and s.shape[0] == B else None)
    return out


def _save_state(ctx: ForwardContext, cfg: LayerConfig, **states) -> None:
    if not FLAGS.prev_batch_state or cfg.reversed:
        return
    for n, v in states.items():
        ctx.state_out[f"{cfg.name}:{n}"] = v


# ---------------------------------------------------------------------------
# pooling over time
# ---------------------------------------------------------------------------

def _per_sub(cfg, x) -> bool:
    """Whether a nested ([B,S,T,D]) input pools PER SUB-SEQUENCE (output a
    [B,S,D] sequence) instead of over all valid tokens (output [B,D]).

    The all-token reduction is the default and matches the reference's
    default AggregateLevel.EACH_TIMESTEP; an explicit agg_level='seq'
    (AggregateLevel.EACH_SEQUENCE, carried in LayerConfig.trans_type)
    selects the per-sub form (ref: SequencePoolLayer.cpp sequence-level
    dispatch, which CHECKs hasSubseq for the 'seq' level — mirrored
    here)."""
    if cfg.trans_type == "seq":
        if x.sub_lengths is None:
            raise ValueError(
                f"layer {cfg.name!r}: agg_level=AggregateLevel."
                f"EACH_SEQUENCE needs a NESTED (sub-sequence) input; "
                f"this input is a plain sequence — drop agg_level or "
                f"feed sub_lengths")
        return True
    return False


@register_layer("max")
def max_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    x = ctx.get_input(cfg, 0)
    if _per_sub(cfg, x):
        out = seqops.nested_pool_max_per_sub(x.value, x.lengths,
                                             x.sub_lengths)
        return finish_layer(ctx, cfg, out, lengths=x.lengths)
    if x.sub_lengths is not None:
        out = seqops.nested_pool_max(x.value, x.lengths, x.sub_lengths)
    else:
        out = seqops.seq_pool_max(x.value, x.lengths)
    return finish_layer(ctx, cfg, out)


@register_layer("average")
def average_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    x = ctx.get_input(cfg, 0)
    if _per_sub(cfg, x):
        out = seqops.nested_pool_avg_per_sub(x.value, x.lengths,
                                             x.sub_lengths,
                                             cfg.average_strategy)
        return finish_layer(ctx, cfg, out, lengths=x.lengths)
    if x.sub_lengths is not None:
        out = seqops.nested_pool_avg(x.value, x.lengths, x.sub_lengths,
                                     cfg.average_strategy)
    else:
        out = seqops.seq_pool_avg(x.value, x.lengths, cfg.average_strategy)
    return finish_layer(ctx, cfg, out)


@register_layer("seqlastins")
def seq_last_ins_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    x = ctx.get_input(cfg, 0)
    if _per_sub(cfg, x):
        out = seqops.nested_pool_edge_per_sub(x.value, x.lengths,
                                              x.sub_lengths,
                                              bool(cfg.select_first))
        return finish_layer(ctx, cfg, out, lengths=x.lengths)
    if x.sub_lengths is not None:
        pool = (seqops.nested_pool_first if cfg.select_first
                else seqops.nested_pool_last)
        out = pool(x.value, x.lengths, x.sub_lengths)
    elif cfg.select_first:
        out = seqops.seq_pool_first(x.value, x.lengths)
    else:
        out = seqops.seq_pool_last(x.value, x.lengths)
    return finish_layer(ctx, cfg, out)


@register_layer("expand")
def expand_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Broadcast per-sequence vector across a target sequence's timesteps
    (ref: ExpandLayer.cpp; input 1 provides the sequence layout)."""
    x = ctx.get_input(cfg, 0)
    like = ctx.get_input(cfg, 1)
    out = seqops.expand_to_sequence(x.value, like.lengths, like.max_len)
    b = ctx.bias_of(cfg)
    if b is not None:
        out = out + b
    return finish_layer(ctx, cfg, out, like=like, lengths=like.lengths)


@register_layer("subseq")
def sub_sequence_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Per-sequence slice by offset/size id inputs (ref: SubSequenceLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    off = ctx.get_input(cfg, 1)
    sz = ctx.get_input(cfg, 2)
    out, lengths = seqops.sub_sequence(x.value, off.ids.reshape(-1),
                                       sz.ids.reshape(-1), lengths=x.lengths)
    b = ctx.bias_of(cfg)
    if b is not None:
        out = out + b
    return finish_layer(ctx, cfg, out, lengths=lengths)


@register_layer("seqconcat")
def seq_concat_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    out, lengths = seqops.seq_concat(a.value, a.lengths, b.value, b.lengths)
    return finish_layer(ctx, cfg, out, lengths=lengths)


@register_layer("seqreshape")
def seq_reshape_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    x = ctx.get_input(cfg, 0)
    out, lengths = seqops.seq_reshape(x.value, x.lengths, cfg.size)
    return finish_layer(ctx, cfg, out, lengths=lengths)


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------

@register_layer("lstmemory")
def lstmemory_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """LSTM over a pre-projected [B,T,4D] input (ref: LstmLayer.cpp — the
    input projection is the layer below, as in the reference DSL; recurrent
    weight [D,4D] on the input edge; bias [4D] or [7D] with peepholes)."""
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    b = ctx.bias_of(cfg)
    h0, c0 = _prev_state(ctx, cfg, x.value.shape[0], ("h", "c"))
    hs, last_h, last_c = rnnops.lstm_scan(
        x.value, x.lengths, w, b, h0=h0, c0=c0,
        active_type=cfg.active_type or "tanh",
        gate_active_type=cfg.attrs.get("active_gate_type", "sigmoid"),
        state_active_type=cfg.attrs.get("active_state_type", "tanh"),
        reverse=cfg.reversed,
    )
    _save_state(ctx, cfg, h=last_h, c=last_c)
    out_cfg = _without_activation(cfg)
    return finish_layer(ctx, out_cfg, hs, like=x, lengths=x.lengths)


@register_layer("gated_recurrent")
def gated_recurrent_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """GRU over a pre-projected [B,T,3D] input (ref: GatedRecurrentLayer.cpp);
    one recurrent parameter [D,3D] split into gate [D,2D] + candidate [D,D]."""
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    b = ctx.bias_of(cfg)
    D = cfg.size
    (h0,) = _prev_state(ctx, cfg, x.value.shape[0], ("h",))
    hs, last_h = rnnops.gru_scan(
        x.value, x.lengths, w[:, : 2 * D], w[:, 2 * D:], b, h0=h0,
        active_type=cfg.active_type or "tanh",
        gate_active_type=cfg.attrs.get("active_gate_type", "sigmoid"),
        reverse=cfg.reversed,
    )
    _save_state(ctx, cfg, h=last_h)
    out_cfg = _without_activation(cfg)
    return finish_layer(ctx, out_cfg, hs, like=x, lengths=x.lengths)


@register_layer("mdlstmemory")
def mdlstm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """2-D multi-dimensional LSTM over a static [H, W] grid
    (ref: MDLstmLayer.cpp:180-486).  Input is pre-projected [B, H*W, 5D];
    grid geometry comes from attrs['height'/'width'], scan direction per
    dimension from attrs['directions']."""
    from paddle_tpu.ops.mdlstm import mdlstm_2d
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    b = ctx.bias_of(cfg)
    assert b is not None, "mdlstmemory requires its bias/peephole parameter"
    directions = tuple(cfg.attrs.get("directions", (True, True)))
    assert len(directions) == 2, "TPU mdlstmemory supports 2-D grids"
    out = mdlstm_2d(
        x.value, w, b,
        height=cfg.attrs["height"], width=cfg.attrs["width"],
        directions=directions, lengths=x.lengths,
        active_type=cfg.active_type or "tanh",
        gate_active_type=cfg.attrs.get("active_gate_type", "sigmoid"),
        state_active_type=cfg.attrs.get("active_state_type", "tanh"),
    )
    out_cfg = _without_activation(cfg)
    return finish_layer(ctx, out_cfg, out, like=x, lengths=x.lengths)


@register_layer("recurrent")
def recurrent_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Vanilla RNN h_t = act(x_t + h W) (ref: RecurrentLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    b = ctx.bias_of(cfg)
    (h0,) = _prev_state(ctx, cfg, x.value.shape[0], ("h",))
    hs, last_h = rnnops.simple_rnn_scan(
        x.value, x.lengths, w, b, h0=h0,
        active_type=cfg.active_type or "tanh", reverse=cfg.reversed)
    _save_state(ctx, cfg, h=last_h)
    out_cfg = _without_activation(cfg)
    return finish_layer(ctx, out_cfg, hs, like=x, lengths=x.lengths)


@register_layer("lstm_step")
def lstm_step_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """One LSTM step on [B,4D] pre-projected input + [B,D] prev cell
    (ref: LstmStepLayer.cpp).  Publishes the new cell under attrs['state_name']."""
    from paddle_tpu.ops.activations import activation_registry
    x4 = ctx.get_input(cfg, 0).value
    c_prev = ctx.get_input(cfg, 1).value
    b = ctx.bias_of(cfg)
    D = cfg.size
    act = activation_registry[cfg.active_type or "tanh"]
    gate = activation_registry[cfg.attrs.get("active_gate_type", "sigmoid")]
    state_act = activation_registry[cfg.attrs.get("active_state_type", "tanh")]
    peep_i = peep_f = peep_o = None
    if b is not None:
        b = b.reshape(-1)
        if b.shape[-1] == 7 * D:
            x4 = x4 + b[: 4 * D]
            peep_i, peep_f, peep_o = b[4 * D:5 * D], b[5 * D:6 * D], b[6 * D:]
        else:
            x4 = x4 + b
    a = act(x4[:, :D])
    zi, zf, zo = x4[:, D:2 * D], x4[:, 2 * D:3 * D], x4[:, 3 * D:]
    if peep_i is not None:
        zi = zi + c_prev * peep_i
        zf = zf + c_prev * peep_f
    i = gate(zi)
    f = gate(zf)
    c_new = a * i + f * c_prev
    if peep_o is not None:
        zo = zo + c_new * peep_o
    o = gate(zo)
    h = o * state_act(c_new)
    ctx.outputs[cfg.attrs["state_name"]] = Argument(value=c_new)
    return Argument(value=h)


@register_layer("gru_step")
def gru_step_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """One GRU step on [B,3D] pre-projected input + [B,D] prev hidden, with
    its own recurrent weight [D,3D] (ref: GruStepLayer.cpp)."""
    from paddle_tpu.ops.activations import activation_registry
    x3 = ctx.get_input(cfg, 0).value
    h_prev = ctx.get_input(cfg, 1).value
    w = ctx.param_of(cfg, 0)
    b = ctx.bias_of(cfg)
    D = cfg.size
    act = activation_registry[cfg.active_type or "tanh"]
    gate = activation_registry[cfg.attrs.get("active_gate_type", "sigmoid")]
    if b is not None:
        x3 = x3 + b.reshape(-1)
    zg = x3[:, : 2 * D] + h_prev @ w[:, : 2 * D]
    u = gate(zg[:, :D])
    r = gate(zg[:, D:])
    c = act(x3[:, 2 * D:] + (r * h_prev) @ w[:, 2 * D:])
    h = u * h_prev + (1.0 - u) * c
    return Argument(value=h)


def _without_activation(cfg: LayerConfig) -> LayerConfig:
    """Recurrent cells apply their activations inside the scan — strip
    active_type so finish_layer doesn't re-apply it."""
    import dataclasses
    return dataclasses.replace(cfg, active_type="")


# ---------------------------------------------------------------------------
# id/decision layers
# ---------------------------------------------------------------------------

@register_layer("maxid")
def maxid_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Argmax ids (+ beam_size top-k ids when configured)
    (ref: MaxIdLayer.cpp, hl_top_k)."""
    x = ctx.get_input(cfg, 0)
    k = max(cfg.beam_size, 1)
    if k == 1:
        ids = jnp.argmax(x.value, axis=-1).astype(jnp.int32)
        return Argument(ids=ids, lengths=x.lengths)
    vals, ids = jax.lax.top_k(x.value, k)
    return Argument(value=vals, ids=ids.astype(jnp.int32), lengths=x.lengths)


@register_layer("sampling_id")
def sampling_id_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Sample an id from each row's distribution (ref: SamplingIdLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    logits = jnp.log(jnp.maximum(x.value, 1e-10))
    ids = jax.random.categorical(ctx.next_rng(), logits, axis=-1).astype(jnp.int32)
    return Argument(ids=ids, lengths=x.lengths)


@register_layer("eos_id")
def eos_id_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """1 where input id == eos (ref: EosIdCheckLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    eos = cfg.attrs.get("eos_id", 0)
    ids = (x.ids == eos).astype(jnp.int32)
    return Argument(ids=ids, lengths=x.lengths)


# ---------------------------------------------------------------------------
# structured-output layers: CRF / CTC
# ---------------------------------------------------------------------------

@register_layer("crf", cost=True)
def crf_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Linear-chain CRF negative log-likelihood over each sequence
    (ref: CRFLayer.cpp, LinearChainCRF.cpp)."""
    from paddle_tpu.ops.crf import crf_nll
    x = ctx.get_input(cfg, 0)
    lbl = ctx.get_input(cfg, 1)
    w = ctx.param_of(cfg, 0)
    cost = crf_nll(x.value, lbl.ids, x.lengths, w)
    if len(cfg.inputs) > 2:
        wt = ctx.get_input(cfg, 2)
        cost = cost * wt.data.reshape(cost.shape)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


@register_layer("crf_decoding")
def crf_decoding_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Viterbi decode; with a label input, emits per-token error indicators
    (ref: CRFDecodingLayer.cpp)."""
    from paddle_tpu.ops.crf import crf_decode
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    path = crf_decode(x.value, x.lengths, w)
    if len(cfg.inputs) > 1:
        lbl = ctx.get_input(cfg, 1)
        err = (path != lbl.ids).astype(jnp.int32) * x.mask(jnp.int32)
        return Argument(ids=err, lengths=x.lengths)
    return Argument(ids=path, lengths=x.lengths)


@register_layer("ctc", cost=True)
def ctc_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """CTC loss (ref: CTCLayer.cpp, LinearChainCTC.cpp)."""
    from paddle_tpu.ops.ctc import ctc_loss
    x = ctx.get_input(cfg, 0)
    lbl = ctx.get_input(cfg, 1)
    cost = ctc_loss(x.value, x.lengths, lbl.ids, lbl.lengths,
                    blank=cfg.blank, norm_by_times=cfg.norm_by_times)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


# ---------------------------------------------------------------------------
# sampled-softmax family
# ---------------------------------------------------------------------------

@register_layer("nce", cost=True)
def nce_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Noise-contrastive estimation cost (ref: NCELayer.cpp,
    MultinomialSampler.cpp).  Samples num_neg_samples negatives per example
    from neg_sampling_dist (uniform when unset)."""
    from paddle_tpu.ops.sampling import nce_cost
    inputs = ctx.get_inputs(cfg)
    # inputs: feature inputs (with params), then label, then optional weight
    n_feat = sum(1 for li in cfg.inputs if li.input_parameter_name)
    feats = [inputs[i].value for i in range(n_feat)]
    lbl = inputs[n_feat]
    ws = [ctx.param_of(cfg, i) for i in range(n_feat)]
    b = ctx.bias_of(cfg)
    dist = None
    if cfg.neg_sampling_dist:
        dist = jnp.asarray(cfg.neg_sampling_dist, jnp.float32)
    cost = nce_cost(ctx.next_rng(), feats, lbl.ids, ws, b,
                    num_classes=cfg.num_classes,
                    num_neg=cfg.num_neg_samples, dist=dist)
    if len(inputs) > n_feat + 1:
        cost = cost * inputs[n_feat + 1].data.reshape(cost.shape)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


@register_layer("hsigmoid", cost=True)
def hsigmoid_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Hierarchical sigmoid cost over a complete binary tree
    (ref: HierarchicalSigmoidLayer.cpp, math/MatrixBitCode.cpp)."""
    from paddle_tpu.ops.sampling import hsigmoid_cost
    inputs = ctx.get_inputs(cfg)
    lbl = inputs[-1]
    feats = inputs[:-1]
    ws = [ctx.param_of(cfg, i) for i in range(len(feats))]
    b = ctx.bias_of(cfg)
    cost = hsigmoid_cost([f.value for f in feats], lbl.ids, ws, b, cfg.num_classes)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])
