"""Shared helpers for layer implementations."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.ops.activations import activation
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


def apply_dropout(ctx: ForwardContext, cfg: LayerConfig, x: Array) -> Array:
    """Classic (non-inverted) dropout, matching the reference: multiply by a
    Bernoulli mask at train time, by (1 - drop_rate) at test time
    (ref: paddle/gserver/layers/Layer.cpp forwardDropOut)."""
    p = cfg.drop_rate
    if p <= 0.0:
        return x
    if ctx.is_training:
        keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - p, x.shape)
        return x * keep.astype(x.dtype)
    return x * (1.0 - p)


def finish_layer(
    ctx: ForwardContext,
    cfg: LayerConfig,
    value: Array,
    like: Optional[Argument] = None,
    lengths: Optional[Array] = None,
    nhwc: bool = False,
) -> Argument:
    """Apply activation + dropout and package the output Argument, inheriting
    sequence structure from `like` (ref: Layer::forwardActivation +
    Argument::resizeAndCopyFrom sequence info propagation).  `nhwc` marks a
    [B, H, W, C] image output (stays channels-last for the next image layer;
    flattened lazily at the flat-row boundary)."""
    if nhwc and cfg.active_type in ("softmax", "sequence_softmax"):
        # whole-row activations are defined on the flat layout
        B, H, W, C = value.shape
        value = value.transpose(0, 3, 1, 2).reshape(B, C * H * W)
        nhwc = False
    if lengths is None and like is not None and not nhwc and value.ndim >= 3:
        lengths = like.lengths
    mask = None
    if cfg.active_type == "sequence_softmax" and lengths is not None:
        mask = (jnp.arange(value.shape[1])[None, :] < lengths[:, None])
    out = activation(cfg.active_type, value, mask=mask)
    out = apply_dropout(ctx, cfg, out)
    out = tp_constrain(ctx, cfg, out)
    sub_lengths = like.sub_lengths if like is not None else None
    return Argument(value=out, lengths=lengths, sub_lengths=sub_lengths, nhwc=nhwc)


def tp_constrain(ctx: ForwardContext, cfg: LayerConfig, x: Array) -> Array:
    """Pin a layer output's tensor-parallel layout when the serving
    engine stamped `tp_out` on it (ServingEngine._tp_param_shardings —
    the Megatron split): 'model' keeps the FFN up-projection's wide
    hidden activation COLUMN-SHARDED on its last axis (it must never
    materialize whole on a device), 'replicated' forces row-sharded
    partial sums (FFN down-projection, LM head) to meet in ONE
    all-reduce right here and keeps the residual stream / layer norms
    replicated.  Without the pins GSPMD propagation is free to shard
    the residual instead — same bytes, but it strews activation
    all-gathers and partial layer-norm reductions through a
    latency-bound decode step (observed on the 2-shard host mesh;
    tools/hlo_shard_check.py counts exactly the pinned collectives).
    No-op off-mesh, when the mesh has no model axis, or when the engine
    never stamped the layer."""
    tp = cfg.attrs.get("tp_out")
    mesh = ctx.mesh
    if not tp or mesh is None:
        return x
    from paddle_tpu.parallel.mesh import MODEL_AXIS

    if tp not in ("replicated", MODEL_AXIS):
        # an unknown stamp silently falling through to some default is
        # exactly the layout drift the pin exists to prevent
        raise ValueError(
            f"layer {cfg.name!r}: unknown tp_out {tp!r} (expected "
            f"'replicated' or {MODEL_AXIS!r})")
    if int(dict(zip(mesh.axis_names, mesh.devices.shape))
           .get(MODEL_AXIS, 1)) < 2:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P() if tp == "replicated" else \
        P(*([None] * (x.ndim - 1) + [MODEL_AXIS]))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
