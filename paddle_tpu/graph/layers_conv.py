"""Convolution / pooling / normalization layers.

Covers the reference's image stack (ref: paddle/gserver/layers/
{ExpandConvLayer,CudnnConvLayer,ConvProjection,ExpandConvTransLayer,PoolLayer,
CudnnPoolLayer,SpatialPyramidPoolLayer,MaxOutLayer,NormProjectionLayer,
BatchNormalizationLayer,CudnnBatchNormLayer,BilinearInterpLayer,
BlockExpandLayer}.cpp and paddle/cuda/src/hl_cuda_cnn.cu).

Re-design: images flow between image layers as channels-last [B, H, W, C]
tensors — the TPU-native conv layout — and are converted to/from the
reference's flat C-major [B, C*H*W] rows only at the image-pipeline boundary
(ForwardContext.get_input flattens lazily; get_image_input unpacks once on
entry).  Layer `size` semantics and the DSL's size inference carry over
unchanged because every flat view is C-major.  All convs lower to
`lax.conv_general_dilated` with NHWC/HWIO dimension numbers, which XLA maps
onto the MXU without per-layer transposes — the im2col/cuDNN split of the
reference collapses into one compiler path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from typing import Optional

from paddle_tpu.config.schema import ConvConfig, LayerConfig, OperatorConfig, PoolConfig, ProjectionConfig
from paddle_tpu.graph.common import finish_layer
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


def _geom(c: ConvConfig):
    fy = c.filter_size_y or c.filter_size
    sy = c.stride_y or c.stride
    py = c.padding_y if c.padding_y else c.padding
    iy = c.img_size_y or c.img_size
    return c.filter_size, fy, c.stride, sy, c.padding, py, c.img_size, iy


def conv_output_size(img: int, filt: int, stride: int, pad: int, caffe_mode: bool = True) -> int:
    """(ref: paddle/math/MathUtils.cpp outputSize)."""
    if caffe_mode:
        return (img + 2 * pad - filt) // stride + 1
    return (img - filt + 2 * pad + stride - 1) // stride + 1


def _pad_amounts(img: int, filt: int, stride: int, pad: int, out: int) -> tuple[int, int]:
    """Explicit (lo, hi) padding that reproduces the configured output size:
    left padding is exactly `pad` (so windows align with the reference's),
    right padding absorbs the remainder (may be negative = crop)."""
    total = (out - 1) * stride + filt - img
    return pad, total - pad


def conv2d_forward_nhwc(x: Array, w: Array, conv: ConvConfig, num_filters: int,
                        transpose: bool = False) -> Array:
    """x [B, H, W, C] -> [B, OH, OW, num_filters] (channels-last throughout).

    w layout: [num_filters, C//groups * fh * fw] matching the reference's
    parameter shape for conv layers (ref: ExpandConvLayer weights), laid out
    as HWIO for the XLA conv (same kernel tensor, TPU-preferred spec).
    """
    fx, fy, sx, sy, px, py, ix, iy = _geom(conv)
    C = conv.channels
    g = conv.groups
    w4 = w.reshape(num_filters, C // g, fy, fx).transpose(2, 3, 1, 0)

    if not transpose:
        oy = conv.output_y or conv_output_size(iy, fy, sy, py, conv.caffe_mode)
        ox = conv.output_x or conv_output_size(ix, fx, sx, px, conv.caffe_mode)
        pad_y = _pad_amounts(iy, fy, sy, py, oy)
        pad_x = _pad_amounts(ix, fx, sx, px, ox)
        return lax.conv_general_dilated(
            x, w4, window_strides=(sy, sx), padding=(pad_y, pad_x),
            dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=g)
    else:
        # transposed conv (ref: ExpandConvTransLayer): output spatial size is
        # the conv-input size that would have produced this input
        y = lax.conv_transpose(
            x, w4, strides=(sy, sx), padding=((py, py), (px, px)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"), transpose_kernel=True)
        # crop/pad to the configured output size
        return y[:, :conv.output_y, :conv.output_x, :]


def conv2d_forward(x_flat: Array, w: Array, conv: ConvConfig, num_filters: int,
                   transpose: bool = False) -> Array:
    """Flat-row wrapper: [B, C*H*W] -> [B, num_filters*OH*OW] (used by conv
    projections/operators inside mixed layers, which live in row space)."""
    _, fy, _, sy, _, py, ix, iy = _geom(conv)
    B = x_flat.shape[0]
    C = conv.channels
    x = x_flat.reshape(B, C, iy, ix).transpose(0, 2, 3, 1)
    y = conv2d_forward_nhwc(x, w, conv, num_filters, transpose=transpose)
    oy, ox = y.shape[1], y.shape[2]
    return y.transpose(0, 3, 1, 2).reshape(B, num_filters * oy * ox)


def _add_conv_bias_nhwc(acc: Array, b: Optional[Array], cfg: LayerConfig) -> Array:
    """Per-channel (shared) or per-position bias on a [B, OH, OW, F] tensor
    (ref: ConvBaseLayer addBias); DSL biases come as [1, k] rows — flatten
    before broadcasting.  Per-position biases are stored flat C-major."""
    if b is None:
        return acc
    b = b.reshape(-1)
    if cfg.shared_biases:
        return acc + b          # [F] broadcasts over the channels-last axis
    _, oy, ox, F = acc.shape
    return acc + b.reshape(F, oy, ox).transpose(1, 2, 0)


def _conv_like_layer(ctx: ForwardContext, cfg: LayerConfig, transpose: bool) -> Argument:
    acc = None
    for i, inp in enumerate(cfg.inputs):
        conv = inp.proj.conv if (inp.proj and inp.proj.conv) else cfg.conv
        iy = conv.img_size_y or conv.img_size
        arg = ctx.get_image_input(cfg, i, conv.channels, iy, conv.img_size)
        w = ctx.param_of(cfg, i)
        y = conv2d_forward_nhwc(arg.value, w, conv, cfg.num_filters,
                                transpose=transpose)
        acc = y if acc is None else acc + y
    acc = _add_conv_bias_nhwc(acc, ctx.bias_of(cfg), cfg)
    return finish_layer(ctx, cfg, acc, nhwc=True)


@register_layer("exconv", "cudnn_conv")
def conv_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Convolution layer; multiple inputs sum their conv outputs
    (ref: ExpandConvLayer.cpp / CudnnConvLayer.cpp)."""
    return _conv_like_layer(ctx, cfg, transpose=False)


@register_layer("exconvt")
def conv_trans_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Transposed convolution (ref: ExpandConvTransLayer.cpp)."""
    return _conv_like_layer(ctx, cfg, transpose=True)


def conv_projection_forward(proj: ProjectionConfig, arg: Argument, w: Array) -> Array:
    """Conv as a projection inside mixed (ref: ConvProjection.cpp)."""
    return conv2d_forward(arg.value, w, proj.conv, proj.num_filters)


def conv_operator_forward(op: OperatorConfig, img: Argument, filt: Argument) -> Array:
    """Conv with the *filter supplied by a layer output* — each sample has its
    own filter (ref: ConvOperator.cpp, used by attention-style models)."""
    conv = op.conv
    fx, fy, sx, sy, px, py, ix, iy = _geom(conv)
    B = img.value.shape[0]
    C = conv.channels
    x = img.value.reshape(B, C, iy, ix)
    w = filt.value.reshape(B, op.num_filters, C, fy, fx)
    oy = conv.output_y or conv_output_size(iy, fy, sy, py, conv.caffe_mode)
    ox = conv.output_x or conv_output_size(ix, fx, sx, px, conv.caffe_mode)
    pad_y = _pad_amounts(iy, fy, sy, py, oy)
    pad_x = _pad_amounts(ix, fx, sx, px, ox)

    def one(xi, wi):
        return lax.conv_general_dilated(
            xi[None], wi, (sy, sx), (pad_y, pad_x),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))[0]

    y = jax.vmap(one)(x, w)
    return y.reshape(B, op.num_filters * oy * ox)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _pool_geom(p: PoolConfig):
    ky = p.size_y or p.size_x
    sy = p.stride_y or p.stride
    py = p.padding_y if p.padding_y else p.padding
    iy = p.img_size_y or p.img_size
    return p.size_x, ky, p.stride, sy, p.padding, py, p.img_size, iy


def pool2d_reduce_window(x: Array, pool: PoolConfig) -> Array:
    """Generic [B, H, W, C] pooling via `lax.reduce_window` — the reference
    semantics all fast paths must match (and the oracle the fast-path test
    compares against)."""
    kx, ky, sx, sy, px, py, ix, iy = _pool_geom(pool)
    oy = pool.output_y or conv_output_size(iy, ky, sy, py, caffe_mode=False)
    ox = pool.output_x or conv_output_size(ix, kx, sx, px, caffe_mode=False)
    pad_y = _pad_amounts(iy, ky, sy, py, oy)
    pad_x = _pad_amounts(ix, kx, sx, px, ox)
    dims = (1, ky, kx, 1)
    strides = (1, sy, sx, 1)
    padding = ((0, 0), pad_y, pad_x, (0, 0))
    if pool.pool_type.startswith("max"):
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
    # average excluding padding (ref: hl_avgpool_forward divides by the
    # clipped window size)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    ones = jnp.ones((1, iy, ix, 1), x.dtype)
    cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
    return s / jnp.maximum(cnt, 1.0)


def pool2d_forward_nhwc(x: Array, pool: PoolConfig) -> Array:
    """[B, H, W, C] -> [B, OH, OW, C] max/avg pooling."""
    kx, ky, sx, sy, px, py, ix, iy = _pool_geom(pool)
    oy = pool.output_y or conv_output_size(iy, ky, sy, py, caffe_mode=False)
    ox = pool.output_x or conv_output_size(ix, kx, sx, px, caffe_mode=False)
    pad_y = _pad_amounts(iy, ky, sy, py, oy)
    pad_x = _pad_amounts(ix, kx, sx, px, ox)
    # Non-overlapping windows that tile the image exactly (the VGG 2x2/s2
    # case) pool via reshape+reduce: the gradient is then an elementwise
    # mask/broadcast fusion instead of TPU's slow select-and-scatter
    # (max-pool backward was ~9% of the VGG train step).  A window whose
    # max is a ReLU zero ties across the window, but the split cotangent
    # dies in ReLU's backward mask anyway, so grads match reduce_window.
    tiles = (sy == ky and sx == kx and pad_y == (0, 0) and pad_x == (0, 0)
             and oy * ky == iy and ox * kx == ix)
    if tiles:
        B, _, _, C = x.shape
        r = x.reshape(B, oy, ky, ox, kx, C)
        if pool.pool_type.startswith("max"):
            return r.max(axis=(2, 4))
        return r.mean(axis=(2, 4))
    if oy == 1 and ox == 1 and ky >= iy and kx >= ix and py == 0 and px == 0:
        # window covers the whole image: global pooling (the avg divisor is
        # the clipped window = the image, matching hl_avgpool_forward)
        if pool.pool_type.startswith("max"):
            return jnp.max(x, axis=(1, 2), keepdims=True)
        return jnp.mean(x, axis=(1, 2), keepdims=True)
    return pool2d_reduce_window(x, pool)


def pool2d_forward(x_flat: Array, pool: PoolConfig) -> Array:
    """Flat-row wrapper: [B, C*H*W] -> [B, C*OH*OW] (pool projections)."""
    _, _, _, _, _, _, ix, iy = _pool_geom(pool)
    B = x_flat.shape[0]
    C = pool.channels
    x = x_flat.reshape(B, C, iy, ix).transpose(0, 2, 3, 1)
    y = pool2d_forward_nhwc(x, pool)
    return y.transpose(0, 3, 1, 2).reshape(B, -1)


@register_layer("pool", "cudnn_pool")
def pool_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """(ref: PoolLayer.cpp / CudnnPoolLayer.cpp)."""
    p = cfg.pool
    x = ctx.get_image_input(cfg, 0, p.channels,
                            p.img_size_y or p.img_size, p.img_size)
    out = pool2d_forward_nhwc(x.value, p)
    return finish_layer(ctx, cfg, out, nhwc=True)


@register_layer("spp")
def spp_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Spatial pyramid pooling: pool at pyramid levels 0..L-1 and concat
    (ref: SpatialPyramidPoolLayer.cpp)."""
    import dataclasses
    p = cfg.pool
    ix, iy = p.img_size, (p.img_size_y or p.img_size)
    x = ctx.get_image_input(cfg, 0, p.channels, iy, ix)
    levels = cfg.attrs.get("pyramid_height", 1)
    B = x.value.shape[0]
    parts = []
    for lvl in range(levels):
        n = 2 ** lvl
        kx, ky = -(-ix // n), -(-iy // n)
        sub = dataclasses.replace(
            p, size_x=kx, size_y=ky, stride=kx, stride_y=ky, padding=0, padding_y=0,
            output_x=n, output_y=n)
        pooled = pool2d_forward_nhwc(x.value, sub)          # [B, n, n, C]
        parts.append(pooled.transpose(0, 3, 1, 2).reshape(B, -1))
    out = jnp.concatenate(parts, axis=-1)
    return finish_layer(ctx, cfg, out)


@register_layer("maxout")
def maxout_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Max over groups of consecutive channels (ref: MaxOutLayer.cpp,
    hl_maxout_forward: out channel o = max over in channels o*g..o*g+g-1)."""
    x = ctx.get_raw_input(cfg, 0)
    groups = cfg.attrs["groups"]
    C = cfg.conv.channels if cfg.conv else cfg.attrs["channels"]
    if x.nhwc:
        B, H, W, _ = x.value.shape
        out = jnp.max(x.value.reshape(B, H, W, C // groups, groups), axis=-1)
        return finish_layer(ctx, cfg, out, nhwc=True)
    B, D = x.value.shape
    hw = D // C
    out = jnp.max(x.value.reshape(B, C // groups, groups, hw), axis=2)
    return finish_layer(ctx, cfg, out.reshape(B, -1), like=x)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register_layer("norm")
def norm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Cross-channel local response normalization (cmrnorm)
    (ref: NormProjectionLayer.cpp, hl_CMRNorm_forward):
    y = x * (1 + scale * sum_{window} x^2)^(-pow)."""
    n = cfg.norm
    C, H, W = n.channels, (n.img_size_y or n.img_size), n.img_size
    x = ctx.get_image_input(cfg, 0, C, H, W)
    v = x.value                                             # [B, H, W, C]
    sq = jnp.square(v)
    half = n.size // 2
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, n.size - 1 - half)))
    wsum = sum(padded[..., i:i + C] for i in range(n.size))
    y = v * jnp.power(1.0 + n.scale * wsum, -n.pow)
    return finish_layer(ctx, cfg, y, nhwc=True)


@register_layer("batch_norm", "cudnn_batch_norm")
def batch_norm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Batch normalization with moving-average inference stats
    (ref: BatchNormalizationLayer.cpp; moving stats are state, not params).

    Image inputs ([B, C*H*W] with conv geometry) normalize per channel;
    plain inputs per feature.
    """
    img = cfg.conv is not None and cfg.conv.img_size > 0
    if img:
        C = cfg.conv.channels
        x = ctx.get_image_input(cfg, 0, C,
                                cfg.conv.img_size_y or cfg.conv.img_size,
                                cfg.conv.img_size)
        v = x.value                      # [B, H, W, C]
        v4 = v
        axes = (0, 1, 2)
        stat_shape = (1, 1, 1, C)
    else:
        x = ctx.get_input(cfg, 0)
        v = x.value
        v4 = v
        axes = (0,)
        stat_shape = (1, v.shape[-1])
    scale = ctx.param_of(cfg, 0)
    bias = ctx.bias_of(cfg)
    eps = 1e-5

    state = ctx.state_in.get(cfg.name)
    if state is None:
        state = {"mean": jnp.zeros(stat_shape[1] if not img else C),
                 "var": jnp.ones(stat_shape[1] if not img else C),
                 "count": jnp.zeros(())}

    use_global = cfg.use_global_stats
    if use_global is None:
        use_global = not ctx.is_training

    if use_global:
        mean = state["mean"].reshape(stat_shape)
        var = state["var"].reshape(stat_shape)
        new_state = state
        if cfg.use_global_stats is True and ctx.state_in.get(cfg.name) is None:
            # explicitly-frozen BN with no stats to carry is a PURE function
            # (fixed mean-0/var-1 affine): registering no state keeps it
            # usable under config-driven pipeline parallelism, whose stage
            # ring has no mutable-state channel (parallel/pipeline_config).
            # Loaded/carried stats (fine-tune-frozen BN) still round-trip
            # through state_out below.
            return finish_layer(
                ctx, cfg, _bn_normalize(v4, mean, var, scale, bias,
                                        stat_shape, eps).reshape(v.shape)
                .astype(v.dtype), like=x, nhwc=img)
    else:
        # statistics in >= float32 (promote bf16/f16 under mixed precision;
        # keep f64 in f64 for the grad-check tests)
        from paddle_tpu.utils.dtypes import promote_compute
        v32 = promote_compute(v4)
        mean = jnp.mean(v32, axis=axes).reshape(stat_shape)
        var = jnp.var(v32, axis=axes).reshape(stat_shape)
        f = cfg.moving_average_fraction
        new_state = {
            "mean": f * state["mean"] + (1 - f) * mean.reshape(-1),
            "var": f * state["var"] + (1 - f) * var.reshape(-1),
            "count": state["count"] + 1,
        }
    ctx.state_out[cfg.name] = new_state
    return finish_layer(
        ctx, cfg, _bn_normalize(v4, mean, var, scale, bias, stat_shape,
                                eps).reshape(v.shape).astype(v.dtype),
        like=x, nhwc=img)


def _bn_normalize(v4, mean, var, scale, bias, stat_shape, eps):
    stat_dt = mean.dtype
    normed = (v4.astype(stat_dt) - mean) / jnp.sqrt(var + eps)
    normed = normed * scale.reshape(stat_shape).astype(stat_dt)
    if bias is not None:
        normed = normed + bias.reshape(stat_shape).astype(stat_dt)
    return normed


@register_layer("data_norm")
def data_norm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Input feature normalization from precomputed stats
    (ref: DataNormLayer.cpp; strategy z-score/min-max/decimal-scaling)."""
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)  # [5, D]: min, max, sum, sum^2, count rows
    strategy = cfg.attrs.get("data_norm_strategy", "z-score")
    dmin, dmax, dsum, dsq, dcnt = (w[i] for i in range(5))
    cnt = jnp.maximum(dcnt, 1.0)
    mean = dsum / cnt
    std = jnp.sqrt(jnp.maximum(dsq / cnt - jnp.square(mean), 1e-8))
    if strategy == "min-max":
        out = (x.value - dmin) / jnp.maximum(dmax - dmin, 1e-8)
    elif strategy == "decimal-scaling":
        scale = jnp.power(10.0, jnp.ceil(jnp.log10(jnp.maximum(
            jnp.maximum(jnp.abs(dmax), jnp.abs(dmin)), 1e-8))))
        out = x.value / scale
    else:
        out = (x.value - mean) / std
    return finish_layer(ctx, cfg, out, like=x)


@register_layer("sum_to_one_norm")
def sum_to_one_norm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Row-normalize to sum 1 (ref: SumToOneNormLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    s = jnp.sum(x.value, axis=-1, keepdims=True)
    return finish_layer(ctx, cfg, x.value / jnp.where(jnp.abs(s) > 1e-12, s, 1.0), like=x)


# ---------------------------------------------------------------------------
# resize-ish layers
# ---------------------------------------------------------------------------

@register_layer("bilinear_interp")
def bilinear_interp_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Bilinear upsample (ref: BilinearInterpLayer.cpp, hl_bilinear_forward)."""
    a = cfg.attrs
    C, ih, iw = a["channels"], a["img_size_y"], a["img_size_x"]
    oh, ow = a["out_size_y"], a["out_size_x"]
    x = ctx.get_image_input(cfg, 0, C, ih, iw)
    B = x.value.shape[0]
    out = jax.image.resize(x.value, (B, oh, ow, C), method="bilinear")
    return finish_layer(ctx, cfg, out, nhwc=True)


@register_layer("blockexpand")
def block_expand_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """im2col into a sequence of patch vectors (ref: BlockExpandLayer.cpp):
    output is a sequence with one timestep per block position."""
    a = cfg.attrs
    x = ctx.get_input(cfg, 0)
    C, ih, iw = a["channels"], a["img_size_y"], a["img_size_x"]
    bx, by = a["block_x"], a["block_y"]
    sx, sy = a.get("stride_x", 1), a.get("stride_y", 1)
    px, py = a.get("padding_x", 0), a.get("padding_y", 0)
    B = x.value.shape[0]
    v = x.value.reshape(B, C, ih, iw)
    patches = lax.conv_general_dilated_patches(
        v, (by, bx), (sy, sx), ((py, py), (px, px)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))   # [B, C*by*bx, oy, ox]
    D = C * by * bx
    oy, ox = patches.shape[2], patches.shape[3]
    seq = jnp.moveaxis(patches.reshape(B, D, oy * ox), 1, 2)   # [B, T, D]
    lengths = jnp.full((B,), oy * ox, jnp.int32)
    return finish_layer(ctx, cfg, seq, lengths=lengths)
