"""Mixture-of-experts layer — expert-parallel FFN block.

NEW capability beyond the reference (see parallel/moe.py).  The layer's
5 parameters ride the standard input-parameter mechanism: five LayerInputs
all referencing the single data input carry router/w1/b1/w2/b2.  The aux
load-balancing loss registers into ctx.costs like a cost layer, scaled by
attrs['aux_weight'].
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.common import finish_layer
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.parallel.moe import moe_ffn
from paddle_tpu.parameter.argument import Argument


@register_layer("moe")
def moe_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    x = ctx.get_input(cfg, 0)
    w_router, w1, b1, w2, b2 = (ctx.param_of(cfg, i) for i in range(5))
    top_k = int(cfg.attrs.get("top_k", 2))
    cap = float(cfg.attrs.get("capacity_factor", 1.25))
    aux_w = float(cfg.attrs.get("aux_weight", 0.01))

    v = x.value
    seq_shape = None
    valid = None
    if v.ndim == 3:                      # [B, T, D] -> route per token
        seq_shape = v.shape[:2]
        v = v.reshape(-1, v.shape[-1])
        mask = x.mask()                  # padding never routed (cf. attention)
        if mask is not None:
            valid = mask.reshape(-1)
    y, aux = moe_ffn(v, w_router, w1, b1, w2, b2, top_k=top_k,
                     capacity_factor=cap, valid=valid)
    if seq_shape is not None:
        y = y.reshape(seq_shape + (y.shape[-1],))
    if aux_w > 0 and ctx.is_training:
        # per-sample broadcast so the executor's mean() leaves aux_w * aux
        ctx.costs[f"{cfg.name}.aux"] = jnp.broadcast_to(
            aux_w * aux, (x.batch_size,))
    return finish_layer(ctx, cfg, y, like=x)
