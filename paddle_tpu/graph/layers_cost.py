"""Cost layers.

The reference's cost zoo (ref: paddle/gserver/layers/CostLayer.cpp: multi-class
cross-entropy, self-normalized CE, soft binary CE, sum-of-squares, rank cost,
lambda rank, huber two-class, multi-binary-label CE) as per-sample cost
functions.  Each registers its [B] cost vector into ctx.costs; the executor
sums coeff-weighted costs into the scalar loss that jax.grad differentiates
(ref: Argument::sumCosts + hand-written backwardImp per cost — all replaced by
autodiff).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.parameter.argument import Argument

Array = jax.Array
_EPS = 1e-10


def _record(ctx: ForwardContext, cfg: LayerConfig, cost: Array) -> Argument:
    """Register per-sample cost; optional weight input is the 3rd input
    (ref: CostLayer weights handling in forward)."""
    if len(cfg.inputs) > 2:
        w = ctx.get_input(cfg, 2)
        cost = cost * (w.value.reshape(cost.shape) if w.value is not None else w.ids)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


def _flatten_seq(out: Argument, lbl: Argument):
    """Sequence-shaped costs reduce over valid timesteps — the reference's flat
    token matrix sums per-token costs; on padded tensors we mask."""
    if out.is_sequence:
        mask = out.mask(jnp.float32)
        return out.value, lbl, mask
    return out.value, lbl, None


@register_layer("multi-class-cross-entropy", cost=True)
def multi_class_cross_entropy(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """-log p[label]; input is a probability distribution (softmax already
    applied as the previous layer's activation, matching the reference's
    classification_cost composition) (ref: MultiClassCrossEntropy::forwardImp)."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    probs = out.value
    labels = lbl.ids
    # gather THEN log: log∘gather == gather∘log elementwise, but this keeps
    # the work (and the materialized fp32 array) at O(B*T) instead of
    # O(B*T*vocab) — at vocab 30k the full-array log was 7% of the whole
    # seq2seq train step
    picked_p = jnp.take_along_axis(probs, labels[..., None], axis=-1)[..., 0]
    picked = jnp.log(jnp.maximum(picked_p, _EPS))
    if out.is_sequence:
        cost = -jnp.sum(picked * out.mask(probs.dtype), axis=-1)
    else:
        cost = -picked
    return _record(ctx, cfg, cost)


@register_layer("multi_class_cross_entropy_with_selfnorm", cost=True)
def selfnorm_cross_entropy(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """CE + alpha * log(Z)^2 self-normalization penalty
    (ref: MultiClassCrossEntropyWithSelfNorm::forwardImp)."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    probs = out.value
    z = jnp.sum(probs, axis=-1)
    probs_n = probs / jnp.maximum(z[..., None], _EPS)
    picked = jnp.take_along_axis(
        jnp.log(jnp.maximum(probs_n, _EPS)), lbl.ids[..., None], axis=-1)[..., 0]
    cost = -picked + cfg.softmax_selfnorm_alpha * jnp.square(jnp.log(jnp.maximum(z, _EPS)))
    return _record(ctx, cfg, cost)


@register_layer("soft_binary_class_cross_entropy", cost=True)
def soft_binary_cross_entropy(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """-sum t*log(p) + (1-t)*log(1-p) with soft targets
    (ref: SoftBinaryClassCrossEntropy::forwardImp)."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    p = jnp.clip(out.value, _EPS, 1.0 - _EPS)
    t = lbl.value
    cost = -jnp.sum(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p), axis=-1)
    return _record(ctx, cfg, cost)


@register_layer("multi_binary_label_cross_entropy", cost=True)
def multi_binary_label_cross_entropy(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Binary CE against a set of positive label ids
    (ref: MultiBinaryLabelCrossEntropy::forwardImp; label is a sparse binary
    vector — here a dense 0/1 matrix [B, C])."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    p = jnp.clip(out.value, _EPS, 1.0 - _EPS)
    t = lbl.value
    cost = -jnp.sum(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p), axis=-1)
    return _record(ctx, cfg, cost)


@register_layer("square_error", cost=True)
def square_error(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """0.5 * ||out - label||^2 (ref: SumOfSquaresCostLayer::forwardImp)."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    d = out.value - lbl.value
    if out.is_sequence:
        cost = 0.5 * jnp.sum(jnp.sum(jnp.square(d), axis=-1) * out.mask(d.dtype), axis=-1)
    else:
        cost = 0.5 * jnp.sum(jnp.square(d), axis=-1)
    return _record(ctx, cfg, cost)


@register_layer("rank-cost", cost=True)
def rank_cost(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Pairwise ranking: -t*o + log(1 + exp(o)), o = s_a - s_b
    (ref: RankingCost::forwardImp)."""
    a, b, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1), ctx.get_input(cfg, 2)
    o = (a.value - b.value)[..., 0]
    t = lbl.value[..., 0] if lbl.value is not None else lbl.ids.astype(o.dtype)
    cost = -t * o + jax.nn.softplus(o)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


@register_layer("huber_classification", "huber", cost=True)
def huber_two_class(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Two-class huber cost on a scalar score with labels {0,1} -> y in {-1,1}
    (ref: HuberTwoClass::forwardImp)."""
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    score = out.value[..., 0]
    y = 2.0 * lbl.ids.astype(score.dtype) - 1.0
    a = y * score
    cost = jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))
    return _record(ctx, cfg, cost)


@register_layer("sum_cost", cost=True)
def sum_cost(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Sum input values as cost (ref: SumCostLayer)."""
    out = ctx.get_input(cfg, 0)
    if out.is_sequence:
        cost = jnp.sum(jnp.sum(out.value, axis=-1) * out.mask(out.value.dtype), axis=-1)
    else:
        cost = jnp.sum(out.value, axis=-1)
    ctx.costs[cfg.name] = cfg.coeff * cost
    return Argument(value=cost[:, None])


@register_layer("lambda_cost", cost=True)
def lambda_cost(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """LambdaRank NDCG cost over each list (sequence) (ref: LambdaCost).

    Differentiable surrogate: for each pair (i,j) in a list, logistic pairwise
    loss weighted by |ΔNDCG|.  The reference computes hand-crafted lambdas in
    backward; here the pairwise-weighted loss's autodiff gradient plays that
    role.
    """
    out, lbl = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    s = out.value[..., 0]                      # [B, T] scores
    r = lbl.value[..., 0]                      # [B, T] relevance
    mask = out.mask(s.dtype)
    pair_valid = mask[:, :, None] * mask[:, None, :]
    sdiff = s[:, :, None] - s[:, None, :]
    rdiff = r[:, :, None] - r[:, None, :]
    better = (rdiff > 0).astype(s.dtype)
    gain_w = jnp.abs(rdiff)
    pair_cost = jax.nn.softplus(-sdiff) * better * gain_w * pair_valid
    cost = jnp.sum(pair_cost, axis=(1, 2))
    return _record(ctx, cfg, cost)


# -- in-graph validation layers ---------------------------------------------

@register_layer("auc-validation", "pnpair-validation", validation=True)
def validation_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Evaluation inside the graph during training (ref:
    paddle/gserver/layers/ValidationLayer.cpp; created at Layer.cpp:116-119;
    DSL side config_parser.py:1961-1962).

    The reference's AucValidation/PnpairValidation wrap an Evaluator
    ('last-column-auc' / 'pnpair') fed every forward, with a no-op
    backward.  Here the layer itself is a stop-gradient pass-through of
    its score input; the evaluator wiring is synthesized from the layer
    config by EvaluatorSet (trainer/evaluators.py), which already owns
    the start/eval/finish accumulation protocol.
    """
    out = ctx.get_input(cfg, 0)
    return jax.tree.map(jax.lax.stop_gradient, out)
