"""Autoregressive decoding for sequence-in/logits-out models (the
transformer LM family).

The reference's generation story is beam search over recurrent groups
(RecurrentGradientMachine; graph/generator.py here).  Full-sequence
attention models have no recurrent group to unroll, so this provides the
matching TPU-native decode loop: ONE compiled `lax.scan` over a
fixed-size token buffer — each step runs the full forward on the padded
prefix (masked by the running length), reads the next-token logits at the
last valid position, and samples greedy / temperature / top-k / top-p.

Two decode modes:
  * whole-prefix re-forward (default) — each step runs the full forward on
    the padded buffer; O(T^2) total but zero layer-level support needed,
    and at short contexts it is one fused program XLA pipelines well.
  * `use_cache=True` — per-layer KV caches (init_kv_caches) ride the
    executor's state channel (the same threading as BN moving stats); each
    step runs the stack on ONE new token per row against the caches
    (ops/attention.py:cached_attention_step) — O(T) per token, the
    long-context decode path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import TEST
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


def nucleus_filter(scaled: Array, top_p: float) -> Array:
    """Top-p (nucleus) cut on [B, V] logits: keep the smallest
    probability-sorted prefix whose cumulative mass reaches top_p (the
    first token AT the threshold stays in — the standard formulation),
    -inf elsewhere.  Kept support is EXACT: indices are scattered back
    from the sorted order, so logit ties at the cutoff can never widen
    the set (same discipline as the top-k branch in lm_generate)."""
    if not 0.0 < top_p < 1.0:
        return scaled
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]            # desc
    srt = jnp.take_along_axis(scaled, order, axis=-1)
    probs = jax.nn.softmax(srt, axis=-1)
    keep = jnp.cumsum(probs, axis=-1) - probs < top_p        # n_keep >= 1
    return jnp.full_like(scaled, -jnp.inf).at[
        jnp.arange(scaled.shape[0])[:, None], order].set(
        jnp.where(keep, srt, -jnp.inf))


def pick_next(last: Array, key: Optional[Array], temperature: float = 0.0,
              top_k: int = 0, top_p: float = 0.0,
              is_probs: bool = False) -> Array:
    """One sampling decision on [B, V] next-token scores -> [B] int32.

    Module-level (not a closure inside lm_generate) so the serving
    engine's per-slot sampler (serving/sampler.py:pick_next_per_slot) can
    hold itself to EXACTLY these semantics — any drift between the two
    shows up as a token divergence in the serving parity oracle.

    `is_probs`: the logits layer emits probabilities (softmax activation)
    — sample through log; raw-activation layers sample directly."""
    last = jnp.log(jnp.maximum(last.astype(jnp.float32), 1e-30)) \
        if is_probs else last.astype(jnp.float32)
    if temperature <= 0.0:
        return jnp.argmax(last, axis=-1).astype(jnp.int32)
    scaled = last / temperature
    if top_k > 0:
        # exact k-best support via top_k (ref pattern:
        # graph/generator.py beam candidate selection): scatter the
        # k values back to -inf elsewhere so ties at the kth value
        # can never widen the candidate set
        vals, idxs = jax.lax.top_k(scaled, top_k)
        scaled = jnp.full_like(scaled, -jnp.inf).at[
            jnp.arange(scaled.shape[0])[:, None], idxs].set(vals)
    scaled = nucleus_filter(scaled, top_p)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


def _chunked_scan(step, carry, keys, chunk: int, done_of):
    """`lax.scan(step, carry, keys)` split into `chunk`-step scans with a
    HOST all-done check between chunks: a batch whose every row hit eos at
    step 5 of max_new=512 stops paying for the 507 dead steps.  Bit-exact
    with the single scan (scan composes sequentially; done rows are frozen
    by `advance`, so skipped trailing steps are no-ops on the outputs, and
    the pre-split keys mean skipped steps never consumed rng).  Compiled
    signatures stay bounded: one `chunk`-length scan program plus at most
    one remainder-length program."""
    if chunk <= 0 or chunk >= keys.shape[0]:
        carry, _ = jax.lax.scan(step, carry, keys)
        return carry
    i = 0
    while i < keys.shape[0]:
        n = min(chunk, keys.shape[0] - i)
        carry, _ = jax.lax.scan(step, carry, keys[i:i + n])
        i += n
        if i < keys.shape[0] and bool(jnp.all(done_of(carry))):
            break
    return carry


def _resolve_io_names(model, input_name, logits_name):
    """Default input = first data layer; default logits = last non-cost,
    non-validation layer (shared by lm_generate / lm_beam_generate)."""
    if input_name is None:
        input_name = model.input_layer_names[0]
    if logits_name is None:
        from paddle_tpu.graph.registry import (cost_layer_types,
                                               validation_layer_types)
        skip = cost_layer_types | validation_layer_types | {"data"}
        logits_name = [l.name for l in model.layers if l.type not in skip][-1]
    return input_name, logits_name


def _prefill(executor, params, input_name, logits_name, prompt_ids,
             prompt_lengths, total):
    """Fill fresh KV caches with one forward over the padded prompt; return
    (state, last-valid-position logits [B, V])."""
    state = init_kv_caches(executor, prompt_ids.shape[0], total)
    outputs, _, state = executor.forward(
        params, {input_name: Argument(ids=prompt_ids,
                                      lengths=prompt_lengths)},
        state, TEST, None)
    logits = outputs[logits_name].value
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1)[:, 0, :]
    return state, last


def lm_generate(
    executor: GraphExecutor,
    params: dict[str, Array],
    prompt_ids,                   # [B, P] int32 prompt tokens
    prompt_lengths=None,          # [B] valid prompt lengths (default: P)
    max_new: int = 32,
    *,
    input_name: Optional[str] = None,
    logits_name: Optional[str] = None,
    temperature: float = 0.0,     # 0 = greedy
    top_k: int = 0,               # 0 = full distribution
    top_p: float = 0.0,           # 0 = no nucleus cut; else keep the
                                  # smallest prefix with cum. prob >= top_p
    eos_id: int = -1,             # -1 = never stop early
    rng: Optional[Array] = None,
    use_cache: bool = False,      # O(T) per-token decode via KV caches
    early_exit_chunk: int = 0,    # >0: decode in chunked scans with a host
                                  # all-done check between chunks (eos
                                  # batches stop paying for dead steps)
):
    """Returns (tokens [B, P+max_new], lengths [B]) — the prompt plus up to
    max_new sampled tokens per row (rows stop growing at eos_id).

    The model is any config whose `input_name` data layer takes an id
    sequence and whose `logits_name` layer emits [B, T, vocab]
    (next-token distribution at each position) — the transformer LM
    shape.  Defaults: the first id-sequence input layer and the last
    non-cost layer.
    """
    model = executor.model
    input_name, logits_name = _resolve_io_names(model, input_name,
                                                logits_name)

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, P = prompt_ids.shape
    total = P + max_new
    if prompt_lengths is None:
        prompt_lengths = jnp.full((B,), P, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    buf0 = jnp.zeros((B, total), jnp.int32).at[:, :P].set(prompt_ids)

    # only reject knob values that would actually change sampling (the
    # same effective ranges the sampler uses: top_p in (0,1), top_k > 0)
    if temperature <= 0.0 and (top_k > 0 or 0.0 < top_p < 1.0):
        raise ValueError(
            f"top_k={top_k}/top_p={top_p} need temperature > 0 — "
            f"temperature=0 means greedy argmax, which would silently "
            f"ignore them")

    import functools
    sample = functools.partial(
        pick_next, temperature=temperature, top_k=top_k, top_p=top_p,
        is_probs=_is_probs(model, logits_name))

    def advance(buf, lengths, done, nxt):
        # frozen rows keep their buffer and length
        write_pos = jnp.clip(lengths, 0, total - 1)
        new_buf = buf.at[jnp.arange(B), write_pos].set(
            jnp.where(done, buf[jnp.arange(B), write_pos], nxt))
        new_len = jnp.where(done, lengths, jnp.minimum(lengths + 1, total))
        new_done = jnp.logical_or(done, jnp.logical_or(
            nxt == eos_id, new_len >= total))
        return new_buf, new_len, new_done

    if max_new == 0:
        return buf0, prompt_lengths
    keys = jax.random.split(rng, max_new)

    # compile observability: the batch decode compiles per
    # (B, P, max_new, path, knob) tuple — the first call with a new tuple
    # records a compile event on the `compile` lane (obs/compile_watch.py),
    # so a caller churning shapes shows up as a recompile storm instead of
    # a silent slowdown.  id(executor) scopes the key per model instance.
    from paddle_tpu.obs.compile_watch import get_compile_watch
    _cw = get_compile_watch().watch(
        "lm_decode.generate",
        (id(executor), B, P, int(max_new), bool(use_cache),
         int(early_exit_chunk), float(temperature), int(top_k),
         float(top_p), int(eos_id)))

    if use_cache:
        # O(total) per token: prefill the per-layer KV caches on the padded
        # prompt once, then each step runs the stack on ONE new token per
        # row, threading the caches through the executor's state channel
        with _cw:
            state, last = _prefill(executor, params, input_name,
                                   logits_name, prompt_ids, prompt_lengths,
                                   total)
            nxt = sample(last, keys[0])
            buf, lengths, done = advance(buf0, prompt_lengths,
                                         jnp.zeros((B,), bool), nxt)

            def step_cached(carry, key):
                buf, lengths, done, state = carry
                tok = buf[jnp.arange(B),
                          jnp.clip(lengths - 1, 0, total - 1)]
                feed = {input_name: Argument(ids=tok[:, None],
                                             lengths=jnp.ones((B,),
                                                              jnp.int32))}
                outputs, _, state = executor.forward(params, feed, state,
                                                     TEST, None)
                nxt = sample(outputs[logits_name].value[:, 0, :], key)
                buf, lengths, done = advance(buf, lengths, done, nxt)
                return (buf, lengths, done, state), None

            buf, lengths, _, _ = _chunked_scan(
                step_cached, (buf, lengths, done, state), keys[1:],
                early_exit_chunk, done_of=lambda c: c[2])
        return buf, lengths

    def step(carry, key):
        buf, lengths, done = carry
        feed = {input_name: Argument(ids=buf, lengths=lengths)}
        outputs, _, _ = executor.forward(params, feed, None, TEST, None)
        logits = outputs[logits_name].value          # [B, total, V]
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
        nxt = sample(last, key)
        return advance(buf, lengths, done, nxt), None

    with _cw:
        buf, lengths, _ = _chunked_scan(
            step, (buf0, prompt_lengths, jnp.zeros((B,), bool)), keys,
            early_exit_chunk, done_of=lambda c: c[2])
    return buf, lengths


def init_kv_caches(executor: GraphExecutor, batch: int, total: int) -> dict:
    """Zeroed per-attention-layer KV caches sized for `total` positions.
    Passing this dict as `state` to executor.forward flips every causal
    multi_head_attention layer into its incremental cached path
    (graph/layers_attn.py:_cached_step)."""
    dtype = jnp.dtype(executor.compute_dtype) if executor.compute_dtype \
        else jnp.float32
    state: dict = {}
    for l in executor.model.layers:
        if l.type != "multi_head_attention":
            continue
        heads = int(l.attrs["num_heads"])
        h_kv = int(l.attrs.get("num_kv_heads", 0) or heads)
        dh = int(l.size) // heads
        state[l.name] = {
            "k": jnp.zeros((batch, total, h_kv, dh), dtype),
            "v": jnp.zeros((batch, total, h_kv, dh), dtype),
            "pos": jnp.zeros((batch,), jnp.int32),
        }
    assert state, "model has no multi_head_attention layers to cache"
    return state


def _is_probs(model, logits_name: str) -> bool:
    """Whether the logits layer emits probabilities (softmax activation) —
    sampled through log; raw-activation layers sample directly."""
    for l in model.layers:
        if l.name == logits_name:
            return l.active_type in ("softmax", "sequence_softmax")
    return False


def build_draft_roll(executor: GraphExecutor, *,
                     input_name: Optional[str] = None,
                     logits_name: Optional[str] = None):
    """Build the GREEDY k-chain rollout a batched serving drafter jits:
    `roll(params, buf, lens, k) -> [B, k]` proposals, where `buf` is a
    [B, W + k] windowed-context buffer (each row's last valid token at
    `lens[b] - 1`, k columns of slack on the right) and k is STATIC.

    One `lax.scan` of k whole-window forwards: each body re-forwards the
    padded buffer (the zero-support decode mode of `lm_generate` — no KV
    cache to thread, so the rollout stays a pure params/ids -> tokens
    function the serving engine can jit under ONE signature per (B, k)),
    reads the last valid position's logits, takes the shared greedy pick
    (serving/sampler.py:greedy_next — the drafter/sampler tie contract),
    and appends.  Causal masking makes the right-side slack inert, so
    garbage past `lens` can never leak into a proposal.

    Cost: k forwards over W + k positions of whatever model `executor`
    holds — a tiny draft transformer, or the TARGET itself over a
    truncated window (self-speculation; the window cap is what makes it
    cheaper than real decode at long contexts).  Proposals are guesses
    by construction: the verify step re-scores every chain exactly, so
    nothing here can change an emitted token."""
    model = executor.model
    input_name, logits_name = _resolve_io_names(model, input_name,
                                                logits_name)
    from paddle_tpu.serving.sampler import greedy_next

    def roll(params, buf, lens, k: int):
        B, W = buf.shape

        def body(carry, _):
            buf, lens = carry
            feed = {input_name: Argument(ids=buf, lengths=lens)}
            outputs, _, _ = executor.forward(params, feed, None, TEST,
                                             None)
            logits = outputs[logits_name].value        # [B, W, V]
            last = jnp.take_along_axis(
                logits, (jnp.clip(lens, 1, W) - 1)[:, None, None],
                axis=1)[:, 0, :]
            nxt = greedy_next(last)
            buf = buf.at[jnp.arange(B),
                         jnp.clip(lens, 0, W - 1)].set(nxt)
            lens = jnp.minimum(lens + 1, W)
            return (buf, lens), nxt

        _, toks = jax.lax.scan(body, (buf, lens), None, length=k)
        return toks.T                                  # [k, B] -> [B, k]

    return roll


def lm_beam_generate(
    executor: GraphExecutor,
    params: dict[str, Array],
    prompt_ids,                   # [B, P] int32 prompt tokens
    prompt_lengths=None,          # [B] valid prompt lengths (default: P)
    beam_size: int = 4,
    max_new: int = 32,
    *,
    input_name: Optional[str] = None,
    logits_name: Optional[str] = None,
    eos_id: int = -1,             # -1 = never finish early
):
    """Beam search for the LM family — the generation story the reference
    gives recurrent models (RecurrentGradientMachine::beamSearch,
    graph/generator.py here) extended to full-attention models, built on
    the KV-cache decode path: caches are prefilled once per source row,
    tiled to B*beam, and REORDERED by beam parent at every step (the cache
    gather is the TPU-native analog of the reference's per-Path state
    copying).

    Scoring is the plain sum of token log-probabilities (the reference's
    Path::logProb accumulation); a beam that emits `eos_id` is frozen —
    its only continuation is eos at logprob 0.  Returns
    (tokens [B, beam, P+max_new], lengths [B, beam], scores [B, beam]),
    beams sorted best-first per row.
    """
    model = executor.model
    input_name, logits_name = _resolve_io_names(model, input_name,
                                                logits_name)

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, P = prompt_ids.shape
    K = beam_size
    total = P + max_new
    if prompt_lengths is None:
        prompt_lengths = jnp.full((B,), P, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)

    def logprobs_of(raw):                              # [N, V] -> log p
        raw = raw.astype(jnp.float32)
        if _is_probs(model, logits_name):
            return jnp.log(jnp.maximum(raw, 1e-30))
        return jax.nn.log_softmax(raw, axis=-1)

    if max_new == 0:
        buf = jnp.zeros((B, K, total), jnp.int32).at[:, :, :P].set(
            prompt_ids[:, None, :])
        return (buf, jnp.repeat(prompt_lengths[:, None], K, 1),
                jnp.zeros((B, K), jnp.float32))

    # ---- prefill ONCE per source row, then tile caches to B*K ----
    state, last = _prefill(executor, params, input_name, logits_name,
                           prompt_ids, prompt_lengths, total)
    lp0 = logprobs_of(last)                            # [B, V]
    V = lp0.shape[-1]
    state = jax.tree.map(lambda x: jnp.repeat(x, K, axis=0), state)

    # first expansion: top-K tokens of the last prompt position seed the
    # beams (all beams share the prompt, so expanding every beam would
    # produce K duplicates of the same K tokens)
    scores, tok0 = jax.lax.top_k(lp0, K)               # [B, K] each
    buf = jnp.zeros((B, K, total), jnp.int32).at[:, :, :P].set(
        prompt_ids[:, None, :])
    lengths = jnp.repeat(prompt_lengths[:, None], K, axis=1)  # [B, K]
    bi, ki = jnp.arange(B)[:, None], jnp.arange(K)[None, :]
    buf = buf.at[bi, ki, lengths].set(tok0)
    lengths = lengths + 1
    done = (tok0 == eos_id)

    def step(carry, _):
        buf, lengths, scores, done, state = carry
        tok = buf.reshape(B * K, total)[
            jnp.arange(B * K),
            jnp.clip(lengths.reshape(B * K) - 1, 0, total - 1)]
        feed = {input_name: Argument(ids=tok[:, None],
                                     lengths=jnp.ones((B * K,), jnp.int32))}
        outputs, _, state = executor.forward(params, feed, state, TEST, None)
        lp = logprobs_of(outputs[logits_name].value[:, 0, :]) \
            .reshape(B, K, V)
        # frozen beams: eos continues at logprob 0, everything else -inf
        frozen = jnp.full((V,), -jnp.inf).at[jnp.maximum(eos_id, 0)].set(0.0)
        lp = jnp.where(done[:, :, None], frozen[None, None, :], lp)
        cand = scores[:, :, None] + lp                 # [B, K, V]
        scores, flat = jax.lax.top_k(cand.reshape(B, K * V), K)
        parent, tok_new = flat // V, (flat % V).astype(jnp.int32)  # [B, K]

        # reorder beams by parent: token buffers, lengths, done, KV caches
        buf = jnp.take_along_axis(buf, parent[:, :, None], axis=1)
        lengths = jnp.take_along_axis(lengths, parent, axis=1)
        done = jnp.take_along_axis(done, parent, axis=1)

        def reorder(x):                                # [B*K, ...] leaves
            xk = x.reshape(B, K, *x.shape[1:])
            idx = parent.reshape(B, K, *([1] * (x.ndim - 1)))
            return jnp.take_along_axis(xk, idx, axis=1) \
                .reshape(B * K, *x.shape[1:])

        state = jax.tree.map(reorder, state)

        write = jnp.where(done, buf[bi, ki, jnp.clip(lengths, 0, total - 1)],
                          tok_new)
        buf = buf.at[bi, ki, jnp.clip(lengths, 0, total - 1)].set(write)
        lengths = jnp.where(done, lengths, jnp.minimum(lengths + 1, total))
        done = jnp.logical_or(done, tok_new == eos_id)
        return (buf, lengths, scores, done, state), None

    (buf, lengths, scores, _, _), _ = jax.lax.scan(
        step, (buf, lengths, scores, done, state), None, length=max_new - 1)
    # top_k keeps each row's beams sorted best-first already
    return buf, lengths, scores
