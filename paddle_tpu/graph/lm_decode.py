"""Autoregressive decoding for sequence-in/logits-out models (the
transformer LM family).

The reference's generation story is beam search over recurrent groups
(RecurrentGradientMachine; graph/generator.py here).  Full-sequence
attention models have no recurrent group to unroll, so this provides the
matching TPU-native decode loop: ONE compiled `lax.scan` over a
fixed-size token buffer — each step runs the full forward on the padded
prefix (masked by the running length), reads the next-token logits at the
last valid position, and samples greedy / temperature / top-k.

Re-design note: a per-layer KV cache would make each step O(T) instead of
O(T^2); at the classic benchmark scales the whole-prefix re-forward is
one fused program XLA pipelines well, and it needs zero layer-level
support — the cacheized variant is a later optimization, not a
correctness feature.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import TEST
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


def lm_generate(
    executor: GraphExecutor,
    params: dict[str, Array],
    prompt_ids,                   # [B, P] int32 prompt tokens
    prompt_lengths=None,          # [B] valid prompt lengths (default: P)
    max_new: int = 32,
    *,
    input_name: Optional[str] = None,
    logits_name: Optional[str] = None,
    temperature: float = 0.0,     # 0 = greedy
    top_k: int = 0,               # 0 = full distribution
    eos_id: int = -1,             # -1 = never stop early
    rng: Optional[Array] = None,
):
    """Returns (tokens [B, P+max_new], lengths [B]) — the prompt plus up to
    max_new sampled tokens per row (rows stop growing at eos_id).

    The model is any config whose `input_name` data layer takes an id
    sequence and whose `logits_name` layer emits [B, T, vocab]
    (next-token distribution at each position) — the transformer LM
    shape.  Defaults: the first id-sequence input layer and the last
    non-cost layer.
    """
    model = executor.model
    if input_name is None:
        input_name = model.input_layer_names[0]
    if logits_name is None:
        from paddle_tpu.graph.registry import (cost_layer_types,
                                               validation_layer_types)
        skip = cost_layer_types | validation_layer_types | {"data"}
        non_cost = [l.name for l in model.layers if l.type not in skip]
        logits_name = non_cost[-1]

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, P = prompt_ids.shape
    total = P + max_new
    if prompt_lengths is None:
        prompt_lengths = jnp.full((B,), P, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    buf0 = jnp.zeros((B, total), jnp.int32).at[:, :P].set(prompt_ids)

    def step(carry, key):
        buf, lengths, done = carry
        feed = {input_name: Argument(ids=buf, lengths=lengths)}
        outputs, _, _ = executor.forward(params, feed, None, TEST, None)
        logits = outputs[logits_name].value          # [B, total, V]
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
        last = jnp.log(jnp.maximum(last.astype(jnp.float32), 1e-30)) \
            if _is_probs(model, logits_name) else last.astype(jnp.float32)
        if temperature <= 0.0:
            nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
        else:
            scaled = last / temperature
            if top_k > 0:
                # exact k-best support via top_k (ref pattern:
                # graph/generator.py beam candidate selection): scatter the
                # k values back to -inf elsewhere so ties at the kth value
                # can never widen the candidate set
                vals, idxs = jax.lax.top_k(scaled, top_k)
                scaled = jnp.full_like(scaled, -jnp.inf).at[
                    jnp.arange(scaled.shape[0])[:, None], idxs].set(vals)
            nxt = jax.random.categorical(key, scaled).astype(jnp.int32)
        # frozen rows keep their buffer and length
        write_pos = jnp.clip(lengths, 0, total - 1)
        new_buf = buf.at[jnp.arange(B), write_pos].set(
            jnp.where(done, buf[jnp.arange(B), write_pos], nxt))
        new_len = jnp.where(done, lengths, jnp.minimum(lengths + 1, total))
        new_done = jnp.logical_or(done, jnp.logical_or(
            nxt == eos_id, new_len >= total))
        return (new_buf, new_len, new_done), None

    keys = jax.random.split(rng, max_new)
    (buf, lengths, _), _ = jax.lax.scan(
        step, (buf0, prompt_lengths, jnp.zeros((B,), bool)), keys)
    return buf, lengths


def _is_probs(model, logits_name: str) -> bool:
    """Whether the logits layer emits probabilities (softmax activation) —
    sampled through log; raw-activation layers sample directly."""
    for l in model.layers:
        if l.name == logits_name:
            return l.active_type in ("softmax", "sequence_softmax")
    return False
