from paddle_tpu.graph.builder import GraphExecutor  # noqa: F401
from paddle_tpu.graph.registry import layer_registry, register_layer  # noqa: F401

# importing the implementation modules populates the registry
from paddle_tpu.graph import layers_core  # noqa: F401
from paddle_tpu.graph import layers_cost  # noqa: F401
from paddle_tpu.graph import layers_seq  # noqa: F401
from paddle_tpu.graph import layers_conv  # noqa: F401
from paddle_tpu.graph import layers_misc  # noqa: F401
from paddle_tpu.graph import layers_attn  # noqa: F401
from paddle_tpu.graph import layers_moe  # noqa: F401
