"""Elementwise / combination layer zoo.

Covers the reference's small-but-numerous combination layers (ref:
paddle/gserver/layers/{ScalingLayer,SlopeInterceptLayer,InterpolationLayer,
PowerLayer,ConvexCombinationLayer,CosSimLayer,CosSimVecMatLayer,
OuterProdLayer,TensorLayer,MultiplexLayer,TransLayer,ResizeLayer,
FeatureMapExpandLayer,ParameterReluLayer,PrintLayer,SelectiveFullyConnectedLayer}.cpp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.common import finish_layer
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.parameter.argument import Argument

Array = jax.Array


@register_layer("scaling")
def scaling_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Row-wise scale: out[i] = w[i] * x[i]; input0 = weights [B,1], input1 = x
    (ref: ScalingLayer.cpp)."""
    w, x = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    return finish_layer(ctx, cfg, x.value * w.value, like=x)


@register_layer("slope_intercept")
def slope_intercept_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """out = slope * x + intercept (ref: SlopeInterceptLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    slope = cfg.attrs.get("slope", 1.0)
    intercept = cfg.attrs.get("intercept", 0.0)
    return finish_layer(ctx, cfg, slope * x.value + intercept, like=x)


@register_layer("interpolation")
def interpolation_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """out = w*x1 + (1-w)*x2, w per-row [B,1] (ref: InterpolationLayer.cpp)."""
    w, a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1), ctx.get_input(cfg, 2)
    out = w.value * a.value + (1.0 - w.value) * b.value
    return finish_layer(ctx, cfg, out, like=a)


@register_layer("power")
def power_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """out = x ** w, w per-row [B,1] (ref: PowerLayer.cpp)."""
    w, x = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    return finish_layer(ctx, cfg, jnp.power(x.value, w.value), like=x)


@register_layer("convex_comb", "linear_comb")
def linear_comb_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """out = weights-row-matrix @ x-matrix per sample: input0 [B, M] weights,
    input1 [B, M*D] values -> [B, D] (ref: ConvexCombinationLayer.cpp)."""
    w, x = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    B, M = w.value.shape
    D = cfg.size
    xv = x.value.reshape(B, M, D)
    out = jnp.einsum("bm,bmd->bd", w.value, xv)
    return finish_layer(ctx, cfg, out)


@register_layer("cos")
def cos_sim_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Cosine similarity * scale (ref: CosSimLayer.cpp, hl_cossim)."""
    a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    scale = cfg.attrs.get("cos_scale", 1.0)
    eps = 1e-8
    num = jnp.sum(a.value * b.value, axis=-1)
    den = jnp.sqrt(jnp.sum(jnp.square(a.value), axis=-1) *
                   jnp.sum(jnp.square(b.value), axis=-1))
    out = scale * num / jnp.maximum(den, eps)
    return finish_layer(ctx, cfg, out[..., None], like=a)


@register_layer("cos_vm")
def cos_sim_vecmat_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Cosine of a vector against each row of a per-sample matrix:
    input0 [B, D], input1 [B, M*D] -> [B, M] (ref: CosSimVecMatLayer.cpp)."""
    v, m = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    scale = cfg.attrs.get("cos_scale", 1.0)
    B, D = v.value.shape
    M = cfg.size
    mv = m.value.reshape(B, M, D)
    eps = 1e-8
    num = jnp.einsum("bmd,bd->bm", mv, v.value)
    den = jnp.sqrt(jnp.sum(jnp.square(mv), axis=-1) *
                   jnp.sum(jnp.square(v.value), axis=-1, keepdims=True))
    out = scale * num / jnp.maximum(den, eps)
    return finish_layer(ctx, cfg, out)


@register_layer("out_prod")
def outer_prod_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Flattened outer product of two vectors (ref: OuterProdLayer.cpp)."""
    a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    out = jnp.einsum("bi,bj->bij", a.value, b.value)
    return finish_layer(ctx, cfg, out.reshape(out.shape[0], -1))


@register_layer("tensor")
def tensor_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Bilinear tensor product: out_k = x1 W_k x2^T
    (ref: TensorLayer.cpp; parameter [D1, K*D2])."""
    a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    w = ctx.param_of(cfg, 0)
    K = cfg.size
    D1 = a.value.shape[-1]
    D2 = b.value.shape[-1]
    w3 = w.reshape(D1, K, D2)
    out = jnp.einsum("bi,ikj,bj->bk", a.value, w3, b.value)
    bb = ctx.bias_of(cfg)
    if bb is not None:
        out = out + bb
    return finish_layer(ctx, cfg, out)


@register_layer("multiplex")
def multiplex_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Row-wise select among inputs 1..N by index input 0
    (ref: MultiplexLayer.cpp)."""
    sel = ctx.get_input(cfg, 0)
    options = [ctx.get_input(cfg, i).value for i in range(1, len(cfg.inputs))]
    stacked = jnp.stack(options, axis=1)          # [B, N, D]
    idx = sel.ids
    out = jnp.take_along_axis(stacked, idx[:, None, None].astype(jnp.int32)
                              .repeat(stacked.shape[-1], -1), axis=1)[:, 0]
    return finish_layer(ctx, cfg, out)


@register_layer("trans")
def trans_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Transpose the (batch x dim) matrix (ref: TransLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    return finish_layer(ctx, cfg, x.value.T)


@register_layer("resize")
def resize_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Reinterpret the batch as rows of `size` (ref: ResizeLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    return finish_layer(ctx, cfg, x.value.reshape(-1, cfg.size))


@register_layer("featmap_expand")
def featmap_expand_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Tile features num_filters times (ref: FeatureMapExpandLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    out = jnp.repeat(x.value[:, None, :], cfg.num_filters, axis=1)
    return finish_layer(ctx, cfg, out.reshape(x.value.shape[0], -1), like=x)


@register_layer("prelu")
def parameter_relu_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Parametric ReLU with partition sharing (ref: ParameterReluLayer.cpp)."""
    x = ctx.get_input(cfg, 0)
    w = ctx.param_of(cfg, 0)
    D = x.value.shape[-1]
    # each slope is shared across partial_sum consecutive dims (w.size = D/partial_sum)
    slopes = jnp.repeat(w.reshape(-1), D // w.size)
    out = jnp.where(x.value > 0, x.value, x.value * slopes)
    return finish_layer(ctx, cfg, out, like=x)


@register_layer("conv_shift")
def conv_shift_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Circular correlation of each row of a with kernel b (odd length M):
    out[i] = sum_j b[j] * a[(i + j - M//2) mod D] (ref: ConvShiftLayer.cpp,
    used for NTM-style shift attention)."""
    a, b = ctx.get_input(cfg, 0), ctx.get_input(cfg, 1)
    D = a.value.shape[-1]
    M = b.value.shape[-1]
    half = M // 2
    out = jnp.zeros_like(a.value)
    for j in range(M):
        out = out + b.value[:, j:j + 1] * jnp.roll(a.value, half - j, axis=-1)
    return finish_layer(ctx, cfg, out, like=a)


@register_layer("print")
def print_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Debug-print inputs at trace time (ref: PrintLayer.cpp); identity."""
    x = ctx.get_input(cfg, 0)
    jax.debug.print("print layer {}: {}", cfg.name, x.data)
    return x


@register_layer("selective_fc")
def selective_fc_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Selective FC (ref: SelectiveFullyConnectedLayer.cpp): full output here —
    the selection mask is an inference-time sparsity optimization that XLA's
    dense matmul makes unnecessary.  With a selection input, non-selected
    logits are pushed to a large negative value BEFORE the (softmax)
    activation so unselected classes get ~zero probability — the reference
    computes softmax over only the selected columns."""
    inputs = ctx.get_inputs(cfg)
    has_sel = cfg.attrs.get("has_selected_colums", False)
    feat_inputs = inputs[:-1] if has_sel else inputs
    acc = None
    for i, arg in enumerate(feat_inputs):
        w = ctx.param_of(cfg, i)
        y = jnp.matmul(arg.value, w.T if w.shape[0] == cfg.size else w)
        acc = y if acc is None else acc + y
    b = ctx.bias_of(cfg)
    if b is not None:
        acc = acc + b
    if has_sel:
        sel = inputs[-1]
        if cfg.active_type == "softmax":
            acc = jnp.where(sel.value > 0, acc, -1e9)
        else:
            acc = acc * sel.value
    return finish_layer(ctx, cfg, acc, like=feat_inputs[0])


@register_layer("layer_norm")
def layer_norm_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """Last-dim layer normalization with learned scale/bias — beyond the
    reference's zoo (its only norms are cross-map response norms,
    NormLayer.cpp); the transformer-era block needs it.  Statistics in
    fp32 under mixed precision."""
    x = ctx.get_input(cfg, 0)
    from paddle_tpu.utils.dtypes import promote_compute
    v32 = promote_compute(x.value)
    mean = jnp.mean(v32, axis=-1, keepdims=True)
    var = jnp.var(v32, axis=-1, keepdims=True)
    normed = (v32 - mean) * jax.lax.rsqrt(var + 1e-6)
    scale = ctx.param_of(cfg, 0)
    if scale is not None:
        normed = normed * promote_compute(scale).reshape(-1)
    b = ctx.bias_of(cfg)
    if b is not None:
        normed = normed + promote_compute(b).reshape(-1)
    return finish_layer(ctx, cfg, normed.astype(x.value.dtype), like=x)
