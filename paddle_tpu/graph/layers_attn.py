"""Multi-head scaled-dot-product attention layer.

NEW capability beyond the reference (whose attention story is the additive
`simple_attention` composite of fc/expand/sequence_softmax/scaling layers,
ref: python/paddle/trainer_config_helpers/networks.py:1257) — first-class
long-context attention with three execution paths picked automatically:

  * dense   — one fused einsum-softmax-einsum (short sequences),
  * flash   — fused pallas online-softmax kernel, score tiles resident in
    VMEM (long sequences on TPU; ops/pallas_attention.py),
  * blockwise — lax.scan online-softmax over key blocks, O(T) memory (the
    portable long-sequence fallback; ops/attention.py:blockwise_attention),
  * ring    — context parallelism when the executor's mesh has a `seq` axis
    of size > 1: each device holds a sequence shard and K/V rotate around
    the ICI ring (parallel/context.py:ring_attention_sharded),
  * ulysses — the all-to-all context-parallel alternative (explicit
    attn_impl='ulysses'): tokens reshard to heads, local full-sequence
    attention, reshard back (parallel/context.py:ulysses_attention_sharded)
    — prefer when heads >= the seq-axis size.
"""

from __future__ import annotations

import jax

from paddle_tpu.config.schema import LayerConfig
from paddle_tpu.graph.common import finish_layer
from paddle_tpu.graph.context import ForwardContext
from paddle_tpu.graph.registry import register_layer
from paddle_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
    multi_head_attention,
)
from paddle_tpu.parameter.argument import Argument

# beyond this many key positions, prefer the O(T)-memory flash/blockwise
# path.  Measured on v5e (MEASURE/attn_bench, round 4, B4 H8 D64 bf16
# fwd+bwd): dense wins below 2k keys (0.033 vs 0.036 ms at 1024),
# blockwise ties at 2048 (0.028 vs 0.030) and dense OOMs by 16k — so the
# crossover sits at 2048; override per layer with block_k_min
_BLOCKWISE_MIN_KEYS = 2048


def _flash_blocks(cfg: LayerConfig) -> dict:
    """Flash-kernel block sizes: per-layer attrs win; else the env-tuned
    defaults (PADDLE_TPU_FLASH_BLOCK_Q/K — written from
    tools/tune_flash.py's on-device sweep); else the kernel's 128x128.
    Used by BOTH the training path and the cached-decode prefill, so a
    tuned configuration applies everywhere flash runs."""
    import os
    return {
        "block_q": int(cfg.attrs.get(
            "block_q", os.environ.get("PADDLE_TPU_FLASH_BLOCK_Q", 128))),
        "block_k": int(cfg.attrs.get(
            "block_k", os.environ.get("PADDLE_TPU_FLASH_BLOCK_K", 128))),
    }


@register_layer("multi_head_attention")
def multi_head_attention_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """inputs: [query, key, value, (query again carrying the out-proj param)];
    attrs: num_heads, causal, block_k, block_k_min, attn_impl,
    num_kv_heads (grouped-query), window (sliding-window),
    use_rope/rope_theta (rotary position embeddings)."""
    q_arg, k_arg, v_arg = (ctx.get_input(cfg, i) for i in range(3))
    w_q, w_k, w_v, w_o = (ctx.param_of(cfg, i) for i in range(4))
    num_heads = int(cfg.attrs["num_heads"])
    causal = bool(cfg.attrs.get("causal", False))

    cache = ctx.state_in.get(cfg.name)
    if isinstance(cache, dict) and "k_pages" in cache:
        # continuous-batching decode against the serving engine's paged KV
        # pool (serving/paged_kv.py): context read through the per-slot
        # page table — the fixed-signature step the engine compiles once
        # and reuses for the whole workload.  A cache carrying `row_slot`
        # is the MIXED prefill/decode step: query tokens packed into one
        # ragged row dimension (decode rows + prompt chunks), each row
        # addressing its own table row at its own position
        assert causal, f"layer {cfg.name!r}: paged decode requires causal"
        if "row_slot" in cache:
            return _paged_ragged_step(ctx, cfg, q_arg, w_q, w_k, w_v, w_o,
                                      num_heads, cache)
        return _paged_step(ctx, cfg, q_arg, w_q, w_k, w_v, w_o, num_heads,
                           cache)
    if isinstance(cache, dict) and "k" in cache:
        # incremental decode against a KV cache (lm_decode use_cache path):
        # the input carries only NEW tokens; per-row positions come from the
        # cache, so caches ride the same state threading as BN moving stats
        assert causal, f"layer {cfg.name!r}: KV-cache decode requires causal"
        return _cached_step(ctx, cfg, q_arg, w_q, w_k, w_v, w_o, num_heads,
                            cache)

    q_valid = q_arg.mask()
    k_valid = k_arg.mask()

    import functools

    mesh = ctx.mesh
    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.parallel.context import (ring_attn_fn, seq_axis_size,
                                             ulysses_attn_fn)
    impl = str(cfg.attrs.get("attn_impl", "auto"))
    if impl not in ("auto", "ring", "ulysses", "flash", "blockwise",
                    "dense"):
        raise ValueError(
            f"layer {cfg.name!r}: unknown attn_impl {impl!r} "
            f"(expected auto/ring/ulysses/flash/blockwise/dense)")
    if impl == "auto":
        if mesh is not None and seq_axis_size(mesh) > 1:
            impl = "ring"
        elif k_arg.max_len >= int(cfg.attrs.get("block_k_min",
                                                _BLOCKWISE_MIN_KEYS)):
            impl = "flash" if pallas_attention.supported() else "blockwise"
        else:
            impl = "dense"
    if impl in ("ring", "ulysses"):
        if mesh is None or seq_axis_size(mesh) < 2:
            raise ValueError(
                f"layer {cfg.name!r}: attn_impl={impl!r} needs the executor "
                f"mesh to have a `seq` axis of size >= 2 (got "
                f"{'no mesh' if mesh is None else dict(zip(mesh.axis_names, mesh.devices.shape))})")
        attn_fn = (ulysses_attn_fn(
                       mesh,
                       block_k=(int(cfg.attrs["block_k"])
                                if "block_k" in cfg.attrs else None),
                       block_k_min=(int(cfg.attrs["block_k_min"])
                                    if "block_k_min" in cfg.attrs else None))
                   if impl == "ulysses" else ring_attn_fn(mesh))
    elif impl == "flash":
        if not pallas_attention.supported():
            raise ValueError(
                f"layer {cfg.name!r}: attn_impl='flash' needs a TPU backend "
                f"(or PADDLE_TPU_PALLAS_INTERPRET=1 to opt into the slow "
                f"interpret mode); current backend is "
                f"{jax.default_backend()!r}")
        attn_fn = functools.partial(pallas_attention.flash_attention,
                                    **_flash_blocks(cfg))
    elif impl == "blockwise":
        attn_fn = functools.partial(
            blockwise_attention, block_k=int(cfg.attrs.get("block_k", 512)))
    else:
        attn_fn = dot_product_attention

    out = multi_head_attention(
        q_arg.value, k_arg.value, v_arg.value,
        w_q, w_k, w_v, w_o, num_heads,
        q_valid=q_valid, k_valid=k_valid, causal=causal,
        bias_o=ctx.bias_of(cfg), attn_fn=attn_fn,
        num_kv_heads=(int(cfg.attrs["num_kv_heads"])
                      if "num_kv_heads" in cfg.attrs else None),
        window=(int(cfg.attrs["window"])
                if "window" in cfg.attrs else None),
        use_rope=bool(cfg.attrs.get("use_rope", False)),
        rope_theta=float(cfg.attrs.get("rope_theta", 10000.0)))
    return finish_layer(ctx, cfg, out, like=q_arg)


def _cached_step(ctx: ForwardContext, cfg: LayerConfig, x_arg: Argument,
                 w_q, w_k, w_v, w_o, num_heads: int,
                 cache: dict) -> Argument:
    """One incremental self-attention call: project the new tokens, fold
    them into this layer's KV cache, attend causally on global positions.
    Emits the updated cache through ctx.state_out."""
    import functools

    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.ops.attention import (blockwise_attention,
                                          cached_attention_step,
                                          dot_product_attention, rope)

    x = x_arg.value                                   # [B, Tn, model_dim]
    B, Tn, _ = x.shape
    model_dim = w_q.shape[1]
    Dh = model_dim // num_heads
    h_kv = int(cfg.attrs.get("num_kv_heads", 0) or num_heads)
    pos = cache["pos"]
    q = (x @ w_q).reshape(B, Tn, num_heads, Dh)
    k = (x @ w_k).reshape(B, Tn, h_kv, Dh)
    v = (x @ w_v).reshape(B, Tn, h_kv, Dh)
    if bool(cfg.attrs.get("use_rope", False)):
        qpos = pos[:, None] + jnp.arange(Tn)[None, :]
        theta = float(cfg.attrs.get("rope_theta", 10000.0))
        q, k = rope(q, qpos, theta), rope(k, qpos, theta)
    n_new = (x_arg.lengths.astype(jnp.int32) if x_arg.lengths is not None
             else jnp.full((B,), Tn, jnp.int32))
    window = (int(cfg.attrs["window"]) if "window" in cfg.attrs else None)
    if Tn > 1 and "cont" not in cache:
        # prefill contract: a multi-token cached call starts from an EMPTY
        # cache (lm_decode feeds the whole prompt once), so attention over
        # the cache degenerates to plain causal self-attention — run it
        # through the impl-selected kernel (flash for long prompts) rather
        # than cached_attention_step, whose O(Tn*Tmax) dense scores and
        # one-hot scatter would defeat the cache at exactly the long
        # contexts it exists for; k/v land in the cache as a static slice.
        # A state dict carrying the static "cont" marker opts OUT of this
        # fast path: the cache is pre-seeded with a committed prefix (the
        # serving engine's prefix-hit suffix prefill) and the new tokens
        # continue FROM `pos` — cached_attention_step below already handles
        # multi-token writes at a per-row dynamic offset with global
        # causal positions, so the continuation needs no new math
        valid = (jnp.arange(Tn)[None, :] < n_new[:, None])
        # honor an explicit attn_impl like the regular forward does (a
        # config pinned to dense — e.g. to sidestep a pallas issue or for
        # a dense-vs-flash bench — must not silently get flash prefill);
        # 'ring'/'ulysses' have no cached-decode analog, so they fall
        # through to the local auto-selection
        impl = str(cfg.attrs.get("attn_impl", "auto"))
        if impl not in ("auto", "ring", "ulysses", "flash", "blockwise",
                        "dense"):
            raise ValueError(
                f"layer {cfg.name!r}: unknown attn_impl {impl!r} "
                f"(expected auto/ring/ulysses/flash/blockwise/dense)")
        long_prompt = Tn >= int(cfg.attrs.get("block_k_min",
                                              _BLOCKWISE_MIN_KEYS))
        if impl == "flash":
            if not pallas_attention.supported():
                raise ValueError(
                    f"layer {cfg.name!r}: attn_impl=flash needs a TPU "
                    f"backend (or PADDLE_TPU_PALLAS_INTERPRET=1 for "
                    f"interpret-mode tests)")
            attn = functools.partial(pallas_attention.flash_attention,
                                     **_flash_blocks(cfg))
        elif impl == "blockwise":
            attn = blockwise_attention
        elif impl == "dense":
            attn = dot_product_attention
        elif long_prompt and pallas_attention.supported():
            attn = functools.partial(pallas_attention.flash_attention,
                                     **_flash_blocks(cfg))
        elif long_prompt:
            attn = blockwise_attention
        else:
            attn = dot_product_attention
        out = attn(q, k, v, q_valid=valid, k_valid=valid, causal=True,
                   **({} if window is None else {"window": window}))
        ck = cache["k"].at[:, :Tn].set(k.astype(cache["k"].dtype))
        cv = cache["v"].at[:, :Tn].set(v.astype(cache["v"].dtype))
        newpos = pos + n_new
    else:
        out, ck, cv, newpos = cached_attention_step(
            q, k, v, cache["k"], cache["v"], pos, n_new, window=window)
    ctx.state_out[cfg.name] = {"k": ck, "v": cv, "pos": newpos}
    o = out.reshape(B, Tn, model_dim) @ w_o
    bias = ctx.bias_of(cfg)
    if bias is not None:
        o = o + bias
    return finish_layer(ctx, cfg, o, like=x_arg)


def _paged_step(ctx: ForwardContext, cfg: LayerConfig, x_arg: Argument,
                w_q, w_k, w_v, w_o, num_heads: int,
                cache: dict) -> Argument:
    """One serving decode micro-step: project each slot's single new token,
    scatter its k/v into the slot's current page of the shared pool, attend
    causally over the slot's paged context (ops/attention.py:
    paged_attention_step — page-table gather, or the Pallas ragged-paged
    kernel when supported).  Emits the updated pool through ctx.state_out;
    the page table itself is host-managed and passes through untouched."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import paged_attention_step, rope

    x = x_arg.value                                   # [S, 1, model_dim]
    S, Tn, _ = x.shape
    assert Tn == 1, (f"layer {cfg.name!r}: paged decode feeds exactly one "
                     f"new token per slot (got {Tn}); prompts prefill "
                     f"through the dense per-request cache")
    model_dim = w_q.shape[1]
    Dh = model_dim // num_heads
    h_kv = int(cfg.attrs.get("num_kv_heads", 0) or num_heads)
    pos = cache["pos"]
    q = (x @ w_q).reshape(S, 1, num_heads, Dh)
    k = (x @ w_k).reshape(S, 1, h_kv, Dh)
    v = (x @ w_v).reshape(S, 1, h_kv, Dh)
    if bool(cfg.attrs.get("use_rope", False)):
        theta = float(cfg.attrs.get("rope_theta", 10000.0))
        qpos = pos[:, None]
        q, k = rope(q, qpos, theta), rope(k, qpos, theta)
    window = (int(cfg.attrs["window"]) if "window" in cfg.attrs else None)
    # a mesh with a `model` axis > 1 = tensor-parallel serving: the op
    # runs the write+read core under shard_map over the head shards
    out, ck, cv = paged_attention_step(
        q, k, v, cache["k_pages"], cache["v_pages"], cache["page_table"],
        pos, window=window,
        use_kernel=(False if str(cfg.attrs.get("attn_impl", "auto"))
                    in ("dense", "blockwise") else None),
        mesh=ctx.mesh)
    ctx.state_out[cfg.name] = {"k_pages": ck, "v_pages": cv,
                               "page_table": cache["page_table"],
                               "pos": pos + 1}
    o = out.reshape(S, 1, model_dim) @ w_o
    bias = ctx.bias_of(cfg)
    if bias is not None:
        o = o + bias
    return finish_layer(ctx, cfg, o, like=x_arg)


def _paged_ragged_step(ctx: ForwardContext, cfg: LayerConfig, x_arg: Argument,
                       w_q, w_k, w_v, w_o, num_heads: int,
                       cache: dict) -> Argument:
    """One MIXED prefill/decode step against the paged pool: the input is
    a packed ragged token list [1, T, model_dim] where row r is one token
    of page-table row `cache["row_slot"][r]` at global position
    `cache["row_pos"][r]` — live decode rows and in-flight prompt chunks
    in one dispatch (ops/attention.py:ragged_paged_attention_step; the
    Pallas row-indirected kernel when supported).  Emits the updated pool
    through ctx.state_out; table and row maps are host-managed and pass
    through untouched."""
    from paddle_tpu.ops.attention import ragged_paged_attention_step, rope

    x = x_arg.value                                   # [1, T, model_dim]
    B, T, _ = x.shape
    assert B == 1, (f"layer {cfg.name!r}: the mixed paged step packs all "
                    f"query rows into one ragged batch row (got B={B})")
    model_dim = w_q.shape[1]
    Dh = model_dim // num_heads
    h_kv = int(cfg.attrs.get("num_kv_heads", 0) or num_heads)
    row_pos = cache["row_pos"]                        # [T] global positions
    q = (x @ w_q).reshape(1, T, num_heads, Dh)
    k = (x @ w_k).reshape(1, T, h_kv, Dh)
    v = (x @ w_v).reshape(1, T, h_kv, Dh)
    if bool(cfg.attrs.get("use_rope", False)):
        theta = float(cfg.attrs.get("rope_theta", 10000.0))
        q, k = rope(q, row_pos, theta), rope(k, row_pos, theta)
    window = (int(cfg.attrs["window"]) if "window" in cfg.attrs else None)
    # mesh `model` axis > 1 = tensor-parallel mixed step (shard_map core)
    out, ck, cv = ragged_paged_attention_step(
        q[0], k[0], v[0], cache["k_pages"], cache["v_pages"],
        cache["page_table"], cache["row_slot"], row_pos, window=window,
        use_kernel=(False if str(cfg.attrs.get("attn_impl", "auto"))
                    in ("dense", "blockwise") else None),
        mesh=ctx.mesh)
    ctx.state_out[cfg.name] = {"k_pages": ck, "v_pages": cv,
                               "page_table": cache["page_table"],
                               "row_slot": cache["row_slot"],
                               "row_pos": row_pos}
    o = out.reshape(1, T, model_dim) @ w_o
    bias = ctx.bias_of(cfg)
    if bias is not None:
        o = o + bias
    return finish_layer(ctx, cfg, o, like=x_arg)


@register_layer("additive_attention_step")
def additive_attention_step_layer(ctx: ForwardContext, cfg: LayerConfig) -> Argument:
    """One fused Bahdanau attention step inside a decoder scan (the
    reference's simple_attention composite collapsed into a single layer —
    ref: networks.py:1257 fc/expand/addto/sequence-softmax/scaling/pool).

    inputs: [decoder_state [B,Ds] (carries W [Ds,D]),
             encoded_proj [B,T,D] static link (carries v [D,1]),
             encoded_sequence [B,T,Dv] static link];
    output: context [B, Dv].
    """
    dec = ctx.get_input(cfg, 0)
    proj = ctx.get_input(cfg, 1)
    seq = ctx.get_input(cfg, 2)
    w = ctx.param_of(cfg, 0)
    v = ctx.param_of(cfg, 1)
    lengths = proj.lengths if proj.lengths is not None else seq.lengths

    from paddle_tpu.ops.attention import additive_attention_step
    from paddle_tpu.ops import pallas_additive
    if pallas_additive.supported() and \
            str(cfg.attrs.get("attn_impl", "auto")) != "dense":
        # lengths flow straight into the kernel: the mask here is always a
        # length prefix, so the kernel's runtime contiguity guard (which
        # costs an O(B*T) check + lax.cond inside the decoder scan) is
        # statically unnecessary
        out = pallas_additive.additive_attention_step(
            dec.value, w, v.reshape(-1), proj.value, seq.value,
            lengths=lengths)
    else:
        mask = None
        if lengths is not None:
            mask = (proj.mask() if proj.lengths is not None else seq.mask())
        out = additive_attention_step(dec.value, w, v.reshape(-1),
                                      proj.value, seq.value, mask)
    return finish_layer(ctx, cfg, out, like=dec)
