"""ForwardContext — per-trace state threaded through layer functions.

Carries what the reference spread across Layer members and globals: the mode
(train/test/generation — ref: PassType in paddle/utils/GlobalConstants.h), the
parameter map (ref: NeuralNetwork::parameterMap_), already-computed layer
outputs (ref: Layer::inputLayers_ pointers), per-layer RNG for dropout and
sampling, and mutable layer state such as batch-norm moving stats (ref:
use_global_stats / movingMean_ in BatchNormalizationLayer).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax

from paddle_tpu.config.schema import LayerConfig, ModelConfig
from paddle_tpu.parameter.argument import Argument

TRAIN = "train"
TEST = "test"
GEN = "gen"


@dataclass
class ForwardContext:
    model: ModelConfig
    params: dict[str, jax.Array]
    mode: str = TRAIN
    rng: Optional[jax.Array] = None
    # layer name -> computed output
    outputs: dict[str, Argument] = field(default_factory=dict)
    # layer name -> incoming state (e.g. BN moving stats), and collected updates
    state_in: dict[str, Any] = field(default_factory=dict)
    state_out: dict[str, Any] = field(default_factory=dict)
    # accumulated per-sample costs from cost layers: name -> [B]
    costs: dict[str, jax.Array] = field(default_factory=dict)
    # device mesh for layers with parallel execution paths (ring attention)
    mesh: Optional[Any] = None
    _rng_counter: int = 0

    @property
    def is_training(self) -> bool:
        return self.mode == TRAIN

    def next_rng(self) -> jax.Array:
        assert self.rng is not None, "forward() needs an rng for stochastic layers"
        self._rng_counter += 1
        return jax.random.fold_in(self.rng, self._rng_counter)

    def get_input(self, cfg: LayerConfig, i: int) -> Argument:
        """Input i in the reference's flat row layout (NHWC image outputs are
        flattened lazily here — image layers use get_image_input instead, so
        tensors stay channels-last between image layers)."""
        return self.get_raw_input(cfg, i).flatten_image()

    def get_raw_input(self, cfg: LayerConfig, i: int) -> Argument:
        name = cfg.inputs[i].input_layer_name
        try:
            return self.outputs[name]
        except KeyError:
            raise KeyError(
                f"layer {cfg.name!r} input {name!r} not computed yet — config out of topo order?")

    def get_image_input(self, cfg: LayerConfig, i: int,
                        channels: int, height: int, width: int) -> Argument:
        """Input i as a [B, H, W, C] channels-last image Argument (the TPU's
        preferred conv layout; XLA keeps it resident without per-layer
        transposes).  Flat-row inputs are unpacked from the reference's
        C-major layout once at entry into the image pipeline."""
        arg = self.get_raw_input(cfg, i)
        if arg.nhwc:
            if arg.value.shape[1:] != (height, width, channels):
                # the consumer's config reinterprets the producer's geometry
                # (e.g. same element count, different C/H/W split) — the flat
                # C-major row layout is the common currency for that
                arg = arg.flatten_image()
            else:
                return arg
        B = arg.value.shape[0]
        v = arg.value.reshape(B, channels, height, width).transpose(0, 2, 3, 1)
        return arg.replace(value=v, nhwc=True)

    def get_inputs(self, cfg: LayerConfig) -> list[Argument]:
        return [self.get_input(cfg, i) for i in range(len(cfg.inputs))]

    def param_of(self, cfg: LayerConfig, i: int) -> Optional[jax.Array]:
        pname = cfg.inputs[i].input_parameter_name
        return self.params[pname] if pname else None

    def bias_of(self, cfg: LayerConfig) -> Optional[jax.Array]:
        return self.params[cfg.bias_parameter_name] if cfg.bias_parameter_name else None
